"""Router high availability: lease-fenced standby takeover over the
journal WAL.

The journal already makes every *replica* replaceable; this module
makes the ROUTER replaceable — the last single point of failure in the
cluster tier.  The design is the classic WAL + lease + fencing-token
triple (the same shape as HDFS NameNode HA or a Raft leader change,
scaled to this in-process harness):

* every journal mutation is write-ahead logged through a shared sink
  (``cluster/wal.py``) *before* it takes effect — in particular before
  a token reaches the client;
* a :class:`Lease` with monotonically increasing epochs names the one
  router allowed to dispatch.  The epoch rides every replica-facing
  call and every WAL append as a fencing token;
* on primary death (``cluster.router_kill`` fault, an uncontained
  router exception) or lease expiry (a stalled primary that missed its
  renewal), the :class:`RouterSupervisor` promotes a standby: acquire
  the next epoch, replay the WAL tail into a bit-identical journal,
  fence the fleet (replicas cancel work dispatched under older epochs
  and reject stale-epoch calls), re-adopt in-flight entries through
  the router's own ``_replay`` path, re-drive journaled-but-undispatched
  handoff packets, and resume pumping.

What the client sees: nothing.  Admissions are idempotent (rids),
delivered tokens are in the WAL so the heir never re-emits them
(emitted tokens fold into the resubmitted prompt — the preemption
trick), and the PR-16 policy fields replay so sampled/grammar streams
continue bitwise.  A zombie primary that keeps running can neither
dispatch (replicas raise ``StaleEpoch``), deliver (its token sinks
drop once the lease moved, and the WAL fences the append regardless),
nor corrupt the log (``fenced_writes`` counts its attempts).
"""

import time

from deepspeed_tpu.serving.cluster import journal as jn
from deepspeed_tpu.serving.cluster.journal import RequestJournal
from deepspeed_tpu.serving.cluster.replica import DEAD
from deepspeed_tpu.serving.cluster.router import ClusterRouter, _Packet
from deepspeed_tpu.serving.cluster.wal import MemoryWalSink
from deepspeed_tpu.serving.metrics import HaMetrics

__all__ = ["Lease", "RouterKilled", "RouterSupervisor"]


class RouterKilled(RuntimeError):
    """The primary router died mid-pump (chaos fault or uncontained
    router bug).  Raised only for callers running WITHOUT a
    RouterSupervisor; under one, it is the takeover trigger."""


class Lease:
    """Monotonic-epoch dispatch lease.

    ``acquire()`` mints the next epoch and names a new holder;
    ``renew()`` extends the current holder's term but FAILS once the
    lease expired or a newer epoch exists — a stalled primary that
    wakes up after its term cannot un-depose the heir.  The epoch never
    decreases: it is the fencing token everything downstream compares
    against.
    """

    def __init__(self, ttl_s=1.0, clock=time.monotonic):
        self.ttl_s = float(ttl_s)
        self._clock = clock
        self.current_epoch = 0
        self.holder = None
        self.expires_at = 0.0

    def acquire(self, holder):
        self.current_epoch += 1
        self.holder = holder
        self.expires_at = self._clock() + self.ttl_s
        return self.current_epoch

    def renew(self, epoch):
        if epoch != self.current_epoch:
            return False               # deposed: a newer epoch exists
        if self._clock() > self.expires_at:
            return False               # too late: the term lapsed
        self.expires_at = self._clock() + self.ttl_s
        return True

    def expired(self):
        return self._clock() > self.expires_at


class RouterSupervisor:
    """Primary + standby routers over one WAL; promotes on death.

    The supervisor owns what must SURVIVE a router: the WAL sink, the
    lease, the client ``on_token`` callbacks (rebound onto the heir's
    reconstructed entries), and rid assignment.  Clients submit and
    pump through the supervisor; ``entry(rid)`` is the live view of a
    request across any number of takeovers (the underlying entry
    object changes when a standby replays the WAL).
    """

    def __init__(self, replicas, *, wal=None, lease_ttl_s=30.0,
                 monitor=None, gauge_every=64, **router_kw):
        self.replicas = list(replicas)
        self.wal = wal if wal is not None else MemoryWalSink()
        self.lease = Lease(ttl_s=lease_ttl_s)
        self.monitor = monitor
        self.ha = HaMetrics(monitor)
        self.gauge_every = int(gauge_every)
        self._router_kw = dict(router_kw)
        self._router_kw.setdefault("monitor", monitor)
        self._sinks = {}           # rid -> client on_token (survives HA)
        self._next_rid = 0
        self.failovers = 0
        self.fenced_token_total = 0   # sink-level drops across routers
        self.takeover_reasons = []
        self._routers_minted = 0
        self.router = self._mint_router(RequestJournal(
            wal=self.wal, epoch=self.lease.acquire("router-0")))

    # --------------------------------------------------------- plumbing
    def _mint_router(self, journal):
        self._routers_minted += 1
        journal.attach_wal(self.wal, self.lease.current_epoch)
        r = ClusterRouter(self.replicas, journal=journal,
                          epoch=self.lease.current_epoch,
                          lease=self.lease, **self._router_kw)
        self.ha.record_gauges(max(1, r.step_idx),
                              self.lease.current_epoch,
                              self.wal.fenced_writes,
                              self.wal.records_appended)
        return r

    @property
    def journal(self):
        return self.router.journal

    @property
    def epoch(self):
        return self.router.epoch

    def entry(self, rid):
        """The CURRENT journal's view of a request — stable across
        takeovers (entry objects are rebuilt from the WAL)."""
        return self.router.journal.entries.get(rid)

    # ----------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None, deadline_s=None, rid=None, sampling=None,
               seed=None, grammar=None):
        if rid is None:
            rid = f"ha-{self._next_rid}"
            self._next_rid += 1
        if on_token is not None:
            self._sinks[rid] = on_token
        return self.router.submit(
            prompt, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, on_token=on_token,
            deadline_s=deadline_s, rid=rid, sampling=sampling,
            seed=seed, grammar=grammar)

    def cancel(self, rid):
        return self.router.cancel(rid)

    # ------------------------------------------------------------- pump
    def step(self):
        """One supervised pump.  A raise out of the primary's step (the
        ``cluster.router_kill`` chaos point, or any uncontained router
        bug) IS the router death; lease expiry catches the stalled-
        not-dead case.  Either way the standby takes over and the pump
        continues without losing the iteration."""
        try:
            live = self.router.step()
        except Exception as e:
            self._takeover(f"router died: {type(e).__name__}: {e}")
            return self.router.step()
        if self.lease.expired() or \
                self.lease.current_epoch != self.router.epoch:
            self._takeover("lease expired")
            return self.router.step()
        if self.gauge_every and \
                self.router.step_idx % self.gauge_every == 0:
            self.ha.record_gauges(self.router.step_idx, self.epoch,
                                  self.wal.fenced_writes,
                                  self.wal.records_appended)
        return live

    def run(self, max_steps=100000):
        """Pump until every journaled request is terminal; returns
        ``{rid: emitted}`` for the FINISHED ones (from the CURRENT
        journal — WAL replay carries pre-takeover history across)."""
        for _ in range(max_steps):
            if not self.step():
                break
            if not any(rep.state != DEAD and rep.has_work()
                       for rep in self.replicas) and \
                    not self.router._packets:
                time.sleep(0.002)
        return {e.rid: list(e.emitted)
                for e in self.router.journal.entries.values()
                if e.state == jn.FINISHED}

    # --------------------------------------------------------- takeover
    def _takeover(self, reason):
        old = self.router
        self.fenced_token_total += old.fenced_tokens
        self.failovers += 1
        self.takeover_reasons.append(reason)
        epoch = self.lease.acquire(f"router-{self._routers_minted}")
        # 1. rebuild the journal from the WAL tail (snapshot + records)
        snapshot, records = self.wal.replay_stream()
        journal = RequestJournal.replay(records, snapshot=snapshot)
        # 2. rebind the surviving client sinks onto the heir's entries
        for rid, entry in journal.entries.items():
            entry.on_token = self._sinks.get(rid)
        # 3. fence the fleet: stale-epoch work is cancelled at the
        # replicas, stale-epoch calls rejected from here on
        for rep in self.replicas:
            if hasattr(rep, "fence") and rep.state != DEAD:
                rep.fence(epoch)
        router = self._mint_router(journal)
        router.step_idx = old.step_idx     # chaos/trace continuity
        # 4. re-adopt in-flight entries through the standard replay
        # path (folds emitted tokens, honours cancel, finalizes
        # already-satisfied streams) and re-drive journaled handoff
        # packets that never dispatched
        stranded = []
        groups = {rep.group.name: rep.group
                  for rep in self.replicas if rep.group is not None}
        for entry in list(journal.live()):
            if entry.state == jn.ROUTED:
                stranded.append(entry.rid)
                router._replay(entry, dead_replica=entry.replica)
            elif entry.state == jn.HANDOFF:
                stranded.append(entry.rid)
                pkt = journal.pending_packets.get(entry.rid)
                group = None if pkt is None else groups.get(pkt["group"])
                transport = "shared_pool" if group is None else \
                    getattr(group, "transport", "shared_pool")
                if group is None:
                    entry.next_try = 0.0
                    journal.requeue(entry, error="handoff group lost "
                                                 "across takeover")
                elif transport != "shared_pool":
                    # cross-pool packet: the old primary's host-side
                    # transfer state (buffered wire frames, in-flight
                    # device_put chunks) died with it — only the WAL
                    # manifest survives.  Re-drive unified, token-exact
                    # off the journal; for a device_put packet whose
                    # source replica survives, defensively free the
                    # still-held source chain first.
                    man = pkt.get("manifest") or {}
                    if pkt.get("pages") and pkt.get("src"):
                        src = next((r for r in self.replicas
                                    if r.id == pkt["src"]), None)
                        sched = getattr(src, "sched", None)
                        if sched is not None:
                            try:
                                sched.kv.pool.free(list(pkt["pages"]))
                            except Exception:
                                pass
                    entry.next_try = 0.0
                    journal.requeue(
                        entry,
                        error="handoff transport lost across takeover;"
                              " re-driven unified from manifest "
                              f"(chunks={man.get('chunks')} "
                              f"bytes={man.get('bytes')})")
                else:
                    router._packets.append(_Packet(
                        entry, group, list(pkt["prompt"]),
                        list(pkt["pages"]), pkt["length"],
                        pkt["first_tok"], group.pool))
        tracer = self._router_kw.get("tracer")
        if tracer is not None:
            tracer.instant(
                "router_takeover", cat="failover",
                args={"epoch": epoch, "reason": reason,
                      "stranded": stranded,
                      "wal_records": self.wal.records_appended})
        self.ha.record_takeover(max(1, old.step_idx), epoch,
                                self.wal.fenced_writes,
                                self.wal.records_appended)
        self.router = router

    # ----------------------------------------------------------- facade
    def drain_all(self, grace_s=None, shed_queued=True):
        return self.router.drain_all(grace_s=grace_s,
                                     shed_queued=shed_queued)

    def audit(self, raise_on_error=True):
        return self.router.audit(raise_on_error=raise_on_error)

    def comm_ledger(self):
        return self.router.comm_ledger()

    def fleet_trace(self):
        return self.router.fleet_trace()

    def dump_trace(self, path):
        return self.router.dump_trace(path)

    def health(self):
        """The router's fleet snapshot plus the ``ha_*`` layer: lease
        epoch, takeovers, WAL cursor and fencing counters — the fields
        the router-chaos CI job asserts on."""
        h = self.router.health()
        h.update({
            "ha_enabled": True,
            "ha_epoch": self.lease.current_epoch,
            "ha_holder": self.lease.holder,
            "ha_failovers": self.failovers,
            "ha_fenced_writes": self.wal.fenced_writes,
            "ha_fenced_tokens": self.fenced_token_total +
            self.router.fenced_tokens,
            "ha_wal_records": self.wal.records_appended,
            "ha_wal_position": self.wal.position(),
        })
        return h
