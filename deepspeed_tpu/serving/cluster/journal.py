"""Router-side request journal: the cluster tier's source of truth.

Every request the router accepts gets ONE journal entry, keyed by a
client-supplied idempotency rid (auto-assigned when omitted).  The
entry survives replica crashes — it records the original prompt, the
tokens already delivered to the client, and which replica currently
holds the work — so failover is pure bookkeeping:

* **at-most-once admission** — resubmitting an rid the journal already
  holds returns the existing entry instead of serving it twice;
* **at-least-once replay** — a dead replica's unfinished entries are
  resubmitted to survivors with the already-emitted tokens folded into
  the prompt (the same recompute trick preemption uses), so the
  continuation is token-exact under the greedy contract;
* **exactly-once client output** — tokens reach the client only
  through :meth:`RequestJournal.token`, which drops anything arriving
  after the entry went terminal (a straggler event from a dying
  replica can never duplicate output).

The journal is bounded: terminal entries rotate out after
``terminal_history`` (live entries are never evicted — they are the
replay state).  ``dump()`` writes the whole thing as JSON for CI
artifacts and post-mortems.
"""

import json
import time
from collections import OrderedDict

QUEUED, ROUTED, HANDOFF = "queued", "routed", "handoff"
FINISHED, FAILED, SHED, CANCELLED = "finished", "failed", "shed", \
    "cancelled"
TERMINAL = (FINISHED, FAILED, SHED, CANCELLED)


class JournalEntry:
    """One client request's cluster-level lifecycle."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "deadline_abs", "on_token", "emitted", "state", "error",
                 "attempts", "replays", "replica", "replica_history",
                 "handle", "next_try", "t_submit", "t_first", "t_last",
                 "cancel_requested", "trace_flow",
                 "sampling", "seed", "grammar")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, deadline_s=None, sampling=None, seed=None,
                 grammar=None):
        self.rid = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.t_submit = time.monotonic()
        self.deadline_abs = None if deadline_s is None \
            else self.t_submit + float(deadline_s)
        self.emitted = []          # tokens DELIVERED to the client
        self.state = QUEUED
        self.error = None
        self.attempts = 0          # admission tries (backpressure retries)
        self.replays = 0           # failover resubmissions
        self.replica = None        # current owner replica id
        self.replica_history = []  # every replica that ever held it
        self.handle = None         # replica-side request handle
        self.next_try = 0.0        # monotonic gate for backoff retries
        self.t_first = None        # first delivered token (cluster TTFT)
        self.t_last = None
        # Decoding-policy wire fields, carried verbatim so a failover
        # resubmission reproduces the EXACT per-request token stream:
        # the position-keyed PRNG plus `sample_offset = len(emitted)`
        # makes the survivor draw the same uniforms the dead replica
        # would have, and the grammar spec recompiles + replays the
        # emitted suffix so the constraint cursor resumes in place.
        self.sampling = dict(sampling) if sampling else None
        self.seed = None if seed is None else int(seed)
        self.grammar = dict(grammar) if grammar else None
        self.cancel_requested = False
        self.trace_flow = None     # open failover-replay flow-link id:
                                   # set when a death replays this entry,
                                   # closed (and cleared) when a survivor
                                   # picks it up — the explicit
                                   # dead-replica -> replay span link in
                                   # the merged fleet trace

    @property
    def remaining_new(self):
        return self.max_new_tokens - len(self.emitted)

    def serve_prompt(self):
        """The prompt a (re)submission serves: original prompt with the
        already-delivered tokens folded in, so a survivor recomputes
        their KV but never re-emits them."""
        return self.prompt + self.emitted

    def finished_by_emitted(self):
        """True when the emitted stream already satisfies the request
        (budget reached, or the last delivered token was EOS) — a
        replay in that state finalizes instead of resubmitting."""
        if self.remaining_new <= 0:
            return True
        return bool(self.emitted) and self.eos_token_id is not None and \
            self.emitted[-1] == self.eos_token_id

    def snapshot(self):
        return {
            "rid": self.rid, "state": self.state, "error": self.error,
            "prompt_tokens": len(self.prompt),
            "emitted_tokens": len(self.emitted),
            "max_new_tokens": self.max_new_tokens,
            "attempts": self.attempts, "replays": self.replays,
            "replica": self.replica,
            "replica_history": list(self.replica_history),
            "sampling": self.sampling, "seed": self.seed,
            "grammar": self.grammar,
        }


class RequestJournal:
    """rid-keyed journal with idempotent admission and bounded terminal
    retention."""

    def __init__(self, terminal_history=4096):
        self.entries = OrderedDict()      # rid -> entry (live + recent)
        self.terminal_history = int(terminal_history)
        self._terminal_count = 0
        self._auto_rid = 0

    def admit(self, prompt, max_new_tokens, eos_token_id=None,
              on_token=None, deadline_s=None, rid=None, sampling=None,
              seed=None, grammar=None):
        """Returns ``(entry, created)``; a duplicate rid returns the
        incumbent with ``created=False`` (at-most-once admission)."""
        if rid is None:
            rid = f"auto-{self._auto_rid}"
            self._auto_rid += 1
        if rid in self.entries:
            return self.entries[rid], False
        entry = JournalEntry(rid, prompt, max_new_tokens, eos_token_id,
                             on_token, deadline_s, sampling=sampling,
                             seed=seed, grammar=grammar)
        self.entries[rid] = entry
        return entry, True

    def token(self, entry, tok):
        """The ONLY path tokens take to the client.  Terminal entries
        swallow stragglers (exactly-once output); live entries append
        and forward."""
        if entry.state in TERMINAL:
            return
        entry.emitted.append(int(tok))
        entry.t_last = time.monotonic()
        if entry.t_first is None:
            entry.t_first = entry.t_last
        if entry.on_token is not None:
            entry.on_token(entry, int(tok))

    def finalize(self, entry, state, error=None):
        entry.state = state
        if error is not None:
            entry.error = error
        entry.handle = None
        entry.replica = None
        self._terminal_count += 1
        self._rotate()

    def _rotate(self):
        """Drop the oldest terminal entries past the retention bound.
        Live entries are replay state and never rotate."""
        excess = self._terminal_count - self.terminal_history
        if excess <= 0:
            return
        for rid in [r for r, e in self.entries.items()
                    if e.state in TERMINAL][:excess]:
            del self.entries[rid]
            self._terminal_count -= 1

    def live(self):
        return [e for e in self.entries.values()
                if e.state not in TERMINAL]

    def has_live(self):
        return any(e.state not in TERMINAL for e in self.entries.values())

    def counts(self):
        out = {}
        for e in self.entries.values():
            out[e.state] = out.get(e.state, 0) + 1
        return out

    def dump(self, path):
        """CI artifact / post-mortem: every entry's snapshot plus the
        state histogram."""
        with open(path, "w") as f:
            json.dump({"counts": self.counts(),
                       "entries": [e.snapshot()
                                   for e in self.entries.values()]},
                      f, indent=2)
            f.write("\n")
