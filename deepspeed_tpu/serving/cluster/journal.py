"""Router-side request journal: the cluster tier's source of truth.

Every request the router accepts gets ONE journal entry, keyed by a
client-supplied idempotency rid (auto-assigned when omitted).  The
entry survives replica crashes — it records the original prompt, the
tokens already delivered to the client, and which replica currently
holds the work — so failover is pure bookkeeping:

* **at-most-once admission** — resubmitting an rid the journal already
  holds returns the existing entry instead of serving it twice;
* **at-least-once replay** — a dead replica's unfinished entries are
  resubmitted to survivors with the already-emitted tokens folded into
  the prompt (the same recompute trick preemption uses), so the
  continuation is token-exact under the greedy contract;
* **exactly-once client output** — tokens reach the client only
  through :meth:`RequestJournal.token`, which drops anything arriving
  after the entry went terminal (a straggler event from a dying
  replica can never duplicate output).

**Write-ahead log.**  When constructed with a ``wal`` sink (see
``cluster/wal.py``) every mutation is journaled as one record *before*
it is applied — in particular a token record is written before the
token is delivered, so a standby replaying the stream reconstructs
exactly the client-visible state.  Appends carry the journal's
``epoch``; a sink that has seen a newer epoch rejects the append and
the mutation does NOT happen (``fenced`` flips, the deposed router
stops).  :meth:`RequestJournal.replay` rebuilds a journal from a
``(snapshot, records)`` stream bit-identically over every field in
:meth:`JournalEntry.to_record` — including the PR-16 decoding-policy
fields (``sampling``/``seed``/``grammar``) that make sampled streams
continue bitwise after a takeover.

The journal is bounded: terminal entries rotate out after
``terminal_history`` (live entries are never evicted — they are the
replay state).  ``dump()`` writes the whole thing as JSON — to a
``.tmp`` then renamed (crash-safe, like checkpoints) with the WAL
position in the header — for CI artifacts and post-mortems.
"""

import json
import os
import time
from collections import OrderedDict

QUEUED, ROUTED, HANDOFF = "queued", "routed", "handoff"
FINISHED, FAILED, SHED, CANCELLED = "finished", "failed", "shed", \
    "cancelled"
TERMINAL = (FINISHED, FAILED, SHED, CANCELLED)


class JournalEntry:
    """One client request's cluster-level lifecycle."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "deadline_abs", "on_token", "emitted", "state", "error",
                 "attempts", "replays", "replica", "replica_history",
                 "replica_inc", "handle", "next_try", "t_submit",
                 "t_first", "t_last", "cancel_requested", "trace_flow",
                 "sampling", "seed", "grammar", "tenant", "adapter")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, deadline_s=None, sampling=None, seed=None,
                 grammar=None, tenant=None, adapter=None):
        self.rid = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.t_submit = time.monotonic()
        self.deadline_abs = None if deadline_s is None \
            else self.t_submit + float(deadline_s)
        self.emitted = []          # tokens DELIVERED to the client
        self.state = QUEUED
        self.error = None
        self.attempts = 0          # admission tries (backpressure retries)
        self.replays = 0           # failover resubmissions
        self.replica = None        # current owner replica id
        self.replica_history = []  # every replica that ever held it
        self.replica_inc = 0       # owner's incarnation at dispatch time:
                                   # a sink minted for incarnation N of a
                                   # replica is deaf after restart N+1, so
                                   # a flapping replica can't double-emit
        self.handle = None         # replica-side request handle
        self.next_try = 0.0        # monotonic gate for backoff retries
        self.t_first = None        # first delivered token (cluster TTFT)
        self.t_last = None
        # Decoding-policy wire fields, carried verbatim so a failover
        # resubmission reproduces the EXACT per-request token stream:
        # the position-keyed PRNG plus `sample_offset = len(emitted)`
        # makes the survivor draw the same uniforms the dead replica
        # would have, and the grammar spec recompiles + replays the
        # emitted suffix so the constraint cursor resumes in place.
        self.sampling = dict(sampling) if sampling else None
        self.seed = None if seed is None else int(seed)
        self.grammar = dict(grammar) if grammar else None
        # Tenancy attribution, journaled verbatim: a failover
        # resubmission lands on the survivor under the SAME tenant
        # (quota/billing/namespace) and the same adapter weights.
        self.tenant = tenant
        self.adapter = adapter
        self.cancel_requested = False
        self.trace_flow = None     # open failover-replay flow-link id:
                                   # set when a death replays this entry,
                                   # closed (and cleared) when a survivor
                                   # picks it up — the explicit
                                   # dead-replica -> replay span link in
                                   # the merged fleet trace

    @property
    def remaining_new(self):
        return self.max_new_tokens - len(self.emitted)

    def serve_prompt(self):
        """The prompt a (re)submission serves: original prompt with the
        already-delivered tokens folded in, so a survivor recomputes
        their KV but never re-emits them."""
        return self.prompt + self.emitted

    def finished_by_emitted(self):
        """True when the emitted stream already satisfies the request
        (budget reached, or the last delivered token was EOS) — a
        replay in that state finalizes instead of resubmitting."""
        if self.remaining_new <= 0:
            return True
        return bool(self.emitted) and self.eos_token_id is not None and \
            self.emitted[-1] == self.eos_token_id

    def snapshot(self):
        return {
            "rid": self.rid, "state": self.state, "error": self.error,
            "prompt_tokens": len(self.prompt),
            "emitted_tokens": len(self.emitted),
            "max_new_tokens": self.max_new_tokens,
            "attempts": self.attempts, "replays": self.replays,
            "replica": self.replica,
            "replica_history": list(self.replica_history),
            "sampling": self.sampling, "seed": self.seed,
            "grammar": self.grammar,
            "tenant": self.tenant, "adapter": self.adapter,
        }

    def to_record(self):
        """The replayable state — every field a WAL round-trip must
        reproduce bit-identically.  Excludes process-local handles
        (``on_token``/``handle``/``trace_flow``) and the latency clocks
        (``t_first``/``t_last``/``next_try``), which restart with the
        adopting router."""
        return {
            "rid": self.rid, "prompt": list(self.prompt),
            "max_new_tokens": self.max_new_tokens,
            "eos_token_id": self.eos_token_id,
            "t_submit": self.t_submit, "deadline_abs": self.deadline_abs,
            "emitted": list(self.emitted), "state": self.state,
            "error": self.error, "attempts": self.attempts,
            "replays": self.replays, "replica": self.replica,
            "replica_history": list(self.replica_history),
            "replica_inc": self.replica_inc,
            "cancel_requested": self.cancel_requested,
            "sampling": self.sampling, "seed": self.seed,
            "grammar": self.grammar,
            "tenant": self.tenant, "adapter": self.adapter,
        }

    @classmethod
    def from_record(cls, rec):
        e = cls(rec["rid"], rec["prompt"], rec["max_new_tokens"],
                rec.get("eos_token_id"), sampling=rec.get("sampling"),
                seed=rec.get("seed"), grammar=rec.get("grammar"),
                tenant=rec.get("tenant"), adapter=rec.get("adapter"))
        e.t_submit = rec.get("t_submit", e.t_submit)
        e.deadline_abs = rec.get("deadline_abs")
        e.emitted = [int(t) for t in rec.get("emitted", [])]
        e.state = rec.get("state", QUEUED)
        e.error = rec.get("error")
        e.attempts = int(rec.get("attempts", 0))
        e.replays = int(rec.get("replays", 0))
        e.replica = rec.get("replica")
        e.replica_history = list(rec.get("replica_history", []))
        e.replica_inc = int(rec.get("replica_inc", 0))
        e.cancel_requested = bool(rec.get("cancel_requested", False))
        return e


class RequestJournal:
    """rid-keyed journal with idempotent admission, bounded terminal
    retention, and (optional) write-ahead logging of every mutation."""

    def __init__(self, terminal_history=4096, wal=None, epoch=0,
                 snapshot_every=512):
        self.entries = OrderedDict()      # rid -> entry (live + recent)
        self.terminal_history = int(terminal_history)
        self._terminal_count = 0
        self._auto_rid = 0
        self.wal = wal
        self.epoch = int(epoch)
        self.snapshot_every = max(1, int(snapshot_every))
        self.wal_records = 0              # accepted appends by THIS writer
        self.fenced = False               # a newer epoch owns the WAL
        self._since_snapshot = 0
        self._checkpoint_due = False
        # handoff packets journaled but not yet re-dispatched, rid ->
        # wire record — a takeover re-drives these (pages are plain ids;
        # the adopting router resolves pool/group from its own fleet)
        self.pending_packets = {}

    # ------------------------------------------------------ WAL core
    def _wal(self, record):
        """Write-ahead append.  True = accepted (apply the mutation),
        False = fenced by a newer epoch (the mutation MUST NOT apply —
        exactly-once output is enforced right here).

        Auto-checkpoints are DEFERRED to the start of the next append:
        _wal runs before its record's mutation applies, so a snapshot
        taken here would miss the in-flight record — and compaction
        would then drop that record from the log entirely."""
        if self.wal is None:
            return True
        if self._checkpoint_due:
            self.checkpoint()
        if not self.wal.append(record, epoch=self.epoch):
            self.fenced = True
            return False
        self.wal_records += 1
        self._since_snapshot += 1
        if self._since_snapshot >= self.snapshot_every:
            self._checkpoint_due = True
        return True

    def state_snapshot(self):
        """Full journal state for WAL snapshots (compaction points).
        ``pending_packets`` must ride along: a journaled-but-undispatched
        handoff packet whose record was compacted away would otherwise
        be unrecoverable by the adopting router."""
        return {"auto_rid": self._auto_rid,
                "terminal_count": self._terminal_count,
                "pending_packets": {rid: dict(rec) for rid, rec
                                    in self.pending_packets.items()},
                "entries": [e.to_record() for e in self.entries.values()]}

    def checkpoint(self):
        """Write a WAL snapshot now (also called automatically every
        ``snapshot_every`` records)."""
        if self.wal is None:
            return False
        ok = self.wal.snapshot(self.state_snapshot(), epoch=self.epoch)
        self._checkpoint_due = False
        if ok:
            self._since_snapshot = 0
        else:
            self.fenced = True
        return ok

    @classmethod
    def replay(cls, records, snapshot=None, terminal_history=4096):
        """Reconstruct a journal from a WAL stream: apply ``snapshot``
        (if any), then each record in order.  ``on_token`` sinks and
        replica handles are process-local and come back ``None`` — the
        adopting supervisor rebinds them.  The result round-trips:
        ``to_record()`` of every entry is bit-identical to the
        writer's."""
        j = cls(terminal_history=terminal_history)
        if snapshot:
            j._auto_rid = int(snapshot.get("auto_rid", 0))
            j._terminal_count = int(snapshot.get("terminal_count", 0))
            for rid, rec in snapshot.get("pending_packets",
                                         {}).items():
                j.pending_packets[rid] = dict(rec)
            for rec in snapshot.get("entries", []):
                e = JournalEntry.from_record(rec)
                j.entries[e.rid] = e
        for rec in records:
            j._apply(rec)
        return j

    def attach_wal(self, wal, epoch):
        """Adopt a WAL as the new writer at ``epoch`` — the takeover
        path: a journal reconstructed by :meth:`replay` starts logging
        its own mutations (the old primary's appends are now fenced)."""
        self.wal = wal
        self.epoch = int(epoch)
        self.fenced = False
        self._since_snapshot = 0
        self._checkpoint_due = False

    def _apply(self, rec):
        """Apply one WAL record to local state (no re-logging)."""
        op = rec.get("op")
        if op == "admit":
            e = JournalEntry.from_record(rec)
            self.entries[e.rid] = e
            self._auto_rid = max(self._auto_rid,
                                 int(rec.get("auto_rid", 0)))
            return
        e = self.entries.get(rec.get("rid"))
        if e is None:
            return                       # rotated out: stale terminal rid
        if op == "dispatch":
            e.state = ROUTED
            e.replica = rec["replica"]
            e.replica_inc = int(rec.get("inc", 0))
            e.replica_history.append(rec["replica"])
            e.attempts = int(rec.get("attempts", e.attempts))
        elif op == "token":
            e.emitted.append(int(rec["t"]))
        elif op == "handoff":
            e.state = HANDOFF
            e.replica = None
            # strip the sink's epoch wrap: the stored packet must be
            # bit-identical to what the writer journaled
            self.pending_packets[e.rid] = {k: v for k, v in rec.items()
                                           if k != "e"}
        elif op == "requeue":
            e.state = QUEUED
            e.replica = None
            e.attempts = int(rec.get("attempts", e.attempts))
            e.replays = int(rec.get("replays", e.replays))
            e.error = rec.get("error", e.error)
            self.pending_packets.pop(e.rid, None)
        elif op == "cancel":
            e.cancel_requested = True
        elif op == "finalize":
            e.state = rec["state"]
            if rec.get("error") is not None:
                e.error = rec["error"]
            e.handle = None
            e.replica = None
            self.pending_packets.pop(e.rid, None)
            self._terminal_count += 1
            self._rotate()

    # ------------------------------------------------- mutation API
    def admit(self, prompt, max_new_tokens, eos_token_id=None,
              on_token=None, deadline_s=None, rid=None, sampling=None,
              seed=None, grammar=None, tenant=None, adapter=None):
        """Returns ``(entry, created)``; a duplicate rid returns the
        incumbent with ``created=False`` (at-most-once admission)."""
        if rid is None:
            rid = f"auto-{self._auto_rid}"
            self._auto_rid += 1
        if rid in self.entries:
            return self.entries[rid], False
        entry = JournalEntry(rid, prompt, max_new_tokens, eos_token_id,
                             on_token, deadline_s, sampling=sampling,
                             seed=seed, grammar=grammar, tenant=tenant,
                             adapter=adapter)
        self._wal(dict(entry.to_record(), op="admit",
                       auto_rid=self._auto_rid))
        self.entries[rid] = entry
        return entry, True

    def token(self, entry, tok):
        """The ONLY path tokens take to the client.  Terminal entries
        swallow stragglers (exactly-once output); live entries append
        and forward — after the WAL accepts the record.  A fenced
        append means a newer router owns this stream: the token is
        dropped here, never delivered twice."""
        if entry.state in TERMINAL:
            return
        if not self._wal({"op": "token", "rid": entry.rid,
                          "t": int(tok)}):
            return
        entry.emitted.append(int(tok))
        entry.t_last = time.monotonic()
        if entry.t_first is None:
            entry.t_first = entry.t_last
        if entry.on_token is not None:
            entry.on_token(entry, int(tok))

    def dispatch(self, entry, replica_id, incarnation=0):
        """Record that ``replica_id`` (at ``incarnation``) now owns the
        entry."""
        if not self._wal({"op": "dispatch", "rid": entry.rid,
                          "replica": replica_id, "inc": int(incarnation),
                          "attempts": entry.attempts}):
            return
        entry.state = ROUTED
        entry.replica = replica_id
        entry.replica_inc = int(incarnation)
        entry.replica_history.append(replica_id)
        self.pending_packets.pop(entry.rid, None)

    def handoff(self, entry, group, prompt, pages, length, first_tok,
                manifest=None, src=None):
        """Record a prefill->decode handoff packet awaiting dispatch.
        ``pages`` are plain page ids — the pool object is resolved by
        whoever (re)drives the packet.  Cross-pool packets additionally
        carry ``manifest`` (chunk count, exact payload bytes, digest,
        epoch — what a takeover needs to re-drive or account for an
        interrupted transfer) and ``src`` (the exporting replica id,
        which resolves the source pool when pages must be freed)."""
        rec = {"op": "handoff", "rid": entry.rid, "group": group,
               "prompt": [int(t) for t in prompt],
               "pages": [int(p) for p in pages], "length": int(length),
               "first_tok": int(first_tok)}
        if manifest is not None:
            rec["manifest"] = dict(manifest)
        if src is not None:
            rec["src"] = src
        if not self._wal(rec):
            return
        entry.state = HANDOFF
        entry.replica = None
        self.pending_packets[entry.rid] = rec

    def requeue(self, entry, error=None):
        """Return the entry to the routable queue (failover replay,
        handoff degrade, backpressure backoff).  Counters are journaled
        at their CURRENT values — bump ``attempts``/``replays`` before
        calling."""
        if error is not None:
            entry.error = error
        if not self._wal({"op": "requeue", "rid": entry.rid,
                          "attempts": entry.attempts,
                          "replays": entry.replays,
                          "error": entry.error}):
            return
        entry.state = QUEUED
        entry.replica = None
        self.pending_packets.pop(entry.rid, None)

    def mark_cancel(self, entry):
        if entry.cancel_requested or entry.state in TERMINAL:
            return
        if not self._wal({"op": "cancel", "rid": entry.rid}):
            return
        entry.cancel_requested = True

    def finalize(self, entry, state, error=None):
        if not self._wal({"op": "finalize", "rid": entry.rid,
                          "state": state, "error": error}):
            return
        entry.state = state
        if error is not None:
            entry.error = error
        entry.handle = None
        entry.replica = None
        self.pending_packets.pop(entry.rid, None)
        self._terminal_count += 1
        self._rotate()

    # ----------------------------------------------------- queries
    def _rotate(self):
        """Drop the oldest terminal entries past the retention bound.
        Live entries are replay state and never rotate."""
        excess = self._terminal_count - self.terminal_history
        if excess <= 0:
            return
        for rid in [r for r, e in self.entries.items()
                    if e.state in TERMINAL][:excess]:
            del self.entries[rid]
            self._terminal_count -= 1

    def live(self):
        return [e for e in self.entries.values()
                if e.state not in TERMINAL]

    def has_live(self):
        return any(e.state not in TERMINAL for e in self.entries.values())

    def counts(self):
        out = {}
        for e in self.entries.values():
            out[e.state] = out.get(e.state, 0) + 1
        return out

    def audit(self):
        """Invariant sweep; returns a list of violations (empty =
        clean).  The chaos/flap tests pin this stays empty under
        failover, revival, and router takeover."""
        problems = []
        for e in self.entries.values():
            if len(e.emitted) > e.max_new_tokens:
                problems.append(f"{e.rid}: emitted {len(e.emitted)} > "
                                f"budget {e.max_new_tokens}")
            if e.state in TERMINAL and e.replica is not None:
                problems.append(f"{e.rid}: terminal but owned by "
                                f"{e.replica}")
            if e.state in TERMINAL and e.handle is not None:
                problems.append(f"{e.rid}: terminal with live handle")
            if e.state == ROUTED and e.replica is None:
                problems.append(f"{e.rid}: routed with no owner")
        owners = {}
        for e in self.entries.values():
            if e.state == ROUTED:
                owners.setdefault((e.replica, e.rid), 0)
                owners[(e.replica, e.rid)] += 1
        for (rep, rid), n in owners.items():
            if n > 1:
                problems.append(f"{rid}: adopted {n}x by {rep}")
        return problems

    def dump(self, path):
        """CI artifact / post-mortem: every entry's snapshot plus the
        state histogram and the WAL position.  Crash-safe: written to
        ``<path>.tmp`` then renamed, the checkpoint engine's atomicity
        rule."""
        payload = {"counts": self.counts(),
                   "epoch": self.epoch,
                   "wal_position": None if self.wal is None else
                                   self.wal.position(),
                   # in-flight handoff packets WITH their transfer
                   # manifests: the dump round-trips exactly what a
                   # takeover would re-drive
                   "pending_packets": {rid: dict(rec) for rid, rec
                                       in self.pending_packets.items()},
                   "entries": [e.snapshot()
                               for e in self.entries.values()]}
        tmp = str(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
