"""Process-backed engine replica: one ServingScheduler in its own
process, driven over a JSONL stdin/stdout protocol.

stdin ops (one JSON object per line):
  {"op": "submit", "rid": ..., "prompt": [...], "max_new_tokens": N,
   "eos_token_id": E?, "deadline_s": D?,
   "sampling": {...}?, "seed": S?, "grammar": {...}?,
   "sample_offset": O?,           # decoding policy; omitted = greedy
   "handoff": true?,              # prefill role: export the chain at
                                  # prompt end instead of decoding
   "trace": {"trace_id": ...}?}   # cluster trace ctx rides the wire
  {"op": "attach", "rid": ..., "prompt": [...], "length": L,
   "first_tok": T, "manifest": {...}, ...}   # decode role: adopt a
                                  # relayed chain once its sidecar
                                  # frames verify against the manifest
  {"op": "attach_abort", "rid": ...}  # mid-transfer fault: free the
                                      # partial destination chain
  {"op": "cancel", "rid": ...}
  {"op": "fingerprint"}      # reply {"ev": "fp", ...} now (prefix
                             # digests also ride every heartbeat)
  {"op": "drain"}            # stop admitting, finish in-flight
  {"op": "trace"}            # enable span tracing at runtime
  {"op": "fence", "epoch": N}  # router-HA fence: reject ops carrying a
                               # lower epoch, cancel in-flight requests
                               # dispatched under one (their tokens
                               # belong to a deposed router)

KV page-chain payloads NEVER ride this JSONL wire: role workers get a
dedicated binary sidecar fd (``--kv-fd-out`` on prefill: exported
frames out; ``--kv-fd-in`` on decode: relayed frames in), carrying
length-prefixed ``transport.encode_frame`` frames.  Only the manifest
and the attach metadata travel on the control wire.

Ops may carry "epoch": N (router-HA).  A submit whose epoch is below
the worker's fence is REJECTED on the wire with a "fenced" done event
— the in-process check in ProcessReplica is the fast path, this is the
authority a reordering transport cannot bypass.

stdout events (one JSON object per line, flushed immediately — a token
the router never read is a token the router will replay, so buffering
here would manufacture duplicate work on a crash):
  {"ev": "ready"}                          # engine built, serving
  {"ev": "hb", "health": {...}}            # periodic health heartbeat
  {"ev": "tok", "rid": ..., "t": ...}      # one generated token
  {"ev": "done", "rid": ..., "status": ..., "tokens": [...],
   "error": ...?}
  {"ev": "handoff", "rid": ..., "prompt": [...], "length": L,
   "first_tok": T, "manifest": {...}}       # prefill role: the chain's
                                            # frames are on the sidecar
  {"ev": "attached", "rid": ...}            # decode role: manifest
                                            # verified, chain adopted
  {"ev": "fp", "page_size": P, "digests": [...], ...}  # prefix cache
                                            # fingerprint (also rides
                                            # heartbeats as hb["fp"])
  {"ev": "spans", "spans": [...]}          # --trace: serialized span
                                           # batch, flushed with each
                                           # heartbeat (epoch-µs ts, so
                                           # the router merges them onto
                                           # the fleet timeline)

SIGTERM is the elastic-agent preemption notice: the worker drains
in-flight requests within ``DS_PREEMPTION_GRACE_S`` (shedding the
still-queued remainder distinctly) and exits 0.  SIGKILL — the failure
the cluster tier exists to survive — is exactly what it looks like.
"""

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time


def _emit(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _build_engine(model_name, dtype="float32"):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2, gpt2_small, gpt2_tiny
    from deepspeed_tpu.models.llama import Llama, llama_tiny

    models = {
        "gpt2-tiny": lambda: GPT2(gpt2_tiny()),
        "gpt2-small": lambda: GPT2(gpt2_small()),
        "llama-tiny": lambda: Llama(llama_tiny()),
    }
    engine = deepspeed_tpu.init_inference(
        models[model_name](), dtype=dtype, kv_cache_dtype=dtype,
        mesh={"data": 1, "model": 1})
    # seeded init: every worker of the same model config holds the SAME
    # params, so a failover replay onto a different worker continues
    # the greedy stream token-exact
    engine.init_params(seed=0)
    return engine


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="gpt2-tiny")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--num-slots", type=int, default=3)
    p.add_argument("--num-pages", type=int, default=32)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-pages-per-slot", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=8)
    p.add_argument("--kv-dtype", default=None,
                   help="paged-KV pool dtype (float32/bfloat16/int8/"
                        "fp8); int8/fp8 pools store quantized pages + "
                        "per-row f32 scale pools.  Default: the engine "
                        "dtype")
    p.add_argument("--prefix-cache", action="store_true")
    p.add_argument("--mem-telemetry", action="store_true",
                   help="page-state attribution + per-request "
                        "page-seconds + pressure forensics; the mem_* "
                        "health fields ride the heartbeat to the router")
    p.add_argument("--comm-telemetry", action="store_true",
                   help="HLO comm-ledger capture + recompile watchdog; "
                        "the comm_* health fields ride the heartbeat "
                        "to the router (the in-process ledger analysis "
                        "runs once, after warmup)")
    p.add_argument("--trace", action="store_true",
                   help="record serving spans and flush them over the "
                        "protocol with each heartbeat")
    p.add_argument("--trace-label", default=None,
                   help="process label for this worker's spans in the "
                        "merged fleet trace (the replica id)")
    p.add_argument("--role", default="unified",
                   choices=["unified", "prefill", "decode"],
                   help="disaggregated-tier role; prefill/decode "
                        "workers move KV chains over the sidecar fds")
    p.add_argument("--kv-fd-out", type=int, default=None,
                   help="prefill role: fd exported page-chain frames "
                        "are written to (binary, length-prefixed)")
    p.add_argument("--kv-fd-in", type=int, default=None,
                   help="decode role: fd relayed page-chain frames "
                        "arrive on (binary, length-prefixed)")
    p.add_argument("--tenants", default=None,
                   help="tenants.json path (TenantConfig.from_dict "
                        "schema) — turns the multi-tenant tier on; "
                        "submits then REQUIRE a tenant field")
    p.add_argument("--lora", default=None,
                   help="adapter roster 'name=path.npz,...' (or "
                        "name=random:<rank>[:<seed>] for synthetic "
                        "factors); requires --tenants")
    p.add_argument("--hb-interval-s", type=float, default=0.2)
    p.add_argument("--threefry-partitionable", action="store_true",
                   help="mirror the parent's jax_threefry_partitionable "
                        "setting: PRNG semantics feed init_params, and "
                        "a failover replay is only token-exact across "
                        "processes when every worker holds bitwise-"
                        "identical params")
    args = p.parse_args(argv)

    if args.threefry_partitionable:
        import jax
        jax.config.update("jax_threefry_partitionable", True)

    from deepspeed_tpu.serving.cluster import transport as tp
    from deepspeed_tpu.serving.scheduler import (TERMINAL,
                                                 ServingScheduler)

    engine = _build_engine(args.model, args.dtype)
    tenancy = None
    if args.tenants is not None or args.lora is not None:
        # same builder ds_serve uses: every worker of the fleet derives
        # the IDENTICAL registry (adapter ids, namespaces, weights)
        # from the same CLI strings, so failover replays land under
        # the same tenant/adapter on any survivor
        from deepspeed_tpu.serving.tenancy import build_tenancy
        tenancy = build_tenancy(engine.module.cfg, tenants=args.tenants,
                                lora=args.lora)
    sched = ServingScheduler(
        engine, num_slots=args.num_slots, num_pages=args.num_pages,
        page_size=args.page_size,
        max_pages_per_slot=args.max_pages_per_slot,
        prefill_chunk=args.prefill_chunk, prefix_cache=args.prefix_cache,
        kv_dtype=args.kv_dtype,
        mem_telemetry=args.mem_telemetry,
        comm_telemetry=args.comm_telemetry, tenancy=tenancy)

    fence = {"epoch": 0}   # highest router epoch seen on the wire

    # ---- KV sidecar: the binary fd pair page-chain payloads ride.
    # Prefill exports whole chains out; decode scatters relayed frames
    # in, chunk by chunk, overlapped with its own decode horizon.
    kv_out = None
    if args.role == "prefill" and args.kv_fd_out is not None:
        kv_out = os.fdopen(args.kv_fd_out, "wb")

        def on_handoff(req, pages, length, first_tok):
            """Export the finished prompt's chain: host-stage + frame
            every chunk onto the sidecar, then free the local pages —
            the source's HBM is reclaimed the moment the bytes leave
            (a destination death later still requeues unified token-
            exact off the journal, never off these pages)."""
            t0 = time.monotonic()
            frames, manifest = tp.export_chain_frames(
                engine, sched.pools, pages, req._wire_rid,
                epoch=fence["epoch"])
            for fr in frames:
                kv_out.write(fr)
            kv_out.flush()
            sched.kv.pool.free(pages)
            sched.metrics.record_handoff_transport(
                sched.step_idx, "out", manifest["bytes"],
                manifest["chunks"], (time.monotonic() - t0) * 1e3)
            _emit({"ev": "handoff", "rid": req._wire_rid,
                   "prompt": [int(t) for t in req.orig_prompt],
                   "length": int(length), "first_tok": int(first_tok),
                   "manifest": manifest})

        sched.on_handoff = on_handoff

    kv_frames = queue.Queue()
    if args.role == "decode" and args.kv_fd_in is not None:
        def _kv_reader():
            stream = os.fdopen(args.kv_fd_in, "rb")
            try:
                while True:
                    fr = tp.read_frame(stream)
                    if fr is None:
                        return          # router hung up the sidecar
                    kv_frames.put(fr)
            except Exception:
                pass

        threading.Thread(target=_kv_reader, daemon=True).start()

    # decode-side in-flight imports: wire rid -> {"imp": ChunkImporter,
    # "op": the attach op (metadata for the eventual attach_handoff),
    # "t0": arrival time}.  Frames racing ahead of their attach op on
    # the other pipe park in orphans until the op lands.
    imports = {}
    orphans = {}

    tracer = {"t": None}

    def enable_trace(label=None):
        if tracer["t"] is None:
            from deepspeed_tpu.serving.trace import SpanTracer
            tracer["t"] = SpanTracer(
                process=label or args.trace_label or
                f"worker-{os.getpid()}")
            sched.tracer = tracer["t"]
            if sched.mem.enabled:
                # the pool counter track rides the worker's span flushes
                sched.mem.bind(sched.metrics, tracer["t"])

    if args.trace:
        enable_trace()

    def flush_spans():
        t = tracer["t"]
        if t is not None and t.events:
            _emit({"ev": "spans", "spans": t.serialized(drain=True)})

    term = {"flag": False}
    signal.signal(signal.SIGTERM, lambda *a: term.update(flag=True))

    live = {}          # wire rid -> scheduler Request
    eof = False
    last_hb = 0.0
    _emit({"ev": "ready"})

    def on_token(req, tok):
        _emit({"ev": "tok", "rid": req._wire_rid, "t": int(tok)})

    def report(req):
        row = {"ev": "done", "rid": req._wire_rid, "status": req.state,
               "tokens": [int(t) for t in req.out_tokens]}
        if req.error is not None:
            row["error"] = req.error
        _emit(row)

    def shed(rid, error):
        _emit({"ev": "done", "rid": rid, "status": "shed",
               "tokens": [], "error": error})

    def finish_import(rid):
        """Last chunk landed: verify against the manifest, adopt the
        chain.  A verification miss (truncated relay, corrupt frame)
        frees the pages and sheds distinctly — the router requeues
        unified off the journal, never off a half-imported chain."""
        st = imports.pop(rid)
        orphans.pop(rid, None)
        imp, op = st["imp"], st["op"]
        if not imp.verify():
            imp.abort()
            shed(rid, "KV transfer verification failed: "
                      f"{imp.nbytes}B/{imp.seq} chunks vs manifest "
                      f"{imp.manifest['bytes']}B/"
                      f"{imp.manifest['chunks']}")
            return
        try:
            req = sched.attach_handoff(
                op["prompt"], imp.pages, op["length"], op["first_tok"],
                max_new_tokens=op.get("max_new_tokens", 32),
                eos_token_id=op.get("eos_token_id"),
                on_token=on_token, deadline_s=op.get("deadline_s"),
                trace_ctx=op.get("trace"),
                sampling=op.get("sampling"), seed=op.get("seed"),
                grammar=op.get("grammar"),
                sample_offset=op.get("sample_offset", 0),
                tenant=op.get("tenant"), adapter=op.get("adapter"))
        except Exception as e:
            sched.kv.pool.free(imp.pages)
            shed(rid, f"{type(e).__name__}: {e}")
            return
        req._wire_rid = rid
        req._fence_epoch = st["epoch"]
        live[rid] = req
        sched.metrics.record_handoff_transport(
            sched.step_idx, "in", imp.nbytes, imp.seq,
            (time.monotonic() - st["t0"]) * 1e3)
        _emit({"ev": "attached", "rid": rid})

    def feed_frame(st, rid, header, raw):
        imp = st["imp"]
        try:
            imp.feed(header, raw)
        except Exception as e:
            imports.pop(rid, None)
            orphans.pop(rid, None)
            imp.abort()
            shed(rid, f"{type(e).__name__}: {e}")
            return
        if imp.done:
            finish_import(rid)

    def pump_kv():
        """Scatter every sidecar frame that has landed.  Frames that
        raced ahead of their attach op (separate pipes, no cross-fd
        ordering) park in ``orphans`` until the op arrives."""
        while True:
            try:
                header, raw = kv_frames.get_nowait()
            except queue.Empty:
                return
            rid = header["rid"]
            st = imports.get(rid)
            if st is None:
                orphans.setdefault(rid, []).append((header, raw))
                continue
            feed_frame(st, rid, header, raw)

    # stdin rides a reader thread: select()-then-readline() on a
    # BUFFERED stream drops the tail of a multi-line burst (readline
    # pulls the whole kernel buffer into Python's, so select sees an
    # empty fd while ops sit unread) — a blocking reader thread has no
    # such window
    ops = queue.Queue()

    def _stdin_reader():
        for line in sys.stdin:
            ops.put(line)
        ops.put(None)           # EOF sentinel

    threading.Thread(target=_stdin_reader, daemon=True).start()

    def pump_stdin():
        nonlocal eof
        while not eof:
            try:
                line = ops.get_nowait()
            except queue.Empty:
                return
            if line is None:    # router hung up: drain and leave
                eof = True
                term["flag"] = True
                return
            line = line.strip()
            if not line:
                continue
            op = json.loads(line)
            kind = op.get("op")
            op_epoch = op.get("epoch")
            if op_epoch is not None and op_epoch > fence["epoch"]:
                fence["epoch"] = int(op_epoch)
                sched.ha_epoch = fence["epoch"]
            if kind == "submit":
                if op_epoch is not None and op_epoch < fence["epoch"]:
                    # stale-epoch dispatch: a deposed router's late op.
                    # Reject on the wire — never admitted, never echoed
                    sched.ha_fenced += 1
                    _emit({"ev": "done", "rid": op["rid"],
                           "status": "fenced", "tokens": [],
                           "error": f"epoch {op_epoch} < fence "
                                    f"{fence['epoch']}"})
                    continue
                try:
                    req = sched.submit(
                        op["prompt"], op.get("max_new_tokens", 32),
                        eos_token_id=op.get("eos_token_id"),
                        deadline_s=op.get("deadline_s"),
                        on_token=on_token,
                        handoff=bool(op.get("handoff")),
                        trace_ctx=op.get("trace"),
                        sampling=op.get("sampling"),
                        seed=op.get("seed"),
                        grammar=op.get("grammar"),
                        sample_offset=op.get("sample_offset", 0),
                        tenant=op.get("tenant"),
                        adapter=op.get("adapter"))
                except Exception as e:
                    shed(op["rid"], f"{type(e).__name__}: {e}")
                    continue
                req._wire_rid = op["rid"]
                req._fence_epoch = op_epoch
                if req.state in TERMINAL:   # max_new_tokens=0 parity
                    report(req)
                else:
                    live[op["rid"]] = req
            elif kind == "attach":
                if op_epoch is not None and op_epoch < fence["epoch"]:
                    sched.ha_fenced += 1
                    _emit({"ev": "done", "rid": op["rid"],
                           "status": "fenced", "tokens": [],
                           "error": f"epoch {op_epoch} < fence "
                                    f"{fence['epoch']}"})
                    continue
                try:
                    # allocates the whole destination chain up front;
                    # PagePoolExhausted sheds before any bytes scatter
                    imp = tp.ChunkImporter(engine, sched,
                                           op["manifest"])
                except Exception as e:
                    shed(op["rid"], f"{type(e).__name__}: {e}")
                    continue
                st = {"imp": imp, "op": op, "t0": time.monotonic(),
                      "epoch": op_epoch}
                imports[op["rid"]] = st
                for header, raw in orphans.pop(op["rid"], []):
                    feed_frame(st, op["rid"], header, raw)
                    if op["rid"] not in imports:
                        break     # fed to completion (or shed)
            elif kind == "attach_abort":
                rid = op.get("rid")
                orphans.pop(rid, None)
                st = imports.pop(rid, None)
                if st is not None:
                    st["imp"].abort()
            elif kind == "fingerprint":
                if sched.prefix_cache is not None:
                    _emit({"ev": "fp",
                           **sched.prefix_cache.fingerprint()})
            elif kind == "cancel":
                req = live.get(op.get("rid"))
                if req is not None:
                    req.cancel()
            elif kind == "fence":
                # cancel everything dispatched under an older epoch:
                # those tokens would be dropped by the new router's
                # journal anyway, so reclaim the slots/pages now
                for req in list(live.values()):
                    tag = getattr(req, "_fence_epoch", None)
                    if tag is None or tag < fence["epoch"]:
                        req.cancel()
                        sched.ha_fenced += 1
            elif kind == "drain":
                sched.begin_drain(shed_waiting=False)
            elif kind == "trace":
                enable_trace(op.get("label"))

    while True:
        pump_stdin()
        pump_kv()
        if term["flag"]:
            break
        work = sched.step() if (sched.requests or sched._inflight or
                                sched._pending_attach) else False
        for rid in [r for r, req in live.items()
                    if req.state in TERMINAL]:
            report(live.pop(rid))
        now = time.monotonic()
        if now - last_hb >= args.hb_interval_s:
            if sched.comm_telemetry and sched._comm_summary is None \
                    and sched.step_idx >= 2 and not sched.requests:
                # one-time static analysis (an XLA re-compile per
                # signature), gated on an IDLE heartbeat so no live
                # request's latency pays it; the comm_* fields ride
                # every subsequent heartbeat to the router
                sched.comm_ledger()
            flush_spans()
            hb = {"ev": "hb", "health": sched.health()}
            if sched.prefix_cache is not None:
                # the prefix fingerprint rides every heartbeat: the
                # router scores this worker for a prompt exactly like
                # an in-process replica, from digests instead of the
                # trie it cannot see
                hb["fp"] = sched.prefix_cache.fingerprint()
            _emit(hb)
            last_hb = now
        if not work:
            time.sleep(0.01)

    # SIGTERM drain: finish in-flight within the supervisor's grace
    # budget, shed the rest distinctly, report every outcome
    grace = float(os.environ.get("DS_PREEMPTION_GRACE_S", 10.0))
    sched.drain(grace_s=grace, shed_waiting=True)
    for rid in list(live):
        report(live.pop(rid))
    flush_spans()
    _emit({"ev": "hb", "health": sched.health()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
