"""KV page-chain transport: move a handoff's pages between pools.

The disaggregated tier's handoff API already detaches a finished
prompt's page chain from the prefill scheduler (``take_slot_pages``)
and adopts it on the decode side (``adopt_chain``) — this module is
what happens IN BETWEEN when the two sides do not share a pool.  The
page chain is the transfer unit, moved in chunks so the transfer
overlaps both sides' ongoing serving, with a three-path dispatch rule
(:func:`choose_transport`):

``shared_pool``
    Source and destination schedulers share ONE ``PagePool`` + pools
    ref (the original in-process tier).  Nothing moves: the handoff
    stays a host-side ownership transfer of page ids, zero copies.

``device_put``
    Same process, separate pools.  ``engine.export_page_chain``
    gathers a chunk (payload keeps the pool layout + sharding), the
    payload rides ``jax.device_put`` to the destination pool's
    ``NamedSharding``, ``engine.import_page_chain`` scatters it into
    freshly allocated destination pages.  No host staging.

``wire``
    Separate processes (``ProcessReplica``).  The exported chunk is
    staged to host and framed onto a dedicated binary KV sidecar fd —
    length-prefixed frames, NEVER the JSONL control wire — relayed by
    the router from the prefill worker's sidecar to the decode
    worker's, and scattered incrementally on arrival.

Chunking follows the power-of-two bucket discipline every other
serving primitive uses: a chunk of ``n`` pages pads to
``chunk_bucket(n)`` so export/import compile once per bucket, never
per chain length.  Export pads ids with page 0 (a harmless extra
gather, trimmed on host); import pads with ``num_pages`` so the
``mode="drop"`` masking contract swallows the padded writes.

Every leaf of every layer dict moves with the chunk — for quantized
pools that is the k/v payload AND the per-row scale leaves, welded to
their page exactly like ``copy_page`` welds them (a chain that moved
int8/fp8 payload without scales would dequantize on the destination
with whatever stale scales its fresh pages held).

Wire frame format (both sidecar hops)::

    b"KV01" | header_len:u32le | payload_len:u64le | header | payload

where ``header`` is a compact-JSON dict ``{rid, seq, of, pages,
leaves: [[dtype, shape], ...]}`` and ``payload`` is the raw
concatenation of the trimmed host leaves in (layer, sorted-key)
order.  A transfer MANIFEST — ``{pages, chunks, bytes, digest,
epoch}`` — travels on the control wire and into the journal's WAL, so
a router takeover knows exactly what was in flight; ``digest`` is a
blake2b over the concatenated frame payloads in seq order (wire path;
paths that never host-stage record ``digest=""``).
"""

import hashlib
import json
import struct

import numpy as np

from deepspeed_tpu.serving.page_manager import PagePoolExhausted

# max pages per transfer dispatch: one chunk.  Bounds per-dispatch
# payload bytes AND caps the bucket set at {1, 2, 4, 8} — at most
# four compile signatures per primitive regardless of chain length.
CHUNK_PAGES = 8

_MAGIC = b"KV01"
_HDR = struct.Struct("<IQ")


def chunk_bucket(n):
    """Smallest power of two >= n (n >= 1): the padded chunk length
    export/import compile against."""
    b = 1
    while b < n:
        b <<= 1
    return b


def iter_chunks(pages, chunk_pages=CHUNK_PAGES):
    """Split a page chain into transfer chunks, in order."""
    for i in range(0, len(pages), chunk_pages):
        yield list(pages[i:i + chunk_pages])


def num_chunks(n_pages, chunk_pages=CHUNK_PAGES):
    return (n_pages + chunk_pages - 1) // chunk_pages


def choose_transport(src_rep, dst_rep):
    """The three-path dispatch rule.  A replica without an in-process
    scheduler (``ProcessReplica``) forces the wire; two in-process
    schedulers sharing one ``PagePool`` object need no transport at
    all; anything else is a same-process cross-pool ``device_put``."""
    src_sched = getattr(src_rep, "sched", None)
    dst_sched = getattr(dst_rep, "sched", None)
    if src_sched is None or dst_sched is None:
        return "wire"
    if src_sched.kv.pool is dst_sched.kv.pool:
        return "shared_pool"
    return "device_put"


def make_manifest(n_pages, nbytes, digest, epoch, chunk_pages=CHUNK_PAGES):
    return {"pages": int(n_pages),
            "chunks": num_chunks(n_pages, chunk_pages),
            "bytes": int(nbytes), "digest": digest, "epoch": int(epoch)}


# ------------------------------------------------------------ chunks

def export_chunk(engine, pools, chunk):
    """Gather one chunk as a device payload padded to its bucket.
    Returns ``(payload, bucket)``; the payload's page dim is
    ``bucket`` long — trim to ``len(chunk)`` before host staging."""
    b = chunk_bucket(len(chunk))
    ids = np.zeros(b, np.int32)
    ids[:len(chunk)] = chunk
    return engine.export_page_chain(pools, ids), b


def import_chunk(engine, pools_ref, payload, chunk, num_pages):
    """Scatter a (bucket-padded) payload into ``chunk``'s pages,
    updating ``pools_ref`` in place.  Padded ids are ``num_pages``:
    out of range, dropped by the write mask."""
    b = int(np.shape(next(iter(payload[0].values())))[0])
    ids = np.full(b, num_pages, np.int32)
    ids[:len(chunk)] = chunk
    pools_ref.pools = engine.import_page_chain(pools_ref.pools, payload,
                                               ids)


def payload_to_host(payload, n_pages):
    """Trim a device payload to its real pages and pull to host as a
    flat leaf list in deterministic (layer, sorted-key) order — the
    wire order both sides agree on."""
    leaves = []
    for layer in payload:
        for name in sorted(layer):
            leaves.append(np.asarray(layer[name][:n_pages]))
    return leaves


def leaves_to_payload(leaves, layer_keys, bucket):
    """Rebuild the per-layer payload dicts from a flat host leaf list,
    padding each leaf's page dim to ``bucket`` (import compiles per
    bucket, and only the last chunk of a chain is ragged)."""
    keys = sorted(layer_keys)
    payload = []
    for i in range(0, len(leaves), len(keys)):
        group = leaves[i:i + len(keys)]
        layer = {}
        for name, arr in zip(keys, group):
            n = arr.shape[0]
            if n < bucket:
                pad = np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            layer[name] = arr
        payload.append(layer)
    return payload


# ------------------------------------------------------------ frames

def encode_frame(rid, seq, total, leaves):
    """Frame one host-staged chunk for the KV sidecar fd."""
    n_pages = int(leaves[0].shape[0])
    header = {"rid": rid, "seq": int(seq), "of": int(total),
              "pages": n_pages,
              "leaves": [[str(a.dtype), list(a.shape)] for a in leaves]}
    hb = json.dumps(header, separators=(",", ":")).encode()
    raw = b"".join(a.tobytes() for a in leaves)
    return _MAGIC + _HDR.pack(len(hb), len(raw)) + hb + raw


def decode_frame(buf):
    """Decode one full frame from bytes -> (header, raw)."""
    if buf[:4] != _MAGIC:
        raise ValueError("bad KV frame magic")
    hlen, plen = _HDR.unpack(buf[4:4 + _HDR.size])
    off = 4 + _HDR.size
    header = json.loads(buf[off:off + hlen].decode())
    raw = buf[off + hlen:off + hlen + plen]
    if len(raw) != plen:
        raise ValueError("truncated KV frame payload")
    return header, raw


def read_frame(stream):
    """Blocking-read one frame from a binary stream; None on EOF."""
    head = stream.read(4 + _HDR.size)
    if not head or len(head) < 4 + _HDR.size:
        return None
    if head[:4] != _MAGIC:
        raise ValueError("bad KV frame magic")
    hlen, plen = _HDR.unpack(head[4:])
    body = stream.read(hlen + plen)
    if len(body) < hlen + plen:
        return None
    header = json.loads(body[:hlen].decode())
    return header, body[hlen:]


def frame_leaves(header, raw):
    """Reconstruct the flat host leaf list from a decoded frame."""
    leaves, off = [], 0
    for dtype, shape in header["leaves"]:
        arr = np.frombuffer(raw, dtype=np.dtype(dtype), count=int(
            np.prod(shape, dtype=np.int64)), offset=off)
        leaves.append(arr.reshape(shape))
        off += arr.nbytes
    return leaves


# ------------------------------------------------- whole-chain export

def export_chain_frames(engine, pools, pages, rid, *, epoch=0,
                        chunk_pages=CHUNK_PAGES):
    """Export a full chain as wire frames + its manifest (host-staged
    path: the prefill worker's side of a cross-process handoff).
    Returns ``(frames, manifest)``; ``manifest["bytes"]`` is the exact
    payload byte count — ``pages * engine.kv_page_bytes(...)`` — and
    ``manifest["digest"]`` hashes the payloads in seq order."""
    chunks = list(iter_chunks(pages, chunk_pages))
    frames, nbytes = [], 0
    h = hashlib.blake2b(digest_size=16)
    for seq, chunk in enumerate(chunks):
        payload, _ = export_chunk(engine, pools, chunk)
        leaves = payload_to_host(payload, len(chunk))
        frame = encode_frame(rid, seq, len(chunks), leaves)
        raw = b"".join(a.tobytes() for a in leaves)
        h.update(raw)
        nbytes += len(raw)
        frames.append(frame)
    manifest = make_manifest(len(pages), nbytes, h.hexdigest(), epoch,
                             chunk_pages)
    return frames, manifest


class ChunkImporter:
    """Destination-side incremental importer: fresh pages allocated up
    front, each arriving chunk scattered AS IT LANDS — the import of
    chunk k overlaps the transfer of chunk k+1, and the destination
    keeps decoding between chunks.  ``finish()`` verifies the running
    digest against the manifest; ``abort()`` frees the partial pages
    (the destination half of the frees-both-sides failure rule)."""

    def __init__(self, engine, sched, manifest):
        self.engine = engine
        self.sched = sched
        self.manifest = manifest
        # PagePoolExhausted propagates: the caller sheds/requeues the
        # attach before any bytes were scattered
        self.pages = sched.kv.pool.allocate(manifest["pages"])
        self._keys = list(sched.pools["layers"][0])
        self._h = hashlib.blake2b(digest_size=16)
        self.seq = 0
        self.nbytes = 0
        self.done = False
        self.aborted = False

    def feed(self, header, raw):
        """Scatter one frame's chunk.  Frames must arrive in seq order
        (the sidecar is a pipe; order is inherent)."""
        if header["seq"] != self.seq:
            raise ValueError(
                f"KV frame out of order: got seq {header['seq']}, "
                f"want {self.seq}")
        leaves = frame_leaves(header, raw)
        n = header["pages"]
        chunk = self.pages[self.seq * CHUNK_PAGES:
                           self.seq * CHUNK_PAGES + n]
        payload = leaves_to_payload(leaves, self._keys, chunk_bucket(n))
        import_chunk(self.engine, self.sched._pools_ref, payload, chunk,
                     self.sched.kv.pool.num_pages)
        self._h.update(raw)
        self.nbytes += len(raw)
        self.seq += 1
        if self.seq == self.manifest["chunks"]:
            self.done = True

    def verify(self):
        """True when every chunk landed and the digest matches."""
        return (self.done
                and self.nbytes == self.manifest["bytes"]
                and self._h.hexdigest() == self.manifest["digest"])

    def abort(self):
        if not self.aborted:
            self.aborted = True
            self.sched.kv.pool.free(self.pages)


__all__ = ["CHUNK_PAGES", "chunk_bucket", "iter_chunks", "num_chunks",
           "choose_transport", "make_manifest", "export_chunk",
           "import_chunk", "payload_to_host", "leaves_to_payload",
           "encode_frame", "decode_frame", "read_frame", "frame_leaves",
           "export_chain_frames", "ChunkImporter", "PagePoolExhausted"]
