"""Journal write-ahead log: pluggable, epoch-fenced record sinks.

The :class:`~deepspeed_tpu.serving.cluster.journal.RequestJournal` is
the cluster tier's source of truth for exactly-once client output.  To
make the *router* replaceable, every journal mutation is first written
to a WAL sink as one JSON record; a standby that tails the stream can
rebuild the journal bit-identically (``RequestJournal.replay``) and
take over mid-flight.

Two sinks share one contract:

* :class:`MemoryWalSink` — an in-process record list, the test double
  and the default for ``RouterSupervisor`` (primary and standby live
  in one process, so the "stream" is just shared memory);
* :class:`FileWalSink` — crash-safe JSONL segments on disk.  Records
  append to ``wal-NNNNNN.jsonl`` (flushed per record, fsync'd on
  rotation/close, or per record with ``fsync_records=True``);
  snapshots write ``snapshot-NNNNNN.json`` via tmp+rename (the same
  atomicity rule the checkpoint engine pins) and rotate the live
  segment, so recovery is *newest valid snapshot + the segments at or
  after it*.  A torn tail (the classic half-written last line of a
  crash) is detected and ignored, never parsed into garbage.

**Epoch fencing.**  Every append carries the writer's epoch.  A sink
remembers the highest epoch it has ever seen and *drops* (returns
``False`` for) any append from a lower one, counting it in
``fenced_writes``.  The WAL is therefore the authority that makes
exactly-once output survive a zombie primary: a deposed router's
``journal.token`` hits the fence and the mutation — including client
delivery — never happens.
"""

import json
import os

__all__ = ["MemoryWalSink", "FileWalSink"]


class _WalSinkBase:
    """Shared epoch-fence + counters; subclasses store the bytes."""

    def __init__(self):
        self.max_epoch = 0         # highest writer epoch ever accepted
        self.fenced_writes = 0     # stale-epoch appends dropped
        self.records_appended = 0  # accepted appends (lifetime)
        self.snapshots_taken = 0

    def _admit(self, epoch):
        epoch = int(epoch)
        if epoch < self.max_epoch:
            self.fenced_writes += 1
            return False
        self.max_epoch = epoch
        return True

    # -- subclass surface ------------------------------------------
    def append(self, record, epoch=0):
        """Append one journal record.  Returns True when accepted,
        False when fenced (the caller must NOT apply the mutation)."""
        raise NotImplementedError

    def snapshot(self, state, epoch=0):
        """Write a compaction point; records before it are no longer
        needed for recovery.  Fenced like append."""
        raise NotImplementedError

    def replay_stream(self):
        """``(snapshot_state_or_None, records_after_snapshot)`` — the
        minimal recovery input for ``RequestJournal.replay``."""
        raise NotImplementedError

    def position(self):
        """Durable cursor for dump headers: segment + in-segment
        offset + lifetime record count."""
        raise NotImplementedError

    def close(self):
        pass


class MemoryWalSink(_WalSinkBase):
    """In-process WAL: a snapshot slot plus the records after it."""

    def __init__(self):
        super().__init__()
        self._snapshot = None
        self._records = []
        self._segment = 0          # bumped per snapshot, mirrors file

    def append(self, record, epoch=0):
        if not self._admit(epoch):
            return False
        self._records.append(dict(record, e=int(epoch)))
        self.records_appended += 1
        return True

    def snapshot(self, state, epoch=0):
        if not self._admit(epoch):
            return False
        self._snapshot = json.loads(json.dumps(state))  # deep, json-clean
        self._records = []
        self._segment += 1
        self.snapshots_taken += 1
        return True

    def replay_stream(self):
        return self._snapshot, list(self._records)

    def position(self):
        return {"segment": self._segment, "offset": len(self._records),
                "records": self.records_appended}


class FileWalSink(_WalSinkBase):
    """Crash-safe JSONL WAL under one directory.

    Layout::

        wal-000000.jsonl            # records, oldest segment
        snapshot-000001.json        # state as of segment boundary 1
        wal-000001.jsonl            # records after that snapshot

    Recovery: load the newest parseable ``snapshot-N.json``, then apply
    ``wal-M.jsonl`` for every M >= N in order, stopping a segment at
    the first torn (unparseable) line.  Old segments/snapshots are
    pruned opportunistically after each snapshot.
    """

    def __init__(self, root, fsync_records=False, keep_segments=2):
        super().__init__()
        self.root = str(root)
        self.fsync_records = bool(fsync_records)
        self.keep_segments = max(1, int(keep_segments))
        self.torn_records = 0
        os.makedirs(self.root, exist_ok=True)
        segs = self._segments()
        snap = self._latest_snapshot_idx()
        self._seg_idx = max(segs[-1] if segs else 0, snap)
        self._seg_off = 0
        self._fh = None
        # resume appending after any valid tail of the live segment
        if os.path.exists(self._seg_path(self._seg_idx)):
            good, _ = self._read_segment(self._seg_idx)
            self._seg_off = len(good)

    # ------------------------------------------------------- naming
    def _seg_path(self, idx):
        return os.path.join(self.root, f"wal-{idx:06d}.jsonl")

    def _snap_path(self, idx):
        return os.path.join(self.root, f"snapshot-{idx:06d}.json")

    def _segments(self):
        out = []
        for name in os.listdir(self.root):
            if name.startswith("wal-") and name.endswith(".jsonl"):
                try:
                    out.append(int(name[4:-6]))
                except ValueError:
                    pass
        return sorted(out)

    def _latest_snapshot_idx(self):
        best = 0
        for name in os.listdir(self.root):
            if name.startswith("snapshot-") and name.endswith(".json"):
                try:
                    idx = int(name[9:-5])
                except ValueError:
                    continue
                try:
                    with open(os.path.join(self.root, name)) as f:
                        json.load(f)
                except (OSError, ValueError):
                    continue            # torn snapshot: ignore
                best = max(best, idx)
        return best

    # ------------------------------------------------------ writing
    def _handle(self):
        if self._fh is None:
            self._fh = open(self._seg_path(self._seg_idx), "a")
        return self._fh

    def append(self, record, epoch=0):
        if not self._admit(epoch):
            return False
        fh = self._handle()
        fh.write(json.dumps(dict(record, e=int(epoch)),
                            separators=(",", ":")) + "\n")
        fh.flush()
        if self.fsync_records:
            os.fsync(fh.fileno())
        self._seg_off += 1
        self.records_appended += 1
        return True

    def snapshot(self, state, epoch=0):
        if not self._admit(epoch):
            return False
        nxt = self._seg_idx + 1
        tmp = self._snap_path(nxt) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path(nxt))
        # seal the old segment durably, then rotate
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
        self._seg_idx = nxt
        self._seg_off = 0
        self.snapshots_taken += 1
        self._fsync_dir()
        self._prune()
        return True

    def _fsync_dir(self):
        try:
            fd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass                       # not supported everywhere

    def _prune(self):
        """Drop segments/snapshots no recovery path can need."""
        floor = self._seg_idx - self.keep_segments
        for idx in self._segments():
            if idx < floor:
                try:
                    os.remove(self._seg_path(idx))
                except OSError:
                    pass
        for name in list(os.listdir(self.root)):
            if name.startswith("snapshot-") and name.endswith(".json"):
                try:
                    if int(name[9:-5]) < self._seg_idx:
                        os.remove(os.path.join(self.root, name))
                except (ValueError, OSError):
                    pass

    # ------------------------------------------------------ reading
    def _read_segment(self, idx):
        """(records, torn) — stops at the first unparseable line; a
        torn record makes everything after it unreachable (the crash-
        consistency rule: never apply past a hole)."""
        path = self._seg_path(idx)
        records, torn = [], 0
        if not os.path.exists(path):
            return records, torn
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    torn = 1
                    break
        return records, torn

    def replay_stream(self):
        snap_idx = self._latest_snapshot_idx()
        snapshot = None
        if os.path.exists(self._snap_path(snap_idx)):
            with open(self._snap_path(snap_idx)) as f:
                snapshot = json.load(f)
        records = []
        self.torn_records = 0
        for idx in [i for i in self._segments() if i >= snap_idx]:
            recs, torn = self._read_segment(idx)
            records.extend(recs)
            self.torn_records += torn
            if torn:
                break                  # nothing after a hole is safe
        return snapshot, records

    def position(self):
        return {"segment": self._seg_idx, "offset": self._seg_off,
                "records": self.records_appended}

    def close(self):
        if self._fh is not None:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._fh.close()
            self._fh = None
