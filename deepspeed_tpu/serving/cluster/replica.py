"""Engine replicas: the units the cluster router load-balances over.

Two backings share one interface (submit/attach/step/heartbeat/drain/
restart/prefix_match_len):

* :class:`LocalReplica` — a ``ServingScheduler`` in this process,
  stepped cooperatively by the router's pump.  Crashes are simulated
  through the ``cluster.replica_kill`` fault point: an armed injection
  raising at the replica's step entry drops the whole scheduler —
  in-flight requests, queue, prefix cache — exactly like a process
  death, and the shared page pool is made whole again (a real node
  death takes its HBM with it; the in-process model must not leak the
  pool it shares with survivors).
* :class:`ProcessReplica` — a child process running
  ``deepspeed_tpu.serving.cluster.worker`` over a JSONL stdin/stdout
  protocol.  Death is real (SIGKILL), detection is missed heartbeats
  or a reaped pid, and restart honors the elastic agent's
  SIGTERM-then-SIGKILL ``term_grace_s`` contract
  (``DS_PREEMPTION_GRACE_S`` rides the worker env so its drain sizes
  itself against the real budget).

A replica NEVER owns client-visible request state: the router's
journal does.  Replica handles expose ``.state``/``.error``/
``.cancel()`` and stream tokens through the router-supplied callback;
everything else is private.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from collections import deque

from deepspeed_tpu.resilience import faults

UP, DRAINING, DEAD = "up", "draining", "dead"


class ReplicaKilled(RuntimeError):
    """A replica crashed, was killed, or stopped heartbeating."""


class StaleEpoch(RuntimeError):
    """A replica-facing call carried an epoch older than the replica's
    fence — the CALLER is a deposed (zombie) router, not the replica.
    Raised instead of doing the work; the zombie must stop dispatching.
    Deliberately NOT a :class:`ReplicaKilled`: the replica is fine."""


def _fence_check(rep, epoch):
    """Shared epoch gate: ``None`` means the caller is not running
    under HA (legacy single-router path — no fencing).  A newer epoch
    advances the fence (the first dispatch from a new primary fences
    everything older); a stale one raises."""
    if epoch is None:
        return
    epoch = int(epoch)
    if epoch < rep.fence_epoch:
        rep.fenced_calls += 1
        sched = getattr(rep, "sched", None)
        if sched is not None:
            sched.ha_fenced += 1
        raise StaleEpoch(
            f"{rep.id}: epoch {epoch} < fence {rep.fence_epoch}")
    if epoch > rep.fence_epoch:
        rep.fence_epoch = epoch
        sched = getattr(rep, "sched", None)
        if sched is not None:
            sched.ha_epoch = epoch


class LocalReplica:
    """An in-process ServingScheduler behind the replica interface."""

    def __init__(self, replica_id, scheduler_factory, role="unified",
                 group=None):
        self.id = replica_id
        self.role = role                 # unified | prefill | decode
        self.group = group               # DisaggGroup for role workers
        self._factory = scheduler_factory
        self.sched = scheduler_factory()
        self.state = UP
        self.death_reason = None
        self.missed_beats = 0
        self.restarts = 0
        self.incarnation = 0       # bumped per restart: entries + token
                                   # sinks record (replica, incarnation)
                                   # so a flapping/revived replica can't
                                   # be double-adopted or double-emit
        self.fence_epoch = 0       # highest router epoch seen (HA)
        self.fenced_calls = 0      # stale-epoch calls rejected
        self.last_health = None
        self._handoff_sink = None
        # per-replica span tracer (serving/trace.py), owned by the
        # REPLICA not the scheduler: a crash drops the scheduler but the
        # dead replica's spans must survive into the merged fleet trace
        # and the flight-recorder dump
        self.tracer = None

    def enable_trace(self, tracer):
        """Router wiring: attach this replica's tracer (survives die/
        restart — fresh schedulers are re-pointed at it)."""
        self.tracer = tracer
        if self.sched is not None:
            self.sched.tracer = tracer
            if self.sched.mem.enabled:
                # memory telemetry rides the replica's tracer too (the
                # pool counter track lands in the fleet trace)
                self.sched.mem.bind(self.sched.metrics, tracer)

    def attach_mem_flight(self, flight):
        """Router wiring: a scheduler built with memory telemetry gets
        the fleet FlightRecorder, so a sustained-pressure episode on
        this replica dumps fleet-correlatable forensics.  Survives
        die/restart (fresh schedulers are re-wired)."""
        self._mem_flight = flight
        if self.sched is not None and self.sched.mem.enabled:
            self.sched.mem.flight = flight

    def attach_comm_flight(self, flight):
        """Router wiring, the compile twin of :meth:`attach_mem_flight`:
        a scheduler running the recompile watchdog dumps steady-state
        signature churn into the FLEET recorder.  The watchdog is
        engine-lifetime (schedulers reuse it), so the wiring survives
        die/restart; re-wired anyway for custom per-scheduler
        instances."""
        self._comm_flight = flight
        wd = None if self.sched is None else self.sched.compile_watchdog
        if wd is not None:
            wd.flight_recorder = flight

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               deadline_s=None, on_token=None, handoff=False,
               trace_ctx=None, sampling=None, seed=None, grammar=None,
               sample_offset=0, tenant=None, adapter=None, epoch=None):
        _fence_check(self, epoch)
        if self.state != UP:
            raise ReplicaKilled(f"{self.id} is {self.state}")
        req = self.sched.submit(prompt, max_new_tokens,
                                eos_token_id=eos_token_id,
                                on_token=on_token, deadline_s=deadline_s,
                                handoff=handoff, trace_ctx=trace_ctx,
                                sampling=sampling, seed=seed,
                                grammar=grammar,
                                sample_offset=sample_offset,
                                tenant=tenant, adapter=adapter)
        req._fence_epoch = epoch
        return req

    def attach(self, prompt, pages, length, first_tok, *, max_new_tokens,
               eos_token_id=None, deadline_s=None, on_token=None,
               trace_ctx=None, sampling=None, seed=None, grammar=None,
               sample_offset=0, tenant=None, adapter=None, epoch=None):
        _fence_check(self, epoch)
        if self.state != UP:
            raise ReplicaKilled(f"{self.id} is {self.state}")
        req = self.sched.attach_handoff(
            prompt, pages, length, first_tok,
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
            on_token=on_token, deadline_s=deadline_s,
            trace_ctx=trace_ctx, sampling=sampling, seed=seed,
            grammar=grammar, sample_offset=sample_offset,
            tenant=tenant, adapter=adapter)
        req._fence_epoch = epoch
        return req

    def fence(self, epoch):
        """Takeover hygiene: raise the fence so stale-epoch calls are
        rejected, and cancel any in-flight request dispatched under an
        older epoch (its tokens belong to a deposed router's sinks,
        which drop them — cancelling reclaims the slots/pages)."""
        epoch = int(epoch)
        self.fence_epoch = max(self.fence_epoch, epoch)
        if self.sched is None:
            return
        self.sched.ha_epoch = self.fence_epoch
        for req in list(self.sched.requests.values()):
            tag = getattr(req, "_fence_epoch", None)
            if tag is None or tag < epoch:
                req.cancel()
                self.sched.ha_fenced += 1

    def set_handoff_sink(self, cb):
        """Router wiring for prefill workers: where finished-prompt KV
        chains go.  Survives :meth:`restart` (the fresh scheduler is
        rewired)."""
        self._handoff_sink = cb
        if self.sched is not None:
            self.sched.on_handoff = cb

    def prefix_match_len(self, tokens):
        """Prefix-aware routing score: how many leading tokens of the
        prompt this replica's radix cache could serve right now."""
        if self.state != UP or self.sched is None or \
                self.sched.prefix_cache is None or len(tokens) < 2:
            return 0
        return self.sched.prefix_cache.prefix_len(tokens,
                                                  limit=len(tokens) - 1)

    def prefix_stats(self):
        pc = None if self.sched is None else self.sched.prefix_cache
        if pc is None:
            return (0, 0, 0)
        return (pc.hits, pc.lookups, pc.tokens_reused)

    def load(self):
        """Routing tie-break: live work items on this replica."""
        if self.sched is None:
            return 0
        s = self.sched
        return (len(s.waiting) + len(s._pending_attach) +
                sum(r is not None for r in s.slot_req))

    def attach_backlog(self):
        """Chains parked at this replica awaiting a slot.  The router's
        soft admission gate (``attach_backlog() < attach_slots()``)
        never parks more chains than the replica has slots — parked
        chains hold pool pages."""
        return 0 if self.sched is None else \
            len(self.sched._pending_attach)

    def attach_slots(self):
        return 0 if self.sched is None else self.sched.num_slots

    # -------------------------------------------------------------- pump
    def has_work(self):
        if self.sched is None:
            return False
        s = self.sched
        return bool(s.waiting) or bool(s._inflight) or \
            bool(s._pending_attach) or \
            any(r is not None for r in s.slot_req)

    def step(self, step_idx, epoch=None):
        """One scheduler iteration.  The ``cluster.replica_kill`` fault
        point fires first — an armed raise here IS the crash: the
        scheduler is dropped wholesale and :class:`ReplicaKilled`
        surfaces to the router, which replays this replica's journal
        entries onto survivors.  An uncontained scheduler exception
        (shared-dispatch failure, per PR-2's containment policy the
        only kind that can escape) is treated identically: one replica
        dies, never the tier."""
        if self.state == DEAD:
            return False
        _fence_check(self, epoch)
        try:
            faults.fire("cluster.replica_kill", step=step_idx,
                        replica=self.id)
        except Exception as e:
            self.die(f"injected kill: {type(e).__name__}: {e}")
            raise ReplicaKilled(self.death_reason) from e
        if not self.has_work():
            return False
        try:
            return self.sched.step()
        except Exception as e:
            self.die(f"uncontained scheduler error: "
                     f"{type(e).__name__}: {e}")
            raise ReplicaKilled(self.death_reason) from e

    def heartbeat(self, epoch=None):
        """Health snapshot, or :class:`ReplicaKilled` — the router's
        death-detection signal."""
        _fence_check(self, epoch)
        if self.state == DEAD:
            raise ReplicaKilled(f"{self.id} dead: {self.death_reason}")
        self.last_health = self.sched.health()
        return self.last_health

    # ----------------------------------------------------- lifecycle
    @staticmethod
    def _reclaim(sched):
        """Return every pool page a discarded scheduler holds — live
        slots, parked handoff chains, AND its refcounted prefix
        cache.  Mandatory when the pool is shared (a disaggregated
        group's pool outlives its workers in-process, unlike the
        per-node HBM it models): pages an abandoned scheduler still
        references would never recycle and the group would march to
        exhaustion one restart at a time."""
        if sched is None:
            return
        try:
            sched._inflight.clear()
            for slot in range(sched.num_slots):
                if sched.kv.slot_page_count(slot):
                    sched.kv.release_slot(slot)
            while sched._pending_attach:
                req = sched._pending_attach.popleft()
                sched.kv.pool.free(req._attach[0])
            if sched.prefix_cache is not None:
                sched.prefix_cache.evict(sched.kv.pool.num_pages)
        except Exception:
            pass   # reclaim is best-effort; the router replays anyway

    def die(self, reason):
        """Crash semantics: all scheduler state is lost; its pool
        pages are reclaimed (see :meth:`_reclaim`).  The tracer is NOT
        scheduler state — the spans recorded up to the crash are
        exactly what the flight recorder exists to keep."""
        if self.state == DEAD:
            return
        self.state = DEAD
        self.death_reason = reason
        if self.tracer is not None:
            self.tracer.instant("replica_death", cat="failover",
                                args={"reason": str(reason)})
        sched, self.sched = self.sched, None
        self._reclaim(sched)

    def begin_drain(self):
        """Rolling-restart entry: refuse new work, keep serving what is
        already accepted (the router stops routing here too)."""
        if self.state == UP:
            self.state = DRAINING
            self.sched.begin_drain(shed_waiting=False)

    def drained(self):
        return not self.has_work()

    def restart(self, term_grace_s=None):
        """Fresh scheduler from the factory (post-drain rolling restart
        or post-death recovery).  ``term_grace_s`` is a no-op here —
        in-process there is nothing to SIGTERM — and honored by
        :class:`ProcessReplica`.  The outgoing scheduler's pages
        (notably its prefix cache — a drained replica holds nothing
        else) are reclaimed first, or a shared pool would leak them on
        every rolling restart."""
        self._reclaim(self.sched)
        self.sched = self._factory()
        if self._handoff_sink is not None:
            self.sched.on_handoff = self._handoff_sink
        if self.tracer is not None:
            self.sched.tracer = self.tracer
            if self.sched.mem.enabled:
                self.sched.mem.bind(self.sched.metrics, self.tracer)
        if getattr(self, "_mem_flight", None) is not None and \
                self.sched.mem.enabled:
            self.sched.mem.flight = self._mem_flight
        if getattr(self, "_comm_flight", None) is not None and \
                self.sched.compile_watchdog is not None:
            self.sched.compile_watchdog.flight_recorder = \
                self._comm_flight
        if self.fence_epoch:
            self.sched.ha_epoch = self.fence_epoch
        self.state = UP
        self.death_reason = None
        self.missed_beats = 0
        self.restarts += 1
        self.incarnation += 1


class _RemoteHandle:
    """Router-visible handle for a request living in a worker process:
    mirrors the scheduler Request surface the router consumes
    (``state`` / ``error`` / ``cancel()``)."""

    __slots__ = ("rid", "state", "error", "on_token", "_replica")

    def __init__(self, rid, on_token, replica):
        self.rid = rid
        self.state = "running"
        self.error = None
        self.on_token = on_token
        self._replica = replica

    def cancel(self):
        # a broken pipe means the worker is dying: swallow it — cancel
        # must stay idempotent/no-raise for callers (router.cancel),
        # and the heartbeat pass will declare the death and replay
        try:
            self._replica._send({"op": "cancel", "rid": self.rid})
        except Exception:
            pass


class ProcessReplica:
    """A worker process behind the replica interface (JSONL protocol —
    see ``cluster/worker.py``).

    Role workers carry real cross-process KV transport: a ``prefill``
    worker gets a dedicated binary KV sidecar fd (``--kv-fd-out``) its
    exported page-chain frames ride OUT on (length-prefixed, never the
    JSONL control wire; a reader thread buffers them here per worker
    rid), and a ``decode`` worker gets one (``--kv-fd-in``) the router
    relays those frames INTO — the worker scatters each chunk on
    arrival and attaches the request once the manifest verifies.
    Prefix routing for process replicas runs on shipped
    ``PrefixCache.fingerprint()`` digests (heartbeat cadence + the
    ``fingerprint`` op), matched router-side by
    :class:`~deepspeed_tpu.serving.prefix_cache.FingerprintMatcher` —
    the wire twin of ``prefix_len`` scoring."""

    def __init__(self, replica_id, *, model="gpt2-tiny", num_slots=3,
                 num_pages=32, page_size=16, max_pages_per_slot=8,
                 prefill_chunk=8, prefix_cache=False, term_grace_s=5.0,
                 hb_timeout_s=60.0, env=None, trace=False,
                 mem_telemetry=False, comm_telemetry=False,
                 kv_dtype=None, role="unified", group=None,
                 tenants=None, lora=None):
        self.id = replica_id
        self.role = role                 # unified | prefill | decode
        self.group = group               # DisaggGroup for role workers
        self.state = UP
        self.death_reason = None
        self.missed_beats = 0
        self.restarts = 0
        self.incarnation = 0
        self.fence_epoch = 0
        self.fenced_calls = 0
        self.last_health = None
        self.term_grace_s = float(term_grace_s)
        self.hb_timeout_s = float(hb_timeout_s)
        self._cfg = dict(model=model, num_slots=num_slots,
                         num_pages=num_pages, page_size=page_size,
                         max_pages_per_slot=max_pages_per_slot,
                         prefill_chunk=prefill_chunk,
                         prefix_cache=prefix_cache, trace=bool(trace),
                         mem_telemetry=bool(mem_telemetry),
                         comm_telemetry=bool(comm_telemetry),
                         kv_dtype=kv_dtype, tenants=tenants, lora=lora)
        self._env = dict(env or {})
        self._handles = {}
        self._next_rid = 0
        self._handoff_sink = None
        self._fp = None              # FingerprintMatcher, once shipped
        # worker-side spans, flushed over the JSONL protocol with each
        # heartbeat (already epoch-µs-serialized by the worker).  Kept
        # on the REPLICA so a SIGKILLed worker's last flushed window
        # survives into the merged fleet trace / flight record — spans
        # between the last flush and the kill die with the process,
        # exactly like the requests the journal replays.
        self.trace_events = deque(maxlen=8192)
        self._spawn()

    def enable_trace(self, tracer=None):
        """Turn on worker-side span tracing (now, and across restarts).
        The optional ``tracer`` argument is accepted for interface
        parity with LocalReplica and ignored — a process replica's
        spans are recorded in the worker and shipped back serialized."""
        if self._cfg["trace"]:
            return
        self._cfg["trace"] = True
        try:
            self._send({"op": "trace", "label": str(self.id)})
        except Exception:
            pass   # dying worker: the restart respawns with --trace

    # --------------------------------------------------------- process
    def _spawn(self):
        cfg = self._cfg
        cmd = [sys.executable, "-m", "deepspeed_tpu.serving.cluster.worker",
               "--model", cfg["model"],
               "--num-slots", str(cfg["num_slots"]),
               "--num-pages", str(cfg["num_pages"]),
               "--page-size", str(cfg["page_size"]),
               "--max-pages-per-slot", str(cfg["max_pages_per_slot"]),
               "--prefill-chunk", str(cfg["prefill_chunk"])]
        if cfg.get("kv_dtype"):
            # quantized (or explicitly float) paged-KV pools survive a
            # worker restart: the dtype is part of the replica config
            cmd += ["--kv-dtype", str(cfg["kv_dtype"])]
        if cfg["prefix_cache"]:
            cmd.append("--prefix-cache")
        if cfg["mem_telemetry"]:
            cmd.append("--mem-telemetry")
        if cfg.get("comm_telemetry"):
            cmd.append("--comm-telemetry")
        if cfg.get("tenants"):
            # tenancy survives restarts: the respawned worker rebuilds
            # the identical registry (same adapter ids/namespaces)
            cmd += ["--tenants", str(cfg["tenants"])]
        if cfg.get("lora"):
            cmd += ["--lora", str(cfg["lora"])]
        if cfg["trace"]:
            cmd += ["--trace", "--trace-label", str(self.id)]
        # KV sidecar plumbing for role workers: a dedicated binary fd
        # pair per direction, separate from the JSONL control pipes —
        # page-chain payloads never ride (or block) the control wire
        self._wire_frames = {}       # worker rid -> [(header, raw)...]
        self._wire_lock = threading.Lock()
        self._wire_pending = set()   # wire-attach rids not yet adopted
        self._kv_w = None            # decode: parent -> worker frames
        self._kv_r = None            # prefill: worker -> parent frames
        pass_fds, child_fds = (), []
        if self.role == "prefill":
            r_fd, w_fd = os.pipe()
            cmd += ["--role", "prefill", "--kv-fd-out", str(w_fd)]
            pass_fds, child_fds = (w_fd,), [w_fd]
            self._kv_r = os.fdopen(r_fd, "rb")
        elif self.role == "decode":
            r_fd, w_fd = os.pipe()
            cmd += ["--role", "decode", "--kv-fd-in", str(r_fd)]
            pass_fds, child_fds = (r_fd,), [r_fd]
            self._kv_w = os.fdopen(w_fd, "wb")
        try:
            # forward PRNG semantics: seeded init only yields the SAME
            # params in the child when threefry partitioning matches
            import jax
            if jax.config.jax_threefry_partitionable:
                cmd.append("--threefry-partitionable")
        except Exception:
            pass
        env = os.environ.copy()
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the child must import THIS deepspeed_tpu however the parent
        # got it (site-packages, cwd, or an explicit sys.path entry —
        # the env of a driver script run from anywhere): the package's
        # import root rides PYTHONPATH, it is not inherited through -m
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        # the elastic-agent grace contract: the worker's SIGTERM drain
        # sizes itself against the budget the supervisor will enforce
        env["DS_PREEMPTION_GRACE_S"] = str(self.term_grace_s)
        env.update(self._env)
        self._proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, env=env,
            pass_fds=pass_fds)
        for fd in child_fds:
            os.close(fd)    # the child owns its end now
        self._events = deque()
        self._events_lock = threading.Lock()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True)
        self._reader.start()
        if self._kv_r is not None:
            self._kv_reader = threading.Thread(target=self._kv_read_loop,
                                               daemon=True)
            self._kv_reader.start()
        self._last_hb = time.monotonic()
        self._ready = False

    def _kv_read_loop(self):
        """Prefill sidecar reader: buffer exported chain frames per
        worker rid until the router relays (or drops) them.  Frames
        are decoded once here — the relay rewrites only the rid."""
        from deepspeed_tpu.serving.cluster import transport as tp
        stream = self._kv_r
        try:
            while True:
                frame = tp.read_frame(stream)
                if frame is None:
                    return           # EOF: worker died or sidecar closed
                header, raw = frame
                with self._wire_lock:
                    self._wire_frames.setdefault(
                        header["rid"], []).append((header, raw))
        except Exception:
            pass

    def _read_loop(self):
        proc = self._proc
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                with self._events_lock:
                    self._events.append(ev)
        except Exception:
            pass

    def _send(self, op):
        try:
            self._proc.stdin.write(json.dumps(op) + "\n")
            self._proc.stdin.flush()
        except Exception as e:
            raise ReplicaKilled(f"{self.id} pipe broken: {e}") from e

    def wait_ready(self, timeout_s=300.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self._pump_events()
            if self._ready:
                return True
            if self._proc.poll() is not None:
                raise ReplicaKilled(
                    f"{self.id} exited rc={self._proc.returncode} "
                    "before ready")
            time.sleep(0.05)
        raise TimeoutError(f"{self.id} not ready in {timeout_s}s")

    def _pump_events(self):
        while True:
            with self._events_lock:
                if not self._events:
                    return
                ev = self._events.popleft()
            kind = ev.get("ev")
            if kind == "ready":
                self._ready = True
                self._last_hb = time.monotonic()
            elif kind == "hb":
                self._last_hb = time.monotonic()
                self.last_health = ev.get("health")
                if ev.get("fp") is not None:
                    self._absorb_fp(ev["fp"])
            elif kind == "fp":
                self._absorb_fp(ev)
            elif kind == "handoff":
                # prefill worker finished a handoff prompt: its frames
                # are on (or arriving over) the KV sidecar; hand the
                # metadata to the router's wire sink
                rid = ev.get("rid")
                h = self._handles.pop(rid, None)
                if h is None or self._handoff_sink is None:
                    self.drop_wire_frames(rid)
                elif h.state in ("waiting", "prefill", "running"):
                    h.state = "handoff"
                    self._handoff_sink(
                        h, [int(t) for t in ev["prompt"]],
                        int(ev["length"]), int(ev["first_tok"]),
                        ev["manifest"])
            elif kind == "attached":
                # decode worker verified the manifest and adopted the
                # chain: the wire attach left the pending (backlog) set
                self._wire_pending.discard(ev.get("rid"))
            elif kind == "tok":
                h = self._handles.get(ev.get("rid"))
                if h is not None and h.on_token is not None:
                    h.on_token(h, int(ev["t"]))
            elif kind == "done":
                rid = ev.get("rid")
                self._wire_pending.discard(rid)
                h = self._handles.pop(rid, None)
                if h is not None:
                    h.state = ev.get("status", "finished")
                    h.error = ev.get("error")
            elif kind == "spans":
                self.trace_events.extend(ev.get("spans") or [])

    def _absorb_fp(self, fp):
        from deepspeed_tpu.serving.prefix_cache import FingerprintMatcher
        if self._fp is None:
            self._fp = FingerprintMatcher()
        self._fp.update(fp)

    # ------------------------------------------------------------ intake
    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               deadline_s=None, on_token=None, handoff=False,
               trace_ctx=None, sampling=None, seed=None, grammar=None,
               sample_offset=0, tenant=None, adapter=None, epoch=None):
        if handoff and self.role != "prefill":
            raise ValueError(
                "handoff submits require a prefill-role worker "
                "(its KV sidecar is the chain's way out)")
        _fence_check(self, epoch)
        if self.state != UP:
            raise ReplicaKilled(f"{self.id} is {self.state}")
        rid = f"w{self._next_rid}"
        self._next_rid += 1
        handle = _RemoteHandle(rid, on_token, self)
        self._handles[rid] = handle
        op = {"op": "submit", "rid": rid,
              "prompt": [int(t) for t in prompt],
              "max_new_tokens": int(max_new_tokens),
              "eos_token_id": eos_token_id,
              "deadline_s": deadline_s}
        if handoff:
            op["handoff"] = True
        # decoding-policy wire fields are omitted when default so old
        # workers keep accepting the protocol
        if sampling:
            op["sampling"] = dict(sampling)
        if seed:
            op["seed"] = int(seed)
        if grammar:
            op["grammar"] = dict(grammar)
        if sample_offset:
            op["sample_offset"] = int(sample_offset)
        # tenancy fields are omitted when absent for the same
        # wire-compat reason
        if tenant is not None:
            op["tenant"] = str(tenant)
        if adapter is not None:
            op["adapter"] = str(adapter)
        if epoch is not None:
            # the epoch rides the wire too: even if a zombie router
            # slips past the in-process fence (it cannot here, but a
            # network transport could reorder), the WORKER rejects the
            # stale dispatch — defense in depth at the protocol layer
            op["epoch"] = int(epoch)
        if trace_ctx is not None:
            # the trace id crosses the process boundary with the
            # request, so worker-side spans carry the journal rid
            op["trace"] = trace_ctx
        self._send(op)
        return handle

    def set_handoff_sink(self, cb):
        """Router wiring for prefill workers: where finished-prompt
        handoff metadata goes (the frames ride the KV sidecar)."""
        self._handoff_sink = cb

    def prefix_match_len(self, tokens):
        """Prefix-aware routing score from the worker's last shipped
        fingerprint: the wire twin of ``prefix_len`` (page-granular by
        construction — a digest set can't represent the in-process
        copy-on-write partial, and routing doesn't need it)."""
        if self.state != UP or self._fp is None or len(tokens) < 2:
            return 0
        return self._fp.match_len(tokens, limit=len(tokens) - 1)

    def prefix_stats(self):
        if self._fp is None:
            return (0, 0, 0)
        return (self._fp.hits, self._fp.lookups, self._fp.tokens_reused)

    def request_fingerprint(self):
        """Ask the worker for a fresh prefix fingerprint now (it also
        rides every heartbeat); the reply lands via ``_pump_events``."""
        try:
            self._send({"op": "fingerprint"})
        except Exception:
            pass   # dying worker: heartbeats will declare the death

    def load(self):
        return len(self._handles)

    def attach_backlog(self):
        """Wire attaches dispatched but not yet adopted worker-side —
        each holds a freshly allocated destination chain, so the
        router's admission gate bounds them by slot count exactly like
        an in-process replica's parked chains."""
        return len(self._wire_pending)

    def attach_slots(self):
        return int(self._cfg["num_slots"])

    # ------------------------------------------------------ KV sidecar
    def wire_frames_ready(self, rid, total):
        """True once every frame of a chain export is host-buffered."""
        with self._wire_lock:
            return len(self._wire_frames.get(rid, ())) >= int(total)

    def take_wire_frames(self, rid):
        with self._wire_lock:
            return self._wire_frames.pop(rid, [])

    def drop_wire_frames(self, rid):
        with self._wire_lock:
            self._wire_frames.pop(rid, None)

    def begin_wire_attach(self, prompt, length, first_tok, *, manifest,
                          max_new_tokens, eos_token_id=None,
                          deadline_s=None, on_token=None, trace_ctx=None,
                          sampling=None, seed=None, grammar=None,
                          sample_offset=0, tenant=None, adapter=None,
                          epoch=None):
        """Dispatch the decode side of a cross-process handoff: the
        worker allocates the destination chain, scatters relayed
        frames as they land, and adopts the request once the manifest
        verifies (chunk count, exact bytes, running digest).  Frames
        follow via :meth:`send_wire_chunk`."""
        _fence_check(self, epoch)
        if self.state != UP:
            raise ReplicaKilled(f"{self.id} is {self.state}")
        if self._kv_w is None:
            raise ReplicaKilled(f"{self.id} has no KV sidecar "
                                "(not a decode-role worker)")
        rid = f"w{self._next_rid}"
        self._next_rid += 1
        handle = _RemoteHandle(rid, on_token, self)
        self._handles[rid] = handle
        self._wire_pending.add(rid)
        op = {"op": "attach", "rid": rid,
              "prompt": [int(t) for t in prompt],
              "length": int(length), "first_tok": int(first_tok),
              "manifest": dict(manifest),
              "max_new_tokens": int(max_new_tokens),
              "eos_token_id": eos_token_id,
              "deadline_s": deadline_s}
        if sampling:
            op["sampling"] = dict(sampling)
        if seed:
            op["seed"] = int(seed)
        if grammar:
            op["grammar"] = dict(grammar)
        if sample_offset:
            op["sample_offset"] = int(sample_offset)
        if tenant is not None:
            op["tenant"] = str(tenant)
        if adapter is not None:
            op["adapter"] = str(adapter)
        if epoch is not None:
            op["epoch"] = int(epoch)
        if trace_ctx is not None:
            op["trace"] = trace_ctx
        try:
            self._send(op)
        except ReplicaKilled:
            self._wire_pending.discard(rid)
            self._handles.pop(rid, None)
            raise
        return handle

    def send_wire_chunk(self, rid, frame):
        """Relay one buffered frame into the decode worker's sidecar,
        rewriting the source worker's rid to the decode-side one."""
        from deepspeed_tpu.serving.cluster import transport as tp
        header, raw = frame
        hdr = dict(header)
        hdr["rid"] = rid
        hb = json.dumps(hdr, separators=(",", ":")).encode()
        buf = tp._MAGIC + tp._HDR.pack(len(hb), len(raw)) + hb + raw
        try:
            self._kv_w.write(buf)
            self._kv_w.flush()
        except Exception as e:
            raise ReplicaKilled(
                f"{self.id} KV sidecar broken: {e}") from e

    def abort_wire_attach(self, rid):
        """Tear down a dispatched wire attach (mid-transfer fault):
        the worker frees the partial destination chain.  No-raise —
        a dead worker's pages died with its pool."""
        self._wire_pending.discard(rid)
        self._handles.pop(rid, None)
        try:
            self._send({"op": "attach_abort", "rid": rid})
        except Exception:
            pass

    # -------------------------------------------------------------- pump
    def has_work(self):
        """Always False: the actual work runs in the child process, so
        the router's pump has nothing to drive here and may idle-sleep
        between event polls instead of busy-spinning CPU away from the
        worker."""
        return False

    def fence(self, epoch):
        """Raise the local fence AND ship it to the worker, which
        cancels in-flight requests dispatched under older epochs."""
        epoch = int(epoch)
        self.fence_epoch = max(self.fence_epoch, epoch)
        try:
            self._send({"op": "fence", "epoch": self.fence_epoch})
        except Exception:
            pass   # dying worker: heartbeats will declare the death

    def step(self, step_idx, epoch=None):
        if self.state == DEAD:
            return False
        _fence_check(self, epoch)
        try:
            faults.fire("cluster.replica_kill", step=step_idx,
                        replica=self.id)
        except Exception as e:
            self.kill()
            self.die(f"injected kill: {type(e).__name__}: {e}")
            raise ReplicaKilled(self.death_reason) from e
        self._pump_events()
        return bool(self._handles)

    def heartbeat(self, epoch=None):
        _fence_check(self, epoch)
        if self.state == DEAD:
            raise ReplicaKilled(f"{self.id} dead: {self.death_reason}")
        self._pump_events()
        if self._proc.poll() is not None:
            raise ReplicaKilled(
                f"{self.id} exited rc={self._proc.returncode}")
        if time.monotonic() - self._last_hb > self.hb_timeout_s:
            raise ReplicaKilled(
                f"{self.id} silent for > {self.hb_timeout_s}s")
        return self.last_health

    # ----------------------------------------------------- lifecycle
    def kill(self):
        """The real thing: SIGKILL, no goodbye."""
        try:
            if self._proc.poll() is None:
                os.kill(self._proc.pid, signal.SIGKILL)
        except OSError:
            pass

    def _close_kv(self):
        """Close this incarnation's sidecar ends (buffered frames for
        unfinished exports die with them — the journal replays)."""
        for stream in (self._kv_w, self._kv_r):
            if stream is not None:
                try:
                    stream.close()
                except Exception:
                    pass
        self._kv_w = self._kv_r = None
        with self._wire_lock:
            self._wire_frames.clear()
        self._wire_pending.clear()

    def die(self, reason):
        if self.state == DEAD:
            return
        self.state = DEAD
        self.death_reason = reason
        self.kill()
        self._handles.clear()
        self._close_kv()

    def begin_drain(self):
        if self.state != UP:
            return
        self.state = DRAINING
        try:
            self._send({"op": "drain"})
        except Exception:
            # dead pipe: the drain is moot — heartbeats will declare
            # the death; drain_all/rolling_restart must keep going for
            # the surviving replicas instead of aborting mid-shutdown
            pass

    def drained(self):
        self._pump_events()
        return not self._handles

    def restart(self, term_grace_s=None):
        """Elastic-agent restart contract: SIGTERM first (the worker
        drains within ``DS_PREEMPTION_GRACE_S``), SIGKILL only after
        the grace budget, then respawn."""
        grace = self.term_grace_s if term_grace_s is None \
            else float(term_grace_s)
        if self._proc.poll() is None:
            self._proc.terminate()
            deadline = time.monotonic() + grace
            while self._proc.poll() is None and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            if self._proc.poll() is None:
                self._proc.kill()
        try:
            self._proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass
        self._handles.clear()
        self._close_kv()
        self._spawn()
        self.wait_ready()
        if self.fence_epoch:
            self.fence(self.fence_epoch)
        self.state = UP
        self.death_reason = None
        self.missed_beats = 0
        self.restarts += 1
        self.incarnation += 1
