"""Paged KV-cache management (host side).

The design is vLLM/PagedAttention (SOSP '23) adapted to the TPU serving
stack: device memory holds ONE preallocated pool of fixed-size KV pages
per layer (``models/*.init_paged_kv_cache``); which pages belong to which
sequence is pure host bookkeeping — a free list plus a per-slot page
table.  Allocation granularity is a page (``page_size`` tokens), so the
worst-case internal fragmentation is ``page_size - 1`` tokens per live
sequence and external fragmentation is zero by construction.

The device never sees this class: the scheduler passes ``table`` /
lengths as small int32 inputs into the fixed-shape jitted primitives
(``InferenceEngine.prefill_into_slots`` / ``decode_multi``), so request
churn never changes a jit signature (fused decode compiles once per
horizon bucket, never per churn).

MESH-AGNOSTIC BY CONTRACT (sharded multi-chip serving,
``serving/sharding.py``): a page id names the same page on every
device — the pools shard their kv-head dim over the ``model`` mesh
axis, so each device holds its *shard of every page*, and the page dim
itself is never partitioned.  Nothing in this module may ever consult
the mesh; allocation, refcounts, growth, release, rollback
(``truncate_slot``) and donation (``take_slot_pages``) behave
identically at every topology.
"""

import numpy as np


def default_page_size():
    """Backend-dependent page-size default, shared by every pool builder
    (ServingScheduler, bin/ds_serve's draft pool): the paged Pallas
    decode kernel needs 128-multiple pages (TPU lane tiling; anything
    smaller silently drops every decode step to the gather fallback),
    while off-TPU the gather fallback runs regardless, so small pages
    (finer-grained pool sharing) are the better default there."""
    import jax
    return 128 if jax.default_backend() == "tpu" else 16


class PagePoolExhausted(RuntimeError):
    """Raised when a required allocation cannot be satisfied even after
    the caller's eviction policy ran out of victims."""


class PagePool:
    """Fixed pool of fixed-size cache pages with a free list, per-page
    reference counts and allocation accounting (the reference
    counterpart is vLLM's BlockAllocator).

    Reference counting is what makes cross-request KV sharing free:
    ``allocate`` hands out pages at refcount 1, ``share`` adds a holder
    (a second slot mapping the same physical page read-only, or the
    prefix cache retaining a donated page), and ``free`` drops one
    holder — the page only returns to the free list when its last
    holder lets go.  Non-sharing callers see the PR-1 semantics
    unchanged (allocate -> refcount 1, free -> back on the list)."""

    def __init__(self, num_pages, page_size):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently freed pages are re-used first (their
        # pool slices are most likely still warm in cache hierarchies)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._refs = {}              # page id -> holder count (>= 1)
        self.peak_in_use = 0
        self.total_allocs = 0        # pages taken off the free list
        self.total_frees = 0         # pages returned to the free list
        self.total_shares = 0        # extra holders added via share()
        # memory-telemetry event hook: observer(kind, n_pages) with kind
        # in {"alloc", "free", "share"}, called AFTER the books update.
        # None by default — the off path costs one attribute load and a
        # falsy check per pool operation (pool ops are page-granular,
        # never per-token), preserving the zero-cost-when-off contract
        self.observer = None

    @property
    def free_pages(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.num_pages - len(self._free)

    def can_allocate(self, n):
        return n <= len(self._free)

    def ref_count(self, page):
        """Current holder count of an allocated page (0 when free)."""
        return self._refs.get(page, 0)

    def allocate(self, n):
        """Take ``n`` pages off the free list at refcount 1; raises
        PagePoolExhausted if fewer are free (callers gate with
        can_allocate / evict)."""
        if n > len(self._free):
            raise PagePoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({self.pages_in_use}/{self.num_pages} in use)")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        self.total_allocs += n
        self.peak_in_use = max(self.peak_in_use, self.pages_in_use)
        if self.observer is not None:
            self.observer("alloc", n)
        return pages

    def share(self, pages):
        """Add one holder to each already-allocated page (read-only
        prefix sharing / prefix-cache retention).  Sharing a free or
        foreign page id raises :class:`ValueError` — an unallocated
        page gaining a phantom holder would never recycle (a leak) or,
        worse, recycle under a reader (regression-tested in
        tests/unit/test_mem_telemetry.py)."""
        # validate the WHOLE list before mutating anything: a mixed
        # good/bad list must reject atomically, or the caller — who
        # sees only the exception — would be left with phantom holders
        # it cannot account for
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"cannot share page {p}: not currently allocated "
                    f"(free or foreign id; pool has {self.num_pages} "
                    "pages)")
        for p in pages:
            self._refs[p] += 1
        self.total_shares += len(pages)
        if self.observer is not None:
            self.observer("share", len(pages))

    def free(self, pages):
        """Drop one holder per page; a page returns to the free list
        only when its last holder releases it.  Freeing a page that is
        not allocated — a double free, or a foreign id — raises
        :class:`ValueError` instead of silently corrupting the free
        list (a duplicate free-list entry would hand the same page to
        two owners on the next allocate)."""
        # two-pass like share(): reject the whole call before touching
        # the books, so a bad id cannot leave a half-applied free
        # behind the ValueError.  A page listed twice is legal while
        # its refcount covers both drops — count multiplicity here.
        need = {}
        for p in pages:
            need[p] = need.get(p, 0) + 1
        for p, n in need.items():
            if self._refs.get(p, 0) < n:
                raise ValueError(
                    f"cannot free page {p} x{n}: "
                    f"{self._refs.get(p, 0)} holder(s) "
                    f"(double free or foreign id; pool has "
                    f"{self.num_pages} pages)")
        freed = 0
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)
                self.total_frees += 1
                freed += 1
        if self.observer is not None and pages:
            self.observer("free", freed)

    def utilization(self):
        return self.pages_in_use / self.num_pages

    def pages_for_tokens(self, num_tokens):
        """Pages needed to hold ``num_tokens`` cache entries."""
        return -(-int(num_tokens) // self.page_size)


class PagedKVManager:
    """Per-slot page tables over one PagePool.

    ``table`` is the [num_slots, max_pages_per_slot] int32 array handed
    to the jitted decode/prefill primitives each step.  Unassigned
    entries stay 0 — a *valid* page id, because gathers must stay in
    bounds; the attention mask (driven by lengths) hides them.
    """

    def __init__(self, num_pages, page_size, num_slots, max_pages_per_slot,
                 pool=None):
        # ``pool=`` shares one PagePool between several managers: the
        # disaggregated serving tier runs a prefill worker and a decode
        # worker as separate schedulers (separate slot tables) over ONE
        # physical page pool, so a prefill slot's chain can transfer to
        # a decode slot without copying a byte of KV
        if pool is None:
            pool = PagePool(num_pages, page_size)
        elif pool.num_pages != int(num_pages) or \
                pool.page_size != int(page_size):
            raise ValueError(
                f"shared pool is {pool.num_pages}x{pool.page_size}, "
                f"manager wants {num_pages}x{page_size}")
        self.pool = pool
        self.num_slots = int(num_slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        self.table = np.zeros((num_slots, max_pages_per_slot), np.int32)
        self._slot_pages = [[] for _ in range(num_slots)]

    @property
    def page_size(self):
        return self.pool.page_size

    def max_tokens_per_slot(self):
        return self.max_pages_per_slot * self.pool.page_size

    def slot_page_count(self, slot):
        return len(self._slot_pages[slot])

    def pages_needed(self, slot, target_len):
        """Additional pages ``slot`` must allocate to hold positions
        < target_len (0 when already covered).  The serving scheduler's
        horizon pre-reservation sums this across running slots to decide
        whether a fused multi-step decode fits in free pages before
        dispatching it."""
        return max(0, self.pool.pages_for_tokens(target_len) -
                   len(self._slot_pages[slot]))

    def ensure_capacity(self, slot, target_len):
        """Grow ``slot``'s table until positions < target_len are
        writable. Returns True on success; False when the pool is out of
        pages (caller decides eviction).  Raises when target_len exceeds
        the per-slot table (a config error, not a transient)."""
        needed = self.pool.pages_for_tokens(target_len)
        if needed > self.max_pages_per_slot:
            raise ValueError(
                f"sequence of {target_len} tokens needs {needed} pages > "
                f"max_pages_per_slot={self.max_pages_per_slot}")
        have = len(self._slot_pages[slot])
        if needed <= have:
            return True
        if not self.pool.can_allocate(needed - have):
            return False
        new = self.pool.allocate(needed - have)
        for i, p in enumerate(new):
            self.table[slot, have + i] = p
        self._slot_pages[slot].extend(new)
        return True

    def attach_prefix(self, slot, pages):
        """Map a cached page chain read-only into an EMPTY slot's table
        (prefix-cache hit): each page gains one holder — the slot — on
        top of the cache's own reference, so neither a slot release nor
        a cache eviction alone can recycle a page the other still needs.
        The slot must never write positions below the attached boundary
        (``len(pages) * page_size`` tokens); the scheduler guarantees
        this by resuming prefill/decode at that boundary."""
        if self._slot_pages[slot]:
            raise ValueError(
                f"slot {slot} already holds pages; prefix attach must "
                "seed an empty slot")
        if len(pages) > self.max_pages_per_slot:
            raise ValueError(
                f"prefix of {len(pages)} pages > max_pages_per_slot="
                f"{self.max_pages_per_slot}")
        self.pool.share(pages)
        for i, p in enumerate(pages):
            self.table[slot, i] = p
        self._slot_pages[slot] = list(pages)

    def adopt_chain(self, slot, pages):
        """Seed an EMPTY slot with an already-owned page chain (the
        prefill->decode KV handoff: a prefill worker's
        ``take_slot_pages`` detached the chain with its pool references
        intact, and adoption transfers that ownership to this slot —
        unlike :meth:`attach_prefix`, NO new holder is added, because
        the chain changes hands rather than gaining a reader)."""
        if self._slot_pages[slot]:
            raise ValueError(
                f"slot {slot} already holds pages; a handoff chain must "
                "seed an empty slot")
        if len(pages) > self.max_pages_per_slot:
            raise ValueError(
                f"handoff chain of {len(pages)} pages > "
                f"max_pages_per_slot={self.max_pages_per_slot}")
        for i, p in enumerate(pages):
            self.table[slot, i] = p
        self._slot_pages[slot] = list(pages)

    def adopt_page(self, slot, page):
        """Append an already-allocated page to a slot's chain (the
        copy-on-write private copy of a partially matched cached page:
        allocated fresh, filled by the engine's page-copy primitive,
        then owned by the slot like any grown page)."""
        have = len(self._slot_pages[slot])
        if have >= self.max_pages_per_slot:
            raise ValueError(
                f"slot {slot} page chain full "
                f"(max_pages_per_slot={self.max_pages_per_slot})")
        self.table[slot, have] = page
        self._slot_pages[slot].append(page)

    def truncate_slot(self, slot, new_len):
        """Rewind ``slot`` to ``new_len`` tokens (speculative-decode KV
        rollback): pages that fall ENTIRELY past the new boundary leave
        the slot's chain and drop one holder each (``pool.free`` — a
        page the prefix cache or another slot still references survives
        under its remaining holders; only refcount-0 pages recycle).
        The boundary page keeps its stale tail: positions >= new_len
        are overwritten before any later gather can read them, or
        masked out by the attention's length-driven validity mask.
        Returns the number of page references released."""
        keep = self.pool.pages_for_tokens(new_len)
        pages = self._slot_pages[slot]
        if keep >= len(pages):
            return 0
        drop = pages[keep:]
        del pages[keep:]
        self.table[slot, keep:keep + len(drop)] = 0
        self.pool.free(drop)
        return len(drop)

    def take_slot_pages(self, slot):
        """Detach and return a slot's page chain WITHOUT releasing the
        pool references (retirement donating pages to the prefix cache:
        ownership of each page's reference transfers to the caller, who
        either hands it to the cache or frees it)."""
        pages = self._slot_pages[slot]
        self._slot_pages[slot] = []
        self.table[slot, :] = 0
        return pages

    def release_slot(self, slot):
        """Drop the slot's hold on all of its pages (sequence retired or
        preempted); pages shared with the prefix cache stay allocated
        under the cache's reference."""
        pages = self._slot_pages[slot]
        self.pool.free(pages)
        self._slot_pages[slot] = []
        self.table[slot, :] = 0
        return len(pages)

    def utilization(self):
        return self.pool.utilization()
