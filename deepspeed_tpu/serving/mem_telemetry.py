"""HBM capacity observability: page-pool attribution, per-request
memory accounting, pressure forensics, and the refcount invariant
auditor.

This is the MEMORY half of the observability stack, mirroring how the
tracing tier (PR 8/9) covers the TIME half.  Page capacity is the
currency of every serving mechanism — horizons shrink, the prefix
cache drains, spec-K collapses, and admissions shed under pool
pressure — and this module makes every page's lifecycle visible:

* **Attribution** (:func:`classify`) — every page of the pool is
  classified at all times into the page-state taxonomy
  ``{slot, prefix_shared, prefix_sole, handoff, draft, free}``
  (plus ``unattributed`` for shared-pool peers, 0 standalone).  The
  split is **conservation-exact**: the main-pool states always sum to
  ``num_pages`` (the draft pool is a physically separate pool and
  conserves against its own ``draft_num_pages``).  Exported per step as
  ``serving/mem/*`` monitor gauges, as a Perfetto *counter track*
  (``"C"`` events merged into the Chrome trace next to the PR-8 spans),
  and in ``health()`` / the Prometheus exposition with per-device byte
  figures derived from the existing ``pool_bytes_per_device``.

* **Per-request accounting** — each request's pages-held high-water
  mark (``Request.pages_hwm``) and page-seconds integral
  (``Request.page_seconds``): the unit the serving autotuner's cost
  model and future per-tenant quotas bill in.  Reported in ds_serve
  rows and ``summary()``.

* **Pressure forensics** — every capacity decision the scheduler makes
  (slot growth, horizon pre-reservation shrink, spec-K shrink, chained
  dispatch reclaim, admission blocking) records a *causal chain*:
  trigger -> pages needed -> cache pages drained -> victim evicted /
  horizon shrunk / request shed.  Chains land in a bounded
  :class:`PressureLog` ring, as tracer instants, and as
  ``serving/mem/pressure`` events.  A **sustained-pressure episode**
  (free fraction below ``pressure_threshold`` for ``pressure_steps``
  consecutive steps) fires once per episode: it triggers the attached
  :class:`~deepspeed_tpu.tracing.FlightRecorder` with a pool snapshot,
  the recent pressure chains, and the live request/trace rids the
  journal correlates on.

* **Refcount invariant auditor** (:func:`audit_pool`) — cross-checks
  the pool's refcounts against every known holder (slot page tables,
  the prefix-cache trie, parked/in-flight handoff chains) and raises
  :class:`AuditError` on a leak, double-free hazard, or orphan table
  entry — turning the class of bug PR-7's review caught by hand (the
  rolling-restart page leak) into a machine-checked invariant.  Opt-in
  on the scheduler via ``audit_every=N`` (barrier steps) and on the
  cluster via ``ClusterRouter.audit()`` (which sees ALL sharers of a
  disaggregated pool, including router-held handoff packets).

**Zero-cost-when-off.**  Telemetry off is the shared :data:`NULL_MEM`
singleton, exactly like ``NULL_TRACER``: every scheduler call site pays
one attribute load and a falsy check, no device op, no new jit
signature — tokens and compile counts are byte-identical (pinned by
``tests/unit/test_mem_telemetry.py``).  Everything here is pure host
bookkeeping over the host-side page tables; like the page manager it
is mesh-agnostic by contract (page ids are global; only byte figures
consult the recorded topology snapshot).
"""

import time
from collections import deque

PAGE_STATES = ("slot", "prefix_shared", "prefix_sole", "handoff",
               "draft", "free")


class AuditError(RuntimeError):
    """The refcount auditor found a leak / double-free hazard / orphan."""


# ------------------------------------------------------------- auditor

def audit_pool(pool, *, managers=(), caches=(), chains=(), exact=True,
               label="pool", raise_on_error=True):
    """Cross-check ``pool``'s refcounts against every known holder.

    ``managers`` are :class:`PagedKVManager`\\ s over the pool (their
    slot chains each hold one reference per page), ``caches`` are
    :class:`PrefixCache`\\ s (one reference per trie node), ``chains``
    are detached-but-owned page lists in flight (parked
    ``attach_handoff`` chains, router handoff packets — each holds one
    reference per page).  With ``exact=True`` the holder census must
    match the refcounts EXACTLY; ``exact=False`` (a shared pool audited
    from one scheduler that cannot see its peers) skips the
    leaked-reference direction and checks only structural integrity +
    the double-free direction.

    Violations detected:

    * **free-list corruption** — duplicate/out-of-range ids, a page
      both free and allocated, free+allocated != num_pages;
    * **orphan** — a table/trie/chain references a FREE page
      (use-after-free: the next allocate hands it to someone else);
    * **double-free hazard** — more known holders than refcounts (one
      ``free`` by any holder recycles a page others still read);
    * **leak** — more refcounts than known holders (pages that can
      never recycle; the rolling-restart bug class).

    Returns a report dict; raises :class:`AuditError` listing every
    violation when ``raise_on_error`` (the default)."""
    errors = []
    free = pool._free
    free_set = set(free)
    if len(free_set) != len(free):
        errors.append(f"{label}: duplicate page ids on the free list")
    bad = [p for p in free_set if not (0 <= p < pool.num_pages)]
    if bad:
        errors.append(f"{label}: out-of-range free pages {sorted(bad)[:8]}")
    both = free_set & set(pool._refs)
    if both:
        errors.append(f"{label}: pages both free and allocated "
                      f"{sorted(both)[:8]}")
    if len(free) + len(pool._refs) != pool.num_pages:
        errors.append(
            f"{label}: free({len(free)}) + allocated({len(pool._refs)}) "
            f"!= num_pages({pool.num_pages})")
    holders = {}                      # page -> [who, ...]

    def hold(page, who):
        holders.setdefault(int(page), []).append(who)

    for i, mgr in enumerate(managers):
        for slot, pages in enumerate(mgr._slot_pages):
            for p in pages:
                hold(p, f"manager{i}/slot{slot}")
    for i, cache in enumerate(caches):
        if cache is None:
            continue
        for p in cache.iter_pages():
            hold(p, f"cache{i}")
    for i, chain in enumerate(chains):
        for p in chain:
            hold(p, f"chain{i}")
    for p, who in holders.items():
        actual = pool.ref_count(p)
        if actual == 0:
            errors.append(
                f"{label}: page {p} referenced by {who} but FREE "
                "(orphan table entry / double-free)")
        elif actual < len(who):
            errors.append(
                f"{label}: page {p} has {len(who)} holders {who} but "
                f"refcount {actual} (missing share -> double-free hazard)")
    if exact:
        for p, rc in pool._refs.items():
            known = len(holders.get(p, ()))
            if rc > known:
                errors.append(
                    f"{label}: page {p} refcount {rc} > {known} known "
                    "holder(s) (leaked reference)")
    report = {"label": label, "errors": errors,
              "pages_checked": pool.num_pages,
              "holders": sum(len(v) for v in holders.values()),
              "ok": not errors}
    if errors and raise_on_error:
        raise AuditError(
            f"page-pool audit failed ({len(errors)} violation(s)):\n  "
            + "\n  ".join(errors))
    return report


# -------------------------------------------------------- attribution

def classify(sched):
    """Classify every page of ``sched``'s pool into the page-state
    taxonomy.  Conservation-exact by construction:
    ``slot + prefix_shared + prefix_sole + handoff + unattributed +
    free == num_pages``.  ``unattributed`` is pages a shared pool's
    PEER schedulers hold (always 0 for a standalone scheduler — a
    nonzero value there is a leak, which ``audit()`` flags).  The
    draft-model pool is physically separate, so ``draft`` /
    ``draft_free`` conserve against ``draft_num_pages`` instead.
    Pure host sweep over the page tables: O(num_pages + slots).

    Thread-tolerant like ``SpanTracer.serialized``: a /metrics scrape
    thread may sweep while the serving loop mutates the dicts/trie —
    retry the (CPython-atomic in practice) snapshot a few times rather
    than let a mutated-during-iteration RuntimeError turn every busy
    scrape into a 500; the last resort is a degraded-but-conserving
    split (everything allocated reported unattributed)."""
    pool = sched.kv.pool
    for _ in range(4):
        try:
            return _classify_once(sched, pool)
        except RuntimeError:
            continue
    counts = dict.fromkeys(PAGE_STATES, 0)
    counts["free"] = pool.free_pages
    counts["unattributed"] = pool.num_pages - counts["free"]
    return counts


def _classify_once(sched, pool):
    trie = set()
    if sched.prefix_cache is not None:
        trie = set(sched.prefix_cache.iter_pages())
    slot_pages = set()
    for pages in list(sched.kv._slot_pages):
        slot_pages.update(pages)
    handoff_pages = set()
    for req in list(sched._pending_attach):
        handoff_pages.update(req._attach[0])
    counts = dict.fromkeys(PAGE_STATES, 0)
    counts["unattributed"] = 0
    for p in list(pool._refs):
        if p in trie:
            key = "prefix_shared" if pool.ref_count(p) > 1 \
                else "prefix_sole"
        elif p in slot_pages:
            key = "slot"
        elif p in handoff_pages:
            key = "handoff"
        else:
            key = "unattributed"
        counts[key] += 1
    # the free count and the _refs snapshot may straddle a mutation on
    # the serving thread: re-derive free from the allocated census so
    # one scrape stays internally conservation-exact
    counts["free"] = pool.num_pages - sum(
        counts[k] for k in ("slot", "prefix_shared", "prefix_sole",
                            "handoff", "unattributed"))
    # getattr: custom drafters predating the Drafter.mem_stats hook
    # (or duck-typed ones in tests) simply report no draft pool
    stats = None if sched._spec is None else \
        getattr(sched._spec, "mem_stats", lambda: None)()
    if stats:
        counts["draft"] = stats["draft_pages"]
        counts["draft_free"] = stats["draft_free"]
        counts["draft_num_pages"] = stats["draft_num_pages"]
    return counts


def classify_tenants(sched, raise_on_error=True):
    """Per-tenant page attribution (tenancy on): every attributable
    page of the pool is charged to exactly ONE tenant, in the same
    holder-precedence order as :func:`classify` (prefix trie, then
    slot tables, then parked handoff chains), so the per-tenant states
    sum to the global attributable count — conservation per tenant AND
    globally.  A page reachable from TWO tenants' holders is a
    cross-tenant leak (quota isolation broken by construction) and
    raises :class:`AuditError`.

    Returns ``{"label": "tenancy", "ok": ..., "errors": [...],
    "tenants": {tenant: {slot, handoff, prefix_shared, prefix_sole}}}``.
    """
    reg = sched.tenancy
    pool = sched.kv.pool
    errors = []
    owner = {}                    # page -> tenant (first claim wins)
    states = ("slot", "handoff", "prefix_shared", "prefix_sole")
    per = {t: dict.fromkeys(states, 0) for t in reg.tenants}

    def claim(page, tenant, state):
        page = int(page)
        prev = owner.get(page)
        if prev is not None:
            if prev != tenant:
                errors.append(
                    f"page {page} held by BOTH tenant {prev!r} and "
                    f"{tenant!r} (cross-tenant page leak)")
            return
        owner[page] = tenant
        per[tenant][state] += 1

    if sched.prefix_cache is not None:
        for t in reg.tenants:
            for ns in sched._tenant_namespaces(t):
                for p in sched.prefix_cache.ns_iter_pages(ns):
                    claim(p, t, "prefix_shared"
                          if pool.ref_count(p) > 1 else "prefix_sole")
    for slot, r in enumerate(sched.slot_req):
        if r is not None and r.tenant is not None:
            for p in sched.kv._slot_pages[slot]:
                claim(p, r.tenant, "slot")
    for r in sched._pending_attach:
        if r.tenant is not None:
            for p in r._attach[0]:
                claim(p, r.tenant, "handoff")
    g = classify(sched)
    attributable = sum(g.get(k, 0) for k in
                       ("slot", "prefix_shared", "prefix_sole",
                        "handoff"))
    charged = sum(sum(c.values()) for c in per.values())
    if charged != attributable and not errors:
        errors.append(
            f"tenant attribution not conservation-exact: {charged} "
            f"page(s) charged to tenants != {attributable} "
            "attributable page(s) in the global split")
    report = {"label": "tenancy", "errors": errors, "ok": not errors,
              "tenants": per}
    if errors and raise_on_error:
        raise AuditError(
            f"tenant page audit failed ({len(errors)} violation(s)):"
            "\n  " + "\n  ".join(errors))
    return report


# ------------------------------------------------- pressure forensics

class _NullChain:
    """Shared no-op causal chain for the disabled telemetry."""

    __slots__ = ()

    def add(self, act, **fields):
        pass

    def close(self, outcome=None):
        pass


NULL_CHAIN = _NullChain()


class PressureChain:
    """One capacity decision's causal event chain: the trigger (who
    needed pages, how many, how many were free) plus the ordered
    actions taken (cache pages drained, victim evicted, horizon/spec-K
    shrunk) and the outcome.  Committed to the :class:`PressureLog`
    ring — and as a tracer instant — on :meth:`close`."""

    __slots__ = ("mem", "event")

    def __init__(self, mem, trigger, **fields):
        self.mem = mem
        self.event = {"trigger": trigger, **fields, "actions": []}

    def add(self, act, **fields):
        self.event["actions"].append({"act": act, **fields})

    def close(self, outcome=None):
        if self.mem is None:
            return              # idempotent: a chain commits once
        self.event["outcome"] = outcome
        mem, self.mem = self.mem, None
        mem._commit_chain(self.event)


class MemTelemetry:
    """Per-scheduler memory telemetry driver (see module docstring).

    Constructed by ``ServingScheduler(mem_telemetry=True)`` — or built
    by the caller and passed in for custom thresholds — and driven from
    the scheduler's step loop.  ``flight`` may be attached at any time
    (``ds_serve``/``ClusterRouter`` wire their FlightRecorder after
    construction) to turn sustained-pressure episodes into flight
    dumps."""

    enabled = True

    def __init__(self, *, pressure_threshold=0.125, pressure_steps=8,
                 log_capacity=256, flight=None):
        self.pressure_threshold = float(pressure_threshold)
        self.pressure_steps = int(pressure_steps)
        self.pressure_log = deque(maxlen=int(log_capacity))
        self.flight = flight
        self.metrics = None          # bound by the scheduler
        self.tracer = None
        self.page_seconds = 0.0      # cumulative integral, all requests
        self.pages_hwm = 0           # max concurrent non-free pages seen
        self.churn = {}              # pool alloc/free/share event totals
        self.pressure_events = 0     # causal chains recorded
        self.pressure_episodes = 0   # sustained episodes fired
        self._streak = 0
        self._armed = True           # one dump per episode
        self._t_last = None

    def bind(self, metrics, tracer):
        """Scheduler wiring: where gauges and counter samples go."""
        self.metrics = metrics
        self.tracer = tracer

    # --------------------------------------------- pool event hook
    def on_pool_event(self, kind, n):
        """``PagePool.observer`` target: page-granular churn counters
        (allocate/free/share events since start), folded into the
        pressure-episode flight dump — an episode with huge churn and
        steady occupancy reads "thrashing", one with monotone growth
        reads "squeeze".  On a SHARED pool the last binder owns the
        hook; churn is a pool-level figure either way."""
        self.churn[kind] = self.churn.get(kind, 0) + n

    # ------------------------------------------------- causal chains
    def chain(self, trigger, **fields):
        return PressureChain(self, trigger, **fields)

    def _commit_chain(self, event):
        self.pressure_log.append(event)
        self.pressure_events += 1
        if self.metrics is not None:
            self.metrics.record_pressure(event.get("step", 1),
                                         event["trigger"])
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant("mem_pressure", cat="mem",
                                rid=event.get("rid"), args=event)

    # --------------------------------------------------- step driver
    def on_step(self, sched, now=None):
        """Barrier-cadence accounting, called once per scheduler step:
        refresh the page-state attribution, integrate per-request
        page-seconds, emit gauges + the Perfetto counter sample, and
        run sustained-pressure detection."""
        if now is None:
            now = time.monotonic()
        prev, self._t_last = self._t_last, now
        counts = classify(sched)
        pool = sched.kv.pool
        in_use = pool.pages_in_use
        self.pages_hwm = max(self.pages_hwm, in_use)
        if prev is not None:
            for slot in range(sched.num_slots):
                req = sched.slot_req[slot]
                n = len(sched.kv._slot_pages[slot])
                if req is not None and n:
                    # bill from when THIS request could actually have
                    # held the pages: a request admitted after an idle
                    # gap (the accounting clock last ticked at the
                    # previous run()'s drain) must not be billed for
                    # the gap — page-seconds is the tenant-billing
                    # unit, so over-billing is a correctness bug
                    start = prev if req.t_admit is None \
                        else max(prev, req.t_admit)
                    span = now - start
                    if span > 0:
                        req.page_seconds += n * span
                        self.page_seconds += n * span
        for slot in range(sched.num_slots):
            req = sched.slot_req[slot]
            if req is not None:
                req.pages_hwm = max(req.pages_hwm,
                                    len(sched.kv._slot_pages[slot]))
        free_frac = pool.free_pages / pool.num_pages
        if self.metrics is not None:
            self.metrics.record_mem(sched.step_idx, counts, free_frac,
                                    self.page_seconds)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.counter(
                "mem/pages",
                {k: counts.get(k, 0) for k in
                 ("slot", "prefix_shared", "prefix_sole", "handoff",
                  "draft", "unattributed", "free")})
            self.tracer.counter("mem/free_frac", {"free_frac": free_frac})
        # sustained-pressure episode: free fraction under the threshold
        # for N consecutive steps fires ONCE, re-arming only after the
        # pool recovers above the threshold (a long-lived squeeze is
        # one episode, not a dump per step)
        if free_frac < self.pressure_threshold:
            self._streak += 1
            if self._armed and self._streak >= self.pressure_steps:
                self._armed = False
                self.pressure_episodes += 1
                self._fire_episode(sched, counts, free_frac)
        else:
            self._streak = 0
            self._armed = True

    def _fire_episode(self, sched, counts, free_frac):
        if self.metrics is not None:
            self.metrics.record_pressure_episode(sched.step_idx)
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.instant(
                "mem_pressure_episode", cat="mem",
                args={"free_frac": round(free_frac, 4),
                      "steps": self._streak, **counts})
        if self.flight is not None:
            live = [getattr(r, "trace_rid", r.rid)
                    for r in sched.requests.values()]
            self.flight.dump(
                "mem_pressure",
                extra={"pool": counts,
                       "free_frac": round(free_frac, 4),
                       "steps_under_threshold": self._streak,
                       "threshold": self.pressure_threshold,
                       "page_churn": dict(self.churn),
                       "live_rids": live[:64],
                       "pressure_log": list(self.pressure_log)[-32:]})

    def summary_fields(self):
        return {
            "page_seconds_total": round(self.page_seconds, 3),
            "pages_in_use_hwm": self.pages_hwm,
            "mem_pressure_events": self.pressure_events,
            "mem_pressure_episodes": self.pressure_episodes,
        }


class _NullMemTelemetry(MemTelemetry):
    """Telemetry off: one shared, inert instance (the NULL_TRACER
    pattern) — every call site costs one attribute load and a falsy
    check, and nothing may ever record."""

    enabled = False

    def __init__(self):
        super().__init__(log_capacity=1)

    def chain(self, trigger, **fields):   # pragma: no cover - trivial
        return NULL_CHAIN

    def on_step(self, sched, now=None):   # pragma: no cover
        raise AssertionError("NULL_MEM must never be driven")

    def _commit_chain(self, event):       # pragma: no cover
        raise AssertionError("NULL_MEM must never record")


NULL_MEM = _NullMemTelemetry()
