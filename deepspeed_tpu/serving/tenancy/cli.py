"""CLI-facing tenancy construction, shared by ``ds_serve`` and the
process-replica worker so both sides of a cluster build the IDENTICAL
registry from the same ``--tenants``/``--lora`` strings.

* ``--tenants tenants.json`` — a JSON list of tenant dicts (the
  :meth:`TenantConfig.from_dict` schema: name, weight, page_quota,
  adapters, prefix_namespace).
* ``--lora name=path.npz,name2=random:4:7`` — the adapter roster.  A
  ``.npz`` path loads a checkpoint (``layers.{i}.{target}.{a|b}``
  keys); the ``random:<rank>[:<seed>]`` form builds a synthetic
  full-coverage adapter (bench/tests — every worker with the same spec
  and model seed holds bitwise-identical factors, so failover replays
  stay token-exact exactly like base params do).
"""

import json

from deepspeed_tpu.serving.tenancy.adapters import (AdapterStore,
                                                    random_adapter)
from deepspeed_tpu.serving.tenancy.registry import TenantRegistry


def parse_lora_spec(spec):
    """``name=source,...`` -> ordered ``[(name, source), ...]``."""
    out = []
    for item in filter(None, (s.strip() for s in spec.split(","))):
        if "=" not in item:
            raise ValueError(
                f"--lora entry {item!r}: want name=path.npz or "
                "name=random:<rank>[:<seed>]")
        name, src = item.split("=", 1)
        out.append((name.strip(), src.strip()))
    return out


def build_adapter_store(cfg, lora_spec, mesh=None):
    """An :class:`AdapterStore` from a ``--lora`` spec string (or an
    already-parsed list of (name, source) pairs).  Returns None for an
    empty spec — base-only serving keeps the leafless-pytree dispatch
    signature."""
    pairs = parse_lora_spec(lora_spec) if isinstance(lora_spec, str) \
        else list(lora_spec or ())
    if not pairs:
        return None
    store = AdapterStore(cfg, mesh=mesh)
    for name, src in pairs:
        if src.startswith("random"):
            parts = src.split(":")
            rank = int(parts[1]) if len(parts) > 1 else 4
            seed = int(parts[2]) if len(parts) > 2 else 0
            store.add(name, random_adapter(cfg, rank, seed=seed))
        else:
            store.load_npz(name, src)
    return store


def load_tenants(path_or_list):
    """Tenant dicts from a JSON file path (a list, or ``{"tenants":
    [...]}``) or an already-parsed list."""
    if isinstance(path_or_list, str):
        with open(path_or_list) as f:
            data = json.load(f)
    else:
        data = path_or_list
    if isinstance(data, dict):
        data = data.get("tenants", [])
    return list(data)


def build_tenancy(cfg, tenants=None, lora=None, mesh=None,
                  quantum_pages=8):
    """The one-call CLI entry: ``(tenants json path/list, --lora
    spec) -> TenantRegistry`` (or None when no tenants are given —
    tenancy off).  An adapter roster without tenants is rejected:
    adapters only dispatch through a tenant entitlement."""
    store = build_adapter_store(cfg, lora, mesh=mesh)
    if tenants is None:
        if store is not None:
            raise ValueError(
                "--lora without --tenants: adapters serve only through "
                "tenant entitlements (give each tenant an 'adapters' "
                "list in tenants.json)")
        return None
    return TenantRegistry(load_tenants(tenants), adapter_store=store,
                          quantum_pages=quantum_pages)
