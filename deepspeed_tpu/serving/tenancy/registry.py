"""Tenant registry: identity, adapter entitlements, page quotas,
fairness weights, per-tenant prefix namespaces, usage accounting and
the weighted deficit-round-robin (WDRR) admission pick.

Semantics the scheduler builds on:

* **Quota** (``page_quota``) caps a tenant's CONCURRENT page footprint
  — live slot pages + parked handoff chains + its namespace's cached
  prefix pages.  A tenant at quota drains/evicts only its own pages;
  it can never force another tenant's pages out (capacity isolation).
* **Billing** is the PR-11 page-seconds meter: every finished request
  adds its integrated ``pages x seconds`` to the tenant's ledger (the
  chargeback unit ``health()['tenants']`` and the journal expose).
* **Fairness**: admission serves tenants by deficit round-robin with
  per-tenant weights, costed in pages.  Each visit a tenant earns
  ``quantum_pages x weight`` credit; a request admits when its page
  cost fits the tenant's accumulated deficit.  An idle tenant's
  deficit resets (no hoarding), so a burst tenant converges to its
  weight share and cannot starve a lighter one (the starvation
  oracle).
* **Prefix namespace** is ``(tenant namespace, adapter)``: cached KV
  depends on the adapter that produced it, so adapter identity MUST be
  part of the radix key — two tenants (or two adapters of one tenant)
  never share cached KV even for identical prompts.
"""

import collections


class TenantConfig:
    """One tenant: adapter entitlements, capacity quota, fairness
    weight, prefix-cache namespace (defaults to the tenant name)."""

    def __init__(self, name, *, weight=1.0, page_quota=None, adapters=(),
                 prefix_namespace=None):
        if not name or not isinstance(name, str):
            raise ValueError("tenant name must be a non-empty string")
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        if page_quota is not None and page_quota <= 0:
            raise ValueError(f"tenant {name!r}: page_quota must be > 0")
        self.name = name
        self.weight = float(weight)
        self.page_quota = None if page_quota is None else int(page_quota)
        self.adapters = tuple(adapters)
        self.prefix_namespace = prefix_namespace or name

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        name = d.pop("name")
        known = {k: d.pop(k) for k in ("weight", "page_quota", "adapters",
                                       "prefix_namespace") if k in d}
        if d:
            raise ValueError(
                f"tenant {name!r}: unknown config keys {sorted(d)}")
        return cls(name, **known)


class TenantUsage:
    """Per-tenant running ledger (host-side counters only)."""

    __slots__ = ("page_seconds", "pages_hwm", "admitted", "completed",
                 "shed", "preempted", "tokens_emitted")

    def __init__(self):
        self.page_seconds = 0.0
        self.pages_hwm = 0
        self.admitted = 0
        self.completed = 0
        self.shed = 0
        self.preempted = 0
        self.tokens_emitted = 0

    def fields(self):
        return {"page_seconds": round(self.page_seconds, 6),
                "pages_hwm": self.pages_hwm, "admitted": self.admitted,
                "completed": self.completed, "shed": self.shed,
                "preempted": self.preempted,
                "tokens_emitted": self.tokens_emitted}


class TenantRegistry:
    """The scheduler's tenancy root: tenants by name, the shared
    :class:`AdapterStore`, usage ledgers, and WDRR admission state."""

    def __init__(self, tenants, adapter_store=None, quantum_pages=8):
        self.store = adapter_store
        self.tenants = {}
        for t in tenants:
            if isinstance(t, dict):
                t = TenantConfig.from_dict(t)
            if t.name in self.tenants:
                raise ValueError(f"duplicate tenant {t.name!r}")
            for a in t.adapters:
                if adapter_store is None or not adapter_store.has(a):
                    raise ValueError(
                        f"tenant {t.name!r}: adapter {a!r} not in the "
                        "adapter store")
            self.tenants[t.name] = t
        if not self.tenants:
            raise ValueError("TenantRegistry needs at least one tenant")
        seen_ns = {}
        for t in self.tenants.values():
            other = seen_ns.setdefault(t.prefix_namespace, t.name)
            if other != t.name:
                raise ValueError(
                    f"tenants {other!r} and {t.name!r} share prefix "
                    f"namespace {t.prefix_namespace!r} — cached KV "
                    "would cross the tenant boundary")
        self.usage = {n: TenantUsage() for n in self.tenants}
        self.quantum_pages = int(quantum_pages)
        self._deficit = {n: 0.0 for n in self.tenants}
        self._rr = list(self.tenants)
        self._ptr = 0
        self._visit = None       # tenant mid-burst (serves from deficit)

    def __contains__(self, name):
        return name in self.tenants

    def get(self, name):
        t = self.tenants.get(name)
        if t is None:
            raise KeyError(f"unknown tenant {name!r} "
                           f"(have {sorted(self.tenants)})")
        return t

    def resolve(self, tenant, adapter):
        """Validate a (tenant, adapter) submit pair -> (TenantConfig,
        adapter_id).  ``adapter=None`` serves the base model
        (adapter_id -1); a named adapter must be in the tenant's
        entitlement set AND the store."""
        t = self.get(tenant)
        if adapter is None:
            return t, -1
        if adapter not in t.adapters:
            raise ValueError(
                f"tenant {tenant!r} is not entitled to adapter "
                f"{adapter!r} (entitled: {sorted(t.adapters)})")
        return t, self.store.id_of(adapter)

    def namespace(self, tenant, adapter=None):
        """The prefix-cache radix namespace for (tenant, adapter):
        cached KV depends on the adapter weights that wrote it, so the
        adapter is part of the key, not just the tenant."""
        t = self.get(tenant) if isinstance(tenant, str) else tenant
        return (t.prefix_namespace, adapter)

    # -- WDRR admission -------------------------------------------------

    def next_tenant(self, heads):
        """Pick the tenant whose queue head admits next.  ``heads`` maps
        tenant name -> page cost of its oldest waiting request.  Classic
        deficit round-robin: visit tenants in fixed rotation; a visited
        tenant with work earns ``quantum_pages * weight`` credit ONCE
        per rotation visit and admits while its head cost fits the
        accumulated deficit (a burst continues across calls via
        ``_visit`` WITHOUT re-earning — topping up on every revisit
        would let the rotation's first tenant serve forever, the exact
        starvation the oracle in tests/unit/test_tenancy.py pins).
        Idle tenants' deficits reset (no hoarding).  Returns None iff
        ``heads`` is empty."""
        if not heads:
            return None
        for t in self._deficit:
            if t not in heads:
                self._deficit[t] = 0.0
                if self._visit == t:
                    self._visit = None
        # continue the current visit's burst from REMAINING deficit
        v = self._visit
        if v is not None and v in heads and self._deficit[v] >= heads[v]:
            self._deficit[v] -= heads[v]
            return v
        self._visit = None
        n = len(self._rr)
        # bounded: each full rotation tops up every backlogged tenant
        # once, so max(cost)/(quantum*min weight) rotations suffice
        max_cost = max(heads.values())
        min_gain = self.quantum_pages * min(
            self.tenants[t].weight for t in heads)
        rotations = int(max_cost / max(min_gain, 1e-9)) + 2
        for _ in range(rotations * n):
            t = self._rr[self._ptr % n]
            self._ptr += 1
            if t not in heads:
                continue
            self._deficit[t] += self.quantum_pages * \
                self.tenants[t].weight
            if self._deficit[t] >= heads[t]:
                self._deficit[t] -= heads[t]
                self._visit = t
                return t
        # numerically impossible unless weights/quantum are degenerate;
        # serve the largest-deficit backlogged tenant rather than stall
        return max(heads, key=lambda t: self._deficit[t])

    # -- ledgers --------------------------------------------------------

    def bill(self, tenant, *, page_seconds=0.0, pages_hwm=0, tokens=0):
        u = self.usage[tenant]
        u.page_seconds += float(page_seconds)
        u.pages_hwm = max(u.pages_hwm, int(pages_hwm))
        u.tokens_emitted += int(tokens)

    def note(self, tenant, event):
        u = self.usage[tenant]
        setattr(u, event, getattr(u, event) + 1)

    def usage_fields(self):
        return {n: u.fields() for n, u in sorted(self.usage.items())}
