"""AdapterStore: N LoRA adapters as stacked, rank-bucketed device
arrays for the paged multi-LoRA decode path (S-LoRA / Punica shape).

Layout per injected projection (models/lora.lora_targets) and layer::

    a: [n_adapters, in_dim,  rank_bucket]
    b: [n_adapters, rank_bucket, out_dim]

plus one ``scale: [n_adapters]`` (``alpha / rank``).  Ranks zero-pad up
to a power-of-two bucket, so the device pack's SHAPES — and therefore
the jit signatures of every serving primitive that takes it — depend
only on (adapter count, rank bucket, model dims), never on which
adapter any slot runs: adapter churn within a bucket compiles nothing.
Growing the adapter set or crossing a rank bucket re-stacks the pack
(one new signature per horizon/K bucket, the documented warmup).

Sharding mirrors the base matrices: the factor dimension that sits on
the ``model`` mesh axis in the base kernel (out_dim for column-
parallel, in_dim for row-parallel) shards over ``model`` when it
divides, else the tiny factors replicate — either way the delta einsum
composes with the base projection under GSPMD without reshards of x.
"""

import numpy as np

from deepspeed_tpu.models.lora import lora_targets


def _next_pow2(n):
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


def random_adapter(cfg, rank, seed, targets=None, stddev=0.02):
    """A synthetic full-coverage adapter (tests / bench): every target
    of every layer gets dense N(0, stddev) A and B factors — unlike
    real LoRA init (B = 0) both factors are non-zero so the delta
    actually moves logits and the token-exactness oracles bite."""
    targets = targets or lora_targets(cfg)
    rng = np.random.default_rng(seed)
    layers = []
    for _ in range(cfg.num_layers):
        layer = {}
        for t, (d_in, d_out, _) in targets.items():
            layer[t] = (rng.normal(0, stddev, (d_in, rank)).astype(
                            np.float32),
                        rng.normal(0, stddev, (rank, d_out)).astype(
                            np.float32))
        layers.append(layer)
    return layers


class AdapterStore:
    """Holds adapters by name, hands out dense integer ids (insertion
    order), and lazily builds/caches the stacked device pack."""

    def __init__(self, cfg, mesh=None, targets=None):
        self.cfg = cfg
        self.mesh = mesh
        self.targets = dict(targets or lora_targets(cfg))
        self.num_layers = int(cfg.num_layers)
        self._adapters = {}      # name -> {"layers": [...], "alpha", "rank"}
        self._order = []         # name by id
        self._pack = None        # cached device pack
        self._pack_bucket = None

    def __len__(self):
        return len(self._order)

    def names(self):
        return list(self._order)

    def has(self, name):
        return name in self._adapters

    def id_of(self, name):
        return self._order.index(name)

    def rank_of(self, name):
        return self._adapters[name]["rank"]

    def add(self, name, layers, alpha=None):
        """Register adapter ``name``: ``layers`` is one dict per model
        layer mapping target -> (A [in, r], B [r, out]).  Targets may
        cover any subset; dims are validated against the model's target
        table.  ``alpha`` defaults to the adapter's rank (scale 1.0).
        Re-adding a name replaces its weights in place (same id)."""
        if len(layers) != self.num_layers:
            raise ValueError(
                f"adapter {name!r}: {len(layers)} layers, model has "
                f"{self.num_layers}")
        rank = 0
        for i, layer in enumerate(layers):
            for t, (a, b) in layer.items():
                if t not in self.targets:
                    raise ValueError(
                        f"adapter {name!r} layer {i}: unknown target "
                        f"{t!r} (have {sorted(self.targets)})")
                d_in, d_out, _ = self.targets[t]
                a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
                if a.shape[0] != d_in or b.shape[1] != d_out or \
                        a.shape[1] != b.shape[0]:
                    raise ValueError(
                        f"adapter {name!r} layer {i} target {t!r}: "
                        f"A{a.shape} @ B{b.shape} does not fit "
                        f"[{d_in} -> {d_out}]")
                rank = max(rank, a.shape[1])
        if rank == 0:
            raise ValueError(f"adapter {name!r} has no factors")
        if name not in self._adapters:
            self._order.append(name)
        self._adapters[name] = {
            "layers": [{t: (np.asarray(a, np.float32),
                            np.asarray(b, np.float32))
                        for t, (a, b) in layer.items()}
                       for layer in layers],
            "alpha": float(rank if alpha is None else alpha),
            "rank": int(rank),
        }
        self._pack = None
        return self.id_of(name)

    def load_npz(self, name, path, alpha=None):
        """Load an adapter checkpoint: an ``.npz`` with keys
        ``layers.{i}.{target}.a`` / ``....b`` (float arrays)."""
        with np.load(path) as z:
            layers = [dict() for _ in range(self.num_layers)]
            for key in z.files:
                parts = key.split(".")
                if len(parts) != 4 or parts[0] != "layers" or \
                        parts[3] not in ("a", "b"):
                    raise ValueError(
                        f"{path}: unexpected key {key!r} (want "
                        "layers.<i>.<target>.<a|b>)")
                i, t = int(parts[1]), parts[2]
                layers[i].setdefault(t, [None, None])
                layers[i][t][parts[3] == "b"] = np.asarray(z[key])
            for i, layer in enumerate(layers):
                for t, ab in layer.items():
                    if ab[0] is None or ab[1] is None:
                        raise ValueError(
                            f"{path}: layer {i} target {t!r} is missing "
                            "its a or b factor")
                    layer[t] = (ab[0], ab[1])
        return self.add(name, layers, alpha=alpha)

    def rank_bucket(self):
        """Current power-of-two rank bucket (the shape every factor
        stack pads to — a jit-signature input)."""
        if not self._adapters:
            return 0
        return _next_pow2(max(a["rank"] for a in self._adapters.values()))

    def pack(self):
        """The stacked device pack ``{"scale": [n], "layers": [{target:
        {"a", "b"}} ...]}`` — cached until the adapter set changes.
        Adapters that skip a target contribute zero factors there
        (exact-zero delta)."""
        if not self._adapters:
            raise ValueError("AdapterStore is empty")
        if self._pack is not None:
            return self._pack
        import jax
        import jax.numpy as jnp

        n, rb = len(self._order), self.rank_bucket()
        covered = set()
        for ad in self._adapters.values():
            for layer in ad["layers"]:
                covered.update(layer)
        scale = np.zeros(n, np.float32)
        for i, name in enumerate(self._order):
            ad = self._adapters[name]
            scale[i] = ad["alpha"] / ad["rank"]
        layers = []
        for li in range(self.num_layers):
            layer = {}
            for t in sorted(covered):
                d_in, d_out, shard_dim = self.targets[t]
                a = np.zeros((n, d_in, rb), np.float32)
                b = np.zeros((n, rb, d_out), np.float32)
                for i, name in enumerate(self._order):
                    fac = self._adapters[name]["layers"][li].get(t)
                    if fac is None:
                        continue
                    r = fac[0].shape[1]
                    a[i, :, :r] = fac[0]
                    b[i, :r, :] = fac[1]
                layer[t] = {"a": self._put(a, shard_dim == "in"),
                            "b": self._put(b, False,
                                           out=shard_dim == "out")}
            layers.append(layer)
        self._pack = {"scale": jnp.asarray(scale) if self.mesh is None
                      else jax.device_put(scale, self._replicated()),
                      "layers": layers}
        self._pack_bucket = rb
        return self._pack

    def _replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P())

    def _put(self, arr, shard_in, out=False):
        """Commit one factor stack: shard the base matrix's model-
        parallel dimension over ``model`` when it divides, else
        replicate (the factors are tiny; correctness never depends on
        the placement)."""
        import jax
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P
        msize = self.mesh.shape.get("model", 1)
        spec = P()
        if msize > 1:
            if shard_in and arr.shape[1] % msize == 0:
                spec = P(None, "model", None)      # a: [n, in, r]
            elif out and arr.shape[2] % msize == 0:
                spec = P(None, None, "model")      # b: [n, r, out]
        return jax.device_put(arr, NamedSharding(self.mesh, spec))
