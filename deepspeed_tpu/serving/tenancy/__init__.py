"""Multi-tenant serving tier: paged multi-LoRA adapters, per-tenant
quotas billed in page-seconds, and weighted-fair admission over the one
shared page pool.

* :class:`AdapterStore` — N adapters' LoRA factors as stacked device
  arrays (rank-padded to power-of-two buckets) gathered per slot inside
  the fused decode scan (models/lora.py).
* :class:`TenantConfig` / :class:`TenantRegistry` — tenant identity,
  adapter entitlements, page quotas, fairness weights, per-tenant
  prefix-cache namespaces, usage accounting, and the weighted
  deficit-round-robin admission pick.

``ServingScheduler(tenancy=registry)`` turns the tier on; with
``tenancy=None`` (the default) every scheduler path is byte-identical
to the pre-tenancy code — no extra arrays, no extra jit signatures.
"""

from deepspeed_tpu.serving.tenancy.adapters import (AdapterStore,
                                                    random_adapter)
from deepspeed_tpu.serving.tenancy.cli import (build_adapter_store,
                                               build_tenancy,
                                               load_tenants,
                                               parse_lora_spec)
from deepspeed_tpu.serving.tenancy.registry import (TenantConfig,
                                                    TenantRegistry,
                                                    TenantUsage)

__all__ = ["AdapterStore", "random_adapter", "TenantConfig",
           "TenantRegistry", "TenantUsage", "build_adapter_store",
           "build_tenancy", "load_tenants", "parse_lora_spec"]
