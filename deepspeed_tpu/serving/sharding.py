"""Serving sharding layer: logical serving axes -> mesh axes.

The serving stack names its array dimensions with *logical* axes — the
same t5x-style indirection the training params use
(parallel/sharding.py) — and maps them onto the device mesh through one
rule table, so running the paged KV cache and every serving primitive
over a multi-chip topology is a config change, not a rewrite:

  ===========  ================  =============================================
  logical      default mesh ax   carried by
  ===========  ================  =============================================
  kv_heads     model             KV page pools [pages, page_size, KV_H, dim]
  slots        data              per-slot carries (tok/active/lengths/
                                 emitted/budgets/eos), token blocks
                                 [SLOTS, H|K+1], the page table [SLOTS, maxp]
  pages        (replicated)      the page dim of the pools — page ids are
                                 GLOBAL: the host-side free list / page
                                 table / radix cache never know the mesh
  vocab        model             boundary logits a prefill chunk returns
  ===========  ================  =============================================

Weights already shard over ``model`` through the engine's
``_param_shardings``; this module covers the serving-only state.  The
page dim stays replicated by design: every device holds the full page
*index space* (its slice of every page along kv_heads), so
``PagedKVManager`` / ``PrefixCache`` bookkeeping — allocation,
refcounts, donation, COW, eviction — is mesh-agnostic host logic and a
page id means the same thing on every chip.

A multi-slice ICI x DCN topology IS the same config (landed):
``parallel.topology.make_hybrid_mesh`` builds the device array with
``mesh_utils.create_hybrid_device_mesh`` (ICI parallelism within a
slice, DCN across slices — the t5x/MaxText split), ``model`` stays on
the ICI-innermost axis, ``slots`` ride the DCN-spanning data axis, and
this rule table is untouched — the engine takes the split as pure
config (``mesh_dcn=`` / ``ds_serve --mesh ...,dcn.data=N``).  The
shard_map'd paged kernel (ops/attention/decode.py) reads this same
table through :func:`active_rules` so its per-shard split always
agrees with the pinned pool/carry shardings.
"""

import dataclasses

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

# Default logical serving axis -> mesh axis. None = replicated.
SERVING_AXIS_RULES = (
    ("kv_heads", "model"),
    ("slots", "data"),
    ("pages", None),
    ("vocab", "model"),
    ("sequence", "sequence"),
)


def _mesh_axis_size(mesh, axis):
    return int(mesh.shape[axis]) if axis is not None and axis in mesh.shape \
        else 1


@dataclasses.dataclass(frozen=True)
class ServingShardingConfig:
    """Logical-axis rules for the serving stack (immutable; the engine
    resolves it against a concrete mesh + model once, at serving
    setup)."""
    rules: tuple = SERVING_AXIS_RULES

    def axis(self, logical):
        return dict(self.rules).get(logical)

    def validate(self, mesh, num_kv_heads):
        """Mesh-shape validation for sharded serving: the axis carrying
        ``kv_heads`` must divide the model's KV head count — anything
        else would shard mid-head, the exact regime the legacy SPMD
        partitioner silently miscompiles (~1e-2 drift, PR-2 triage).
        Raises a ValueError naming the axis and head count instead."""
        ax = self.axis("kv_heads")
        size = _mesh_axis_size(mesh, ax)
        if size > 1 and num_kv_heads % size != 0:
            raise ValueError(
                f"mesh axis '{ax}' has size {size}, which does not divide "
                f"num_kv_heads={num_kv_heads}: the paged KV pools shard "
                f"their head dim over '{ax}', and an indivisible head "
                "count would shard mid-head (silent numeric drift on "
                f"legacy SPMD partitioners). Pick a mesh whose '{ax}' "
                f"size divides {num_kv_heads}, or a model whose KV head "
                f"count is a multiple of {size}.")

    def validate_heads(self, mesh, num_heads):
        """Construction-time attention-TP validation (the engine calls
        this for every model with a head-count contract, serving or
        not): the configured head axis must divide ``num_heads`` —
        intra-head tensor parallelism silently drifts ~1e-2 on legacy
        SPMD partitioners and has no serving sharding.  Fail loudly,
        naming the axis and count."""
        ax = self.axis("kv_heads")
        size = _mesh_axis_size(mesh, ax)
        if size > 1 and num_heads % size != 0:
            raise ValueError(
                f"mesh axis '{ax}' has size {size}, which does not "
                f"divide num_heads={num_heads}: intra-head tensor "
                "parallelism silently drifts on legacy SPMD partitioners"
                f" and has no serving sharding. Pick a '{ax}' size that "
                f"divides {num_heads} (tensor_parallel.tp_size / mesh"
                "={'%s': ...})." % ax)

    def resolve(self, mesh, *, num_kv_heads, vocab_size=None,
                num_slots=None):
        """Concrete :class:`ServingShardings` for one mesh + model.
        Validates kv-head divisibility (hard error — see
        :meth:`validate`); the vocab and slot axes degrade to
        replicated when they do not divide (tiny fixture vocabularies;
        a slot count smaller than / uneven over the data axis — jax
        requires dim % shards == 0, and a toy server on a big mesh
        should run replicated, not crash)."""
        self.validate(mesh, num_kv_heads)
        kv_ax = self.axis("kv_heads")
        if _mesh_axis_size(mesh, kv_ax) == 1:
            kv_ax = None
        slot_ax = self.axis("slots")
        if _mesh_axis_size(mesh, slot_ax) == 1 or (
                num_slots is not None and
                num_slots % _mesh_axis_size(mesh, slot_ax) != 0):
            slot_ax = None
        page_ax = self.axis("pages")
        if _mesh_axis_size(mesh, page_ax) == 1:
            page_ax = None
        vocab_ax = self.axis("vocab")
        if _mesh_axis_size(mesh, vocab_ax) == 1 or (
                vocab_size is not None and
                vocab_size % _mesh_axis_size(mesh, vocab_ax) != 0):
            vocab_ax = None
        return ServingShardings(mesh=mesh, config=self, kv_axis=kv_ax,
                                slot_axis=slot_ax, page_axis=page_ax,
                                vocab_axis=vocab_ax)


@dataclasses.dataclass(frozen=True)
class SeqParallelPlan:
    """Resolved sequence-parallel prefill plan for one mesh + model.

    ``axis`` is the mesh axis the prompt chunk shards over, ``size``
    its device count, ``impl`` the attention transport — ``"ulysses"``
    (all-to-all head-scatter/seq-gather) when the per-model-shard head
    count divides the axis, ``"ring"`` (ppermute hops) otherwise.  When
    the path is unusable ``axis`` is None and ``reason`` says why; the
    scheduler degrades to the chunked loop instead of crashing."""
    axis: object = None
    size: int = 1
    impl: object = None
    reason: object = None

    @property
    def usable(self):
        return self.axis is not None


def resolve_sequence_plan(mesh, config, *, num_heads, num_kv_heads):
    """Pick the sequence-parallel transport for one mesh + model.

    Decision table (mirrored in serving/README.md):

    * no ``sequence`` mesh axis, or size 1 -> degrade (chunked loop);
    * heads-per-model-shard % axis size == 0 -> ``ulysses`` — the
      all-to-all trades the seq split for a head split, which needs a
      whole number of heads per sequence rank;
    * otherwise -> ``ring`` — ppermute hops never split heads, so any
      head count rides the axis.

    KV heads are NOT a constraint here: the paged landing goes through
    ``paged_write`` against the kv-head-sharded pool exactly like the
    chunked path, and ring/ulysses run on the post-projection
    full-head q/k/v of the chunk."""
    ax = (config or ServingShardingConfig()).axis("sequence")
    size = _mesh_axis_size(mesh, ax)
    if ax is None or ax not in getattr(mesh, "shape", {}):
        return SeqParallelPlan(reason=f"mesh has no '{ax}' axis")
    if size <= 1:
        return SeqParallelPlan(reason=f"mesh axis '{ax}' has size 1")
    model_sz = _mesh_axis_size(mesh, (config or ServingShardingConfig())
                               .axis("kv_heads"))
    local_heads = num_heads // max(1, model_sz)
    if local_heads % size == 0:
        return SeqParallelPlan(axis=ax, size=size, impl="ulysses")
    return SeqParallelPlan(axis=ax, size=size, impl="ring")


@dataclasses.dataclass(frozen=True)
class ServingShardings:
    """Resolved NamedShardings for every serving array family.

    ``slot`` covers the [num_slots] device carries, ``block`` the
    [num_slots, H|K+1] token/valid blocks AND the [num_slots,
    max_pages] page table (both shard dim 0 over the slots axis),
    ``pool`` the per-layer [num_pages, page_size, kv_heads, head_dim]
    KV pools, ``logits`` a prefill chunk's [vocab] boundary row."""
    mesh: object
    config: ServingShardingConfig
    kv_axis: object
    slot_axis: object
    page_axis: object
    vocab_axis: object

    @property
    def replicated(self):
        return NamedSharding(self.mesh, P())

    @property
    def pool(self):
        return NamedSharding(
            self.mesh, P(self.page_axis, None, self.kv_axis, None))

    @property
    def slot(self):
        return NamedSharding(self.mesh, P(self.slot_axis))

    @property
    def block(self):
        return NamedSharding(self.mesh, P(self.slot_axis, None))

    @property
    def logits(self):
        return NamedSharding(self.mesh, P(self.vocab_axis))

    def describe(self):
        """Logical-axis -> resolved mesh axis map (health()/logs)."""
        return {"kv_heads": self.kv_axis, "slots": self.slot_axis,
                "pages": self.page_axis, "vocab": self.vocab_axis}


def pool_bytes_per_device(pools):
    """Per-device bytes of a (possibly sharded) KV pool pytree — each
    device holds its shard of every page, so this is total bytes
    divided by the kv-head sharding factor."""
    total = 0
    for leaf in jax.tree.leaves(pools):
        shard = leaf.sharding.shard_shape(leaf.shape) \
            if hasattr(leaf, "sharding") else leaf.shape
        total += int(np.prod(shard)) * leaf.dtype.itemsize
    return total


_ACTIVE_CONFIG = None


class config_scope:
    """Trace-time channel from the engine to the in-graph KV-pool
    constraint: the engine wraps every serving trace in
    ``config_scope(engine.serving_sharding)`` (alongside
    ``dist.mesh_scope``) so :func:`constrain_kv_pages` constrains with
    the engine's CONFIGURED rule table — a custom table must constrain
    consistently with the pinned out_shardings, or GSPMD would insert a
    full-pool reshard inside every dispatch."""

    def __init__(self, config):
        self.config = config
        self._saved = None

    def __enter__(self):
        global _ACTIVE_CONFIG
        self._saved = _ACTIVE_CONFIG
        _ACTIVE_CONFIG = self.config
        return self.config

    def __exit__(self, *exc):
        global _ACTIVE_CONFIG
        _ACTIVE_CONFIG = self._saved
        return False


def active_rules():
    """The ACTIVE logical-axis rule table as a dict (trace-time): the
    engine-configured table inside a serving trace (``config_scope``),
    the default table otherwise.  The shard_map'd paged kernel resolves
    its per-shard axes through this, so a custom rule table partitions
    the kernel consistently with the pinned shardings."""
    cfg = _ACTIVE_CONFIG
    return dict(cfg.rules if cfg is not None else SERVING_AXIS_RULES)


def constrain_kv_pages(pages):
    """Pin the serving KV pool's mesh sharding on a traced pool array
    ([num_pages, page_size, kv_heads, head_dim]) inside the paged
    attention code.  Reads the engine-installed mesh and rule table at
    TRACE time (``dist.mesh_scope`` + :class:`config_scope` wrap every
    serving trace), so GSPMD never has to guess whether the pool
    scatter/gather should keep the kv-head split; a no-op without a
    mesh, with a trivial model axis, or with an indivisible head count
    (the engine validates the real serving path long before this
    point)."""
    from deepspeed_tpu import comm as dist
    mesh = dist.get_mesh()
    cfg = _ACTIVE_CONFIG
    rules = dict(cfg.rules if cfg is not None else SERVING_AXIS_RULES)
    ax = rules.get("kv_heads")
    if mesh is None or ax is None or ax not in mesh.shape:
        return pages
    size = int(mesh.shape[ax])
    if size <= 1 or pages.shape[2] % size != 0:
        return pages
    return jax.lax.with_sharding_constraint(
        pages, NamedSharding(mesh, P(rules.get("pages"), None, ax, None)))
