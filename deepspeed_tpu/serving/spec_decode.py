"""Speculative decoding: pluggable drafters for draft/verify serving.

Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") converts K cheap *draft* tokens
plus ONE fused target-model *verify* dispatch into up to K+1 accepted
tokens.  The scheduler (``serving/scheduler.py``, ``spec_decode=...``)
collects proposals from a :class:`Drafter`, scores them with
``InferenceEngine.verify_multi`` — a teacher-forced batched forward
over the paged cache — accepts the longest greedy-matching prefix plus
the target's one bonus/correction token, and rolls the KV back past the
rejection point (``PagedKVManager.truncate_slot``).  Because the bonus
token is exactly what sequential greedy decode would have produced,
drafter quality only changes SPEED, never output: serving stays
token-exact vs ``generate()`` with any drafter, including an
adversarially wrong one.

Two stock drafters:

* :class:`NgramDrafter` — model-free prompt-lookup drafting (the
  vLLM/"prompt lookup decoding" trick): propose the continuation that
  followed the most recent earlier occurrence of the request's current
  token suffix inside its own prompt + output history.  Zero extra
  FLOPs and no state to manage — ideal for summarization/extraction/
  code traffic (outputs quote their inputs) and for the CPU rig, where
  every saved target forward is pure win.

* :class:`DraftModelDrafter` — a smaller model of the same architecture
  running on its OWN paged KV slots (its own ``PagedKVManager`` +
  pools, slot-aligned with the target scheduler).  Proposals come from
  one fused ``decode_multi`` over the draft cache; the draft cache is
  kept coherent with the *verified* sequence by lazy teacher-forced
  sync (the same chunked-prefill primitive that seeds it) and rolled
  back alongside the target after each verify.

The drafter API is deliberately forgiving: ``propose`` may return fewer
tokens than asked (or none — the slot then rides the verify dispatch as
a plain one-token decode), and any exception it raises is contained by
the scheduler (that request degrades to normal decode; the loop never
dies — see ``serve.spec_verify`` in ``resilience/faults.py``).

Mesh composition (``serving/sharding.py``): drafting is host-side
token lists and verification is one sharded ``verify_multi`` dispatch,
so spec decode runs unchanged on a multi-chip serving mesh (proven
token-exact on-mesh in ``tests/unit/test_serving_mesh.py``).  A
:class:`DraftModelDrafter`'s engine carries its own mesh — typically
1-device (a tiny draft has nothing to shard), but a meshed draft
engine composes the same way since the two engines only exchange host
token lists.
"""

import numpy as np

from deepspeed_tpu.serving.page_manager import PagedKVManager


class Drafter:
    """Interface the scheduler drives.

    ``propose(items)`` with ``items = [(slot, req, k), ...]`` returns
    ``{slot: [draft token ids]}`` with at most ``k`` tokens per slot
    (fewer — including zero — is always legal).  ``on_verified`` /
    ``on_release`` are lifecycle hooks for stateful drafters; the
    scheduler calls ``on_release`` on EVERY slot-exit path (retire,
    fail, shed, cancel, preemption), so per-slot state cannot leak.
    """

    name = "custom"

    # Capability flag: the lossless leftover-probability verifier makes
    # rejection sampling distribution-exact for ANY proposal distribution,
    # so built-in drafters opt in.  Custom drafters that predate sampled
    # verification keep the conservative default: the scheduler skips
    # sampled slots when drafting (greedy slots still ride spec rounds).
    supports_sampling = False

    def propose(self, items):
        raise NotImplementedError

    def on_verified(self, slot, req, n_emitted, n_accepted):
        """After a verify harvest: ``n_emitted`` tokens (accepted drafts
        + the bonus token) were appended to ``req.out_tokens``."""

    def on_release(self, slot, req):
        """The slot was vacated (any terminal or preemption path)."""

    def mem_stats(self):
        """Memory-telemetry hook: drafters that own device memory (the
        draft model's private page pool) report it here so the
        page-state attribution can account the draft pool next to the
        main one.  ``None`` means "no pool of my own" (NgramDrafter,
        stateless custom drafters)."""
        return None


class NgramDrafter(Drafter):
    """Prompt-lookup / n-gram drafting: match the sequence's trailing
    n-gram against its own earlier history and propose what followed
    the most recent match.

    The suffix length tried runs ``max_ngram`` down to ``min_ngram`` —
    longer matches are more specific, so they are preferred; the MOST
    RECENT earlier occurrence wins (recency tracks the current
    generation regime, e.g. a degenerate repetition loop or a quoted
    span).  ``window`` caps how far back the scan looks so per-proposal
    host cost stays O(window * max_ngram) regardless of sequence
    length.  Completely stateless: history is re-derived from
    ``req.orig_prompt + req.out_tokens`` (NOT ``req.prompt``, which
    folds emitted tokens back in after a preemption and would
    double-count them)."""

    name = "ngram"
    supports_sampling = True

    def __init__(self, max_ngram=3, min_ngram=1, window=1024):
        self.max_ngram = int(max_ngram)
        self.min_ngram = max(1, int(min_ngram))
        self.window = int(window)

    def _propose_one(self, req, k):
        hist = req.orig_prompt + req.out_tokens
        if len(hist) < self.min_ngram + 1:
            return []
        lo = max(0, len(hist) - self.window)
        for m in range(min(self.max_ngram, len(hist) - 1),
                       self.min_ngram - 1, -1):
            pat = hist[-m:]
            # most recent occurrence first: recency tracks the current
            # generation regime (a repetition loop, a quoted span)
            for i in range(len(hist) - m - 1, lo - 1, -1):
                if hist[i:i + m] != pat:
                    continue
                # literal continuation, extended CYCLICALLY past the end
                # of history: the match distance IS the period of the
                # repeating regime, so wrapping drafts the loop's next
                # lap — this is what fills the whole K budget on the
                # degenerate repeats that make spec decode pay (a wrong
                # extrapolation merely gets rejected: speed, not
                # correctness, is at stake)
                period = (len(hist) - m) - i
                cont = []
                for j in range(k):
                    idx = i + m + j
                    while idx >= len(hist):
                        idx -= period
                    cont.append(hist[idx])
                return cont
        return []

    def propose(self, items):
        return {slot: self._propose_one(req, k) for slot, req, k in items}


class DraftModelDrafter(Drafter):
    """Draft-model drafting over a private paged KV cache.

    ``engine`` is an :class:`InferenceEngine` wrapping a SMALLER config
    of the same architecture (params already set).  Each scheduler slot
    maps 1:1 to a draft slot; the draft cache must hold KV for exactly
    the *verified* sequence prefix, which three pieces maintain:

    * ``_written[slot]`` — positions whose KV is KNOWN to match the true
      sequence (``req.orig_prompt + req.out_tokens``).
    * **lazy sync** — before proposing, any gap between ``_written`` and
      ``len(seq) - 1`` (the last emitted token's KV is pending, same
      invariant as the target cache) is teacher-forced in via the
      chunked-prefill primitive, and any unverified draft KV left by a
      round whose verify never harvested (spec fallback, fault degrade)
      is truncated first.  This one mechanism covers initial prompt
      prefill, catch-up after normal-decode interludes, and recovery
      from abandoned rounds.
    * **rollback** — ``on_verified`` truncates the draft chain to the
      newly verified boundary, releasing draft pages past it.

    Proposals for all requesting slots run as ONE fused
    ``decode_multi`` over the draft table (per-slot ``budgets`` carry
    the per-slot K, so one dispatch serves mixed Ks); compile count is
    bounded by the draft horizon bucket set exactly like the target's.
    Draft-pool pressure degrades gracefully: a slot whose draft pages
    cannot grow simply proposes nothing this round."""

    name = "draft"
    supports_sampling = True

    def __init__(self, engine, *, num_slots, num_pages, page_size,
                 max_pages_per_slot=None, prefill_chunk=32):
        self.engine = engine
        if max_pages_per_slot is None:
            max_pages_per_slot = -(-num_pages // 2) or 1
        self.kv = PagedKVManager(num_pages, page_size, num_slots,
                                 max_pages_per_slot)
        self.pools = engine.init_paged_cache(num_pages, page_size)
        self.num_slots = int(num_slots)
        self.lengths = np.zeros(num_slots, np.int32)
        self.prefill_chunk = int(prefill_chunk)
        self._written = np.zeros(num_slots, np.int64)

    def _sync(self, slot, req):
        """Bring the draft cache to the verified boundary; returns False
        when draft pages cannot grow (degrade: no proposal)."""
        seq = req.orig_prompt + req.out_tokens
        target = len(seq) - 1
        written = int(self._written[slot])
        if int(self.lengths[slot]) > written:
            # unverified draft KV from a round that was never harvested
            self.kv.truncate_slot(slot, written)
            self.lengths[slot] = written
        if target > self.kv.max_tokens_per_slot():
            # the verified stream has outgrown the draft slot's table
            # (a draft pool sized smaller than the target's): drafting
            # is impossible from here on — degrade to no proposal
            # rather than let ensure_capacity raise its config error
            return False
        pos = written
        while pos < target:
            chunk = seq[pos:pos + self.prefill_chunk]
            n = len(chunk)
            if not self.kv.ensure_capacity(slot, pos + n):
                self._written[slot] = pos
                return False
            ids = np.zeros((1, self.prefill_chunk), np.int32)
            ids[0, :n] = chunk
            _, self.pools = self.engine.prefill_into_slots(
                ids, slot, n, self.kv.table, self.lengths, self.pools)
            self.lengths[slot] += n
            pos += n
        self._written[slot] = target
        return True

    def propose(self, items):
        out = {slot: [] for slot, _, _ in items}
        batch = []
        for slot, req, k in items:
            if not self._sync(slot, req):
                continue
            # cap K against the POST-sync length: _sync just advanced
            # the slot to the verified boundary, and a cap computed
            # from the stale pre-sync length could push
            # ensure_capacity past max_pages_per_slot (which raises
            # the config error, sticky-degrading the request)
            k = min(int(k),
                    self.kv.max_tokens_per_slot() - int(self.lengths[slot])
                    - 1)
            if k <= 0:
                continue
            # the draft scan writes k positions starting at lengths
            if not self.kv.ensure_capacity(slot,
                                           int(self.lengths[slot]) + k):
                continue
            batch.append((slot, req, k))
        if not batch:
            return out
        toks = np.zeros(self.num_slots, np.int32)
        active = np.zeros(self.num_slots, bool)
        budgets = np.zeros(self.num_slots, np.int32)
        eos_ids = np.full(self.num_slots, -1, np.int32)
        for slot, req, k in batch:
            toks[slot] = req.out_tokens[-1] if req.out_tokens \
                else req.prompt[-1]
            active[slot] = True
            budgets[slot] = k
            if req.eos_token_id is not None:
                # stop drafting past an eos the draft model itself emits
                eos_ids[slot] = int(req.eos_token_id)
        horizon = 1
        while horizon < max(k for _, _, k in batch):
            horizon *= 2
        blk, valid, _, _, _, _, self.pools = self.engine.decode_multi(
            toks, active, self.kv.table, self.lengths, self.pools,
            horizon=horizon, budgets=budgets, eos_ids=eos_ids)
        blk, valid = np.asarray(blk), np.asarray(valid)
        for slot, req, k in batch:
            n = int(valid[slot].sum())
            out[slot] = [int(t) for t in blk[slot][valid[slot]]][:k]
            self.lengths[slot] += n
            if n:
                # the fed token (seq's last) was written at the verified
                # boundary — that one position IS verified
                self._written[slot] += 1
        return out

    def on_verified(self, slot, req, n_emitted, n_accepted):
        # accepted drafts are now part of the true sequence: the draft
        # KV for them is valid; everything past rolls back with the
        # target (draft pages past the boundary recycle).  The draft
        # scan never wrote KV for its LAST proposed token (emitted, KV
        # pending, like any decode) — on full acceptance the verified
        # boundary passes that hole by one, so cap at the written
        # watermark and let _sync teacher-force the gap next round.
        boundary = len(req.orig_prompt) + len(req.out_tokens) - 1
        valid = min(boundary, int(self.lengths[slot]))
        self._written[slot] = valid
        self.kv.truncate_slot(slot, valid)
        self.lengths[slot] = valid

    def on_release(self, slot, req):
        self.kv.release_slot(slot)
        self.lengths[slot] = 0
        self._written[slot] = 0

    def mem_stats(self):
        pool = self.kv.pool
        return {"draft_pages": pool.pages_in_use,
                "draft_free": pool.free_pages,
                "draft_num_pages": pool.num_pages}
