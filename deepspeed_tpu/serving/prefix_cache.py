"""Radix prefix cache: refcounted, copy-on-write KV page sharing.

Mesh-agnostic by contract: the trie stores GLOBAL page ids and token
keys, never device placement — on a sharded serving mesh
(``serving/sharding.py``) a cached page's KV lives as one kv-head
shard per device, a shared page is shared on every device at once, and
the COW copy (``InferenceEngine.copy_page``) moves one index of the
global page dim with each shard copying in place.  No code here may
consult the mesh.

SGLang's RadixAttention (Zheng et al., 2024) on top of the paged KV
pool: a page-granular radix/trie index maps token-ID sequences to
chains of *full, immutable* KV pages left behind by finished requests.
Admission does a longest-prefix match against a new prompt; matched
pages are mapped read-only into the slot's page table (``PagePool``
refcounts make the sharing safe) and chunked prefill resumes from the
cached boundary — prefill FLOPs and page footprint become proportional
to *unique* tokens, not total tokens.

Structure: every tree node owns exactly ONE full page and is keyed by
that page's ``page_size`` token IDs, so the path from the root to a
node spells the exact token sequence whose KV the node's page chain
holds.  That is the cache-coherence invariant: **a chain is keyed by
exact token IDs — any mismatch is a miss, never a wrong-KV hit.**  KV
entries are position-dependent (rotary/ALiBi are applied at absolute
positions), but a prefix always starts at position 0, so equal token
chains imply bitwise-equal cached KV.

Lifecycle:

* **donate** — a finished request's full pages are inserted (ownership
  of the slot's pool reference transfers to the cache); duplicate
  chains keep the incumbent page and return the donor's copy for
  release.  A ``max_pages`` cap bounds retention.
* **match/acquire** — longest-prefix lookup; acquired pages gain one
  holder per sharing slot (``pool.share``).  A *partially* matched page
  is never shared in place: the caller copies it into a fresh private
  page on-device (copy-on-write) before any position in it may be
  overwritten.
* **evict** — cached pages are reclaimable capacity, never a leak:
  under pool pressure, leaves whose only holder is the cache are
  evicted in LRU order (interior nodes become evictable as their
  subtrees drain).  ``PagePoolExhausted`` is only terminal after the
  cache is drained.
"""

import hashlib

import numpy as np

# namespace sentinel meaning "every namespace" (None is a real
# namespace: the legacy single-tenant root)
ALL_NAMESPACES = object()


class _Node:
    """One cached page: ``key`` is the exact ``page_size`` token IDs
    whose KV the page holds; the root is a keyless sentinel."""

    __slots__ = ("key", "page", "parent", "children", "last_used")

    def __init__(self, key=None, page=None, parent=None):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}           # key tuple -> _Node
        self.last_used = 0


class PrefixCache:
    """Page-granular radix index over one :class:`PagePool`."""

    def __init__(self, pool, max_pages=None, min_partial_tokens=None):
        self.pool = pool
        self.page_size = pool.page_size
        self.max_pages = pool.num_pages if max_pages is None \
            else int(max_pages)
        # a partial (copy-on-write) hit must reuse at least this many
        # tokens to be worth the on-device page copy + fresh page: a
        # 1-token accidental match must not pay a whole-page dispatch.
        # Default: a quarter page (1 at tiny page sizes).
        if min_partial_tokens is None:
            min_partial_tokens = self.page_size // 4
        self.min_partial_tokens = max(1, int(min_partial_tokens))
        # one radix root per namespace (multi-tenant isolation): the
        # namespace is part of every lookup/insert key, so one tenant's
        # donated KV can never hit another's prompt.  ``None`` is the
        # legacy single-tenant namespace — every default path behaves
        # exactly as before.
        self._roots = {None: _Node()}
        self._nodes = 0              # == cached pages held by the index
        self._clock = 0              # LRU timestamp source
        # observability (the scheduler folds these into ServingMetrics)
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0
        self.pages_shared = 0
        self.cow_copies = 0
        self.donated_pages = 0
        self.evicted_pages = 0

    @property
    def cached_pages(self):
        return self._nodes

    @property
    def _root(self):
        # legacy single-tenant trie root (pre-namespace alias; the
        # coherence walks in the serving test suites traverse it)
        return self._roots[None]

    def _touch(self, node):
        self._clock += 1
        node.last_used = self._clock

    def _root_for(self, ns, create=False):
        root = self._roots.get(ns)
        if root is None and create:
            root = self._roots[ns] = _Node()
        return root

    def _iter_roots(self, ns):
        if ns is ALL_NAMESPACES:
            return list(self._roots.items())
        root = self._roots.get(ns)
        return [] if root is None else [(ns, root)]

    def ns_pages(self, ns):
        """Cached pages held under ONE namespace (the tenant quota
        ledger counts these against the owning tenant)."""
        root = self._roots.get(ns)
        if root is None:
            return 0
        count, stack = 0, list(root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            count += 1
        return count

    # ------------------------------------------------------------- match
    def match(self, tokens, limit=None, ns=None):
        """Longest-prefix match of ``tokens[:limit]`` against the index.

        Returns ``(full_nodes, partial_node, partial_len)``:
        ``full_nodes`` is the chain of wholly matched pages (their pages
        cover ``tokens[:len(full_nodes) * page_size]`` exactly);
        ``partial_node``, when set, matches ``partial_len`` further
        tokens at the start of its page (the copy-on-write candidate).
        ``limit`` caps the usable prefix — the scheduler passes
        ``len(prompt) - 1`` so at least one prompt token always remains
        to prefill (the boundary logits the first sampled token needs).
        Pure lookup: no refcounts move, no LRU touch, no stats — the
        hit/lookup counters advance once per ADMISSION (the scheduler's
        attach), not per attempt, so a capacity-blocked request re-
        matched every step cannot inflate the hit rate."""
        ps = self.page_size
        if limit is None:
            limit = len(tokens)
        limit = min(limit, len(tokens))
        root = self._roots.get(ns)
        if root is None:
            return [], None, 0
        node, full_nodes, i = root, [], 0
        while i + ps <= limit:
            child = node.children.get(tuple(int(t) for t in
                                            tokens[i:i + ps]))
            if child is None:
                break
            full_nodes.append(child)
            node = child
            i += ps
        partial_node, partial_len = None, 0
        rest = [int(t) for t in tokens[i:limit]]
        if rest:
            for key, child in node.children.items():
                n = 0
                while n < len(rest) and key[n] == rest[n]:
                    n += 1
                if n > partial_len:
                    partial_node, partial_len = child, n
            if partial_len < self.min_partial_tokens:
                partial_node, partial_len = None, 0
        return full_nodes, partial_node, partial_len

    def acquire(self, nodes):
        """Hand the matched chain's pages to a slot attach: the whole
        path is LRU-touched and the share is counted.  The caller
        (``PagedKVManager.attach_prefix``) takes the pool reference —
        exactly ONE holder per sharing slot."""
        pages = [n.page for n in nodes]
        for n in nodes:
            self._touch(n)
        self.pages_shared += len(pages)
        return pages

    def touch(self, node):
        """LRU-touch without sharing (the copy-on-write path reads a
        cached page but maps a private copy, so no reference moves)."""
        self._touch(node)

    # ------------------------------------------------------------ donate
    def insert(self, tokens, pages, ns=None):
        """Donate a finished request's full pages: ``pages[j]`` holds
        the KV of ``tokens[j*ps : (j+1)*ps]``.  The caller transfers
        ownership of each page's pool reference; pages the cache does
        NOT keep (duplicate chains, cap overflow) are returned for the
        caller to free.  Never triggers pool allocation."""
        ps = self.page_size
        node, leftover = self._root_for(ns, create=True), []
        for j, page in enumerate(pages):
            key = tuple(int(t) for t in tokens[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is not None:
                # chain already cached: keep the incumbent page (other
                # slots may share it), hand the donor's copy back
                leftover.append(page)
                node = child
                self._touch(node)
                continue
            if self._nodes >= self.max_pages and \
                    not self._evict_lru(protect=self._path(node)):
                leftover.extend(pages[j:])
                return leftover
            child = _Node(key, page, parent=node)
            node.children[key] = child
            node = child
            self._nodes += 1
            self.donated_pages += 1
            self._touch(node)
        return leftover

    def _path(self, node):
        out = set()
        while node is not None and node.key is not None:
            out.add(id(node))
            node = node.parent
        return out

    # ------------------------------------------------------------- evict
    def _evictable(self, protect, ns=ALL_NAMESPACES):
        """Leaves whose only holder is the cache itself (live slots add
        holders via acquire, making their chains un-evictable).
        ``ns`` scopes the sweep to ONE namespace (a tenant at quota
        drains only its own pages); the default sweeps every root."""
        out = []
        stack = [root for _, root in self._iter_roots(ns)]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.key is not None and not n.children and \
                    id(n) not in protect and \
                    self.pool.ref_count(n.page) == 1:
                out.append(n)
        return out

    def _evict_lru(self, protect=frozenset()):
        """Free ONE cached page (the least recently used evictable
        leaf).  Returns True when a page was reclaimed."""
        return self.evict(1, protect) == 1

    def evict(self, n_pages, protect=frozenset(), ns=ALL_NAMESPACES):
        """Reclaim up to ``n_pages`` cached pages, LRU-first.  Each pass
        collects the CURRENT evictable leaves once and drains them in
        LRU order; interior nodes exposed by a pass become candidates in
        the next (a parent can never leave before its children anyway,
        so per-pass batching keeps the policy LRU-within-a-layer while
        a full drain stays O(depth x tree) instead of O(pages x tree)).
        Returns the number of pages actually freed."""
        freed = 0
        while freed < n_pages:
            victims = self._evictable(protect, ns)
            if not victims:
                break
            victims.sort(key=lambda n: n.last_used)
            for victim in victims:
                if freed >= n_pages:
                    break
                del victim.parent.children[victim.key]
                self.pool.free([victim.page])
                self._nodes -= 1
                self.evicted_pages += 1
                freed += 1
        return freed

    def reclaimable_pages(self, protect=frozenset(), ns=ALL_NAMESPACES):
        """EXACTLY how many pages ``evict(..., protect)`` can free right
        now: a node is drainable only when the cache is its sole holder,
        it is not protected, AND its whole subtree is drainable — a
        parent can only leave after its children, so one shared (or
        protected) descendant pins its entire ancestor chain.  Capacity
        planners (horizon shrink, admission, chaining) rely on this
        being achievable, not an upper bound: phantom capacity here
        would suppress horizon shrink and convert it into a
        live-request preemption.  Iterative post-order — chain depth is
        unbounded (one page per ``page_size`` tokens of the longest
        donated sequence) and this runs inside the serving loop."""
        total = 0
        for _, root in self._iter_roots(ns):
            results = {}              # id(node) -> (count, drainable)
            stack = [(root, False)]
            while stack:
                node, visited = stack.pop()
                if not visited:
                    stack.append((node, True))
                    stack.extend((c, False)
                                 for c in node.children.values())
                    continue
                count, ok = 0, True
                for child in node.children.values():
                    c_count, c_ok = results.pop(id(child))
                    count += c_count
                    ok = ok and c_ok
                if node.key is not None:
                    if ok and id(node) not in protect and \
                            self.pool.ref_count(node.page) == 1:
                        count += 1
                    else:
                        ok = False
                results[id(node)] = (count, ok)
            total += results[id(root)][0]
        return total

    def iter_pages(self):
        """Every page id the trie currently holds one pool reference
        for (one per node, across all namespaces) — the census the
        memory-telemetry auditor (``serving/mem_telemetry.audit_pool``)
        and page-state classifier sweep.  Pure iterative walk, no
        refcounts move."""
        stack = [c for _, root in self._iter_roots(ALL_NAMESPACES)
                 for c in root.children.values()]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n.page

    def ns_iter_pages(self, ns):
        """``iter_pages`` scoped to one namespace (the per-tenant page
        attribution sweep in ``mem_telemetry.classify``)."""
        root = self._roots.get(ns)
        stack = [] if root is None else list(root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n.page

    def prefix_len(self, tokens, limit=None, ns=None):
        """Fingerprint export for the cluster router: how many leading
        tokens of ``tokens`` this cache could serve RIGHT NOW (whole
        matched pages plus the best copy-on-write partial).  Pure
        lookup — no refcounts move, no LRU touch, no stats — so the
        prefix-aware router can score every replica per admission
        without perturbing any cache."""
        full, _, plen = self.match(tokens, limit=limit, ns=ns)
        return len(full) * self.page_size + plen

    def hit_rate(self):
        return self.hits / self.lookups if self.lookups else 0.0

    def fingerprint(self, max_digests=4096):
        """Wire-portable digest of what this cache could serve: one
        :func:`prefix_digest` per cached page-aligned prefix (every
        trie node digests the FULL token path from the root through
        it), plus the raw hit counters.  A worker ships this at
        heartbeat cadence / on the ``fingerprint`` protocol op; the
        router-side :class:`FingerprintMatcher` then scores a
        ``ProcessReplica`` for a prompt exactly like ``prefix_len``
        scores an in-process replica — page-granular (the remote
        copy-on-write partial isn't representable in a digest set,
        and routing only needs the page-aligned score).  Pure walk:
        no refcounts, no LRU touches."""
        digests = []
        stack = [(root, (), ns) for ns, root in
                 self._iter_roots(ALL_NAMESPACES)]
        while stack and len(digests) < max_digests:
            node, path, ns = stack.pop()
            for key, child in node.children.items():
                child_path = path + key
                # the namespace salts the digest, so a router matching
                # tenant A's prompt can never score a hit against
                # tenant B's cached pages — the isolation invariant
                # holds over the wire too
                digests.append(prefix_digest(child_path, ns=ns))
                stack.append((child, child_path, ns))
        return {"page_size": self.page_size, "digests": digests,
                "lookups": self.lookups, "hits": self.hits,
                "tokens_reused": self.tokens_reused}


def prefix_digest(tokens, ns=None):
    """Deterministic cross-process digest of a token prefix: blake2b
    over the little-endian int32 token bytes.  NOT Python ``hash()``
    — that is seed-randomized per process, and the whole point is
    that the router and a worker compute identical digests.  A
    non-None namespace (multi-tenant isolation) salts the digest, so
    equal prompts in different namespaces digest differently; the
    ``None`` namespace keeps the legacy unsalted bytes (mixed-version
    fleets keep matching)."""
    h = hashlib.blake2b(digest_size=8)
    if ns is not None:
        h.update(repr(ns).encode("utf-8") + b"\x00")
    h.update(np.asarray(tokens, "<i4").tobytes())
    return h.hexdigest()


class FingerprintMatcher:
    """Router-side view of a remote worker's prefix cache, built from
    shipped :meth:`PrefixCache.fingerprint` payloads.  ``match_len``
    is the wire twin of ``PrefixCache.prefix_len``: the longest
    page-aligned cached prefix of a prompt, in tokens."""

    def __init__(self):
        self.page_size = 0
        self._digests = frozenset()
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0

    def update(self, fp):
        """Absorb one shipped fingerprint (latest wins — the cache
        mutates between heartbeats and stale entries only cost a
        slightly off score, never correctness)."""
        self.page_size = int(fp.get("page_size", 0) or 0)
        self._digests = frozenset(fp.get("digests", ()))
        self.lookups = int(fp.get("lookups", 0))
        self.hits = int(fp.get("hits", 0))
        self.tokens_reused = int(fp.get("tokens_reused", 0))

    def match_len(self, tokens, limit=None, ns=None):
        """Longest page-aligned prefix of ``tokens[:limit]`` present
        in the shipped digest set, in tokens.  Walks shortest-first
        and stops at the first miss — the trie guarantees every
        ancestor of a cached prefix is cached too, so a missing
        k-page digest rules out every longer one.  ``ns`` must be the
        same (tenant namespace, adapter) key the serving cache used,
        or the salted digests can never match."""
        if not self._digests or not self.page_size:
            return 0
        n = len(tokens) if limit is None else min(limit, len(tokens))
        matched = 0
        for k in range(self.page_size, n + 1, self.page_size):
            if prefix_digest(tokens[:k], ns=ns) not in self._digests:
                break
            matched = k
        return matched
