"""Serving observability: TTFT / per-token latency / queue and pool
gauges, emitted as ``(tag, value, step)`` events through the existing
``monitor/`` path (MonitorMaster.write_events) so serving metrics land in
the same TensorBoard/WandB/CSV sinks as training metrics.

Latency samples are durations computed by the scheduler from
``time.monotonic()`` timestamps — never wall-clock, so an NTP step
cannot produce negative or wild TTFT/ITL values.  Terminal outcomes are
counted distinctly (completed / failed / shed / cancelled): an operator
must be able to tell "we errored" from "we refused load"."""

from collections import deque

import numpy as np

from deepspeed_tpu.monitor.monitor import clamp_min_step


def _percentile(values, q):
    return float(np.percentile(np.asarray(values, np.float64), q)) \
        if values else 0.0


class ServingMetrics:
    """Aggregates per-request latency samples and per-step gauges."""

    def __init__(self, monitor=None):
        self.monitor = monitor        # MonitorMaster-compatible (or None)
        self.ttft_s = []              # submit -> first token, per request
        self.tpot_s = []              # inter-token gaps, per token
        self.tbt_s = []               # horizon-boundary gaps, per request
        self.completed = 0
        self.failed = 0               # per-request error, contained
        self.shed = 0                 # deadline/capacity load shedding
        self.cancelled = 0
        self.preemptions = 0
        self.tokens_emitted = 0
        self.page_util = []           # pool utilization per step
        self.queue_depths = []
        self.horizons = []            # fused decode horizon per harvest
        self.device_wait_s = 0.0      # step time blocked on the device
        self.host_s = 0.0             # step time doing host bookkeeping
        # prefix-cache aggregates (admission-time KV reuse)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefill_tokens_saved = 0  # == cached prefix tokens reused
        self.cache_evictions = 0       # cached pages drained under pressure
        # speculative decoding (draft/verify rounds)
        self.spec_dispatches = 0       # verify_multi rounds harvested
        self.spec_proposed = 0         # draft tokens scored (sum widths)
        self.spec_accepted = 0         # drafts the target's argmax matched
        self.spec_emitted = 0          # tokens a verify round produced
        self.spec_rollbacks = 0        # rounds that discarded written KV
        self.spec_rollback_tokens = 0  # KV positions rolled back
        self.spec_slot_rounds = 0      # (slot, round) pairs that proposed
        self.spec_degraded = 0         # drafter/verify faults contained
        self.spec_degrade_log = deque(maxlen=64)  # (step, rid, reason)
        self.handoffs = 0              # prefill->decode KV chains handed
        self.handoff_tokens = 0        # prefilled positions transferred
        # handoff transport (cross-pool transfers; all 0 on shared_pool)
        self.handoff_bytes_out = 0     # KV payload bytes exported
        self.handoff_bytes_in = 0      # KV payload bytes imported
        self.handoff_chunks = 0        # chunk dispatches either direction
        self.handoff_transport_ms = 0.0  # wall ms moving chains
        self.handoff_aborted = 0       # transfers torn down mid-chain
        # sequence-parallel prefill (long-context routing)
        self.seq_prefill_routed = 0    # prompts routed onto the sp path
        self.seq_prefill_chunks = 0    # sp chunk dispatches
        self.seq_prefill_tokens = 0    # prompt tokens landed via sp chunks
        self.seq_prefill_degraded = 0  # long prompts kept on chunked path
        self.seq_prefill_shed = 0      # prompts shed on the reserve cap
        # decoding-policy subsystem (serving/sampling/)
        self.sampled_requests = 0      # intakes with a sampled policy
        self.grammar_requests = 0      # intakes carrying a grammar
        self.policy_dispatches = 0     # fused dispatches on the policy twins
        self.grammar_violations = 0    # grammar cursor rejected a token
        # memory telemetry (MemTelemetry drives these; all 0 when off)
        self.mem_pressure_events = 0   # capacity causal chains recorded
        self.mem_pressure_episodes = 0  # sustained episodes fired
        # multi-tenant serving (tenancy on; all 0 otherwise)
        self.quota_shed = 0            # requests shed on page quota
        # online autotuner (OnlineTuner drives these; all 0 when off)
        self.tune_nudges = 0           # knob nudges applied
        self.tune_log = deque(maxlen=64)   # (step, knob, value)
        self.mesh_info = {}            # serving topology (record_mesh)
        self._events = []

    # ---------------------------------------------------------- recording
    def _write(self, events):
        """The ONE funnel serving events take to the monitor sink.  The
        ``step >= 1`` invariant is enforced centrally here
        (``monitor.clamp_min_step`` — construction-time gauges
        legitimately predate step 1 and stamp to it silently;
        MonitorMaster additionally clamps-with-warning for emitters
        outside this funnel), replacing the old per-callsite
        hand-stamping."""
        if self.monitor is not None:
            self.monitor.write_events(clamp_min_step(events, warn=False))

    def record_mesh(self, mesh_info, step=0):
        """One-shot serving-topology gauges at scheduler construction:
        per-axis mesh sizes and the per-device KV-pool footprint (each
        device holds its kv-head shard of every page).  Scalar-only
        sinks get one gauge per mesh axis; the full map rides
        ``health()``.  Fires before the first live step — the central
        clamp in ``_write`` lands it at step 1."""
        self.mesh_info = mesh_info
        events = [(f"serving/mesh/{ax}", size, step)
                  for ax, size in
                  (mesh_info.get("mesh_shape") or {}).items()]
        if mesh_info.get("kv_pool_bytes_per_device") is not None:
            events.append(("serving/mesh/kv_pool_bytes_per_device",
                           mesh_info["kv_pool_bytes_per_device"], step))
        self._write(events)

    def record_step(self, step, *, queue_depth, running, waiting,
                    page_utilization, device_wait_s=0.0, host_s=0.0,
                    cached_pages=None):
        self.page_util.append(page_utilization)
        self.queue_depths.append(queue_depth)
        self.device_wait_s += device_wait_s
        self.host_s += host_s
        self._events = [
            ("serving/queue_depth", queue_depth, step),
            ("serving/running", running, step),
            ("serving/waiting", waiting, step),
            ("serving/page_utilization", page_utilization, step),
            ("serving/device_wait_ms", device_wait_s * 1e3, step),
            ("serving/host_ms", host_s * 1e3, step),
        ]
        if cached_pages is not None:
            self._events.append(
                ("serving/prefix_cache/cached_pages", cached_pages, step))
        self._write(self._events)

    def record_prefix(self, step, cached_tokens, prompt_tokens):
        """One admission-time prefix-cache lookup: ``cached_tokens`` of
        the ``prompt_tokens``-long prompt were served from cached pages
        (0 = miss).  Every cached token is a prefill token NOT
        computed."""
        self.prefix_lookups += 1
        if cached_tokens > 0:
            self.prefix_hits += 1
            self.prefill_tokens_saved += cached_tokens
        self._write([
                ("serving/prefix_cache/cached_prefix_tokens",
                 cached_tokens, step),
                ("serving/prefix_cache/hit_rate",
                 self.prefix_hits / self.prefix_lookups, step),
                ("serving/prefix_cache/prefill_tokens_saved",
                 self.prefill_tokens_saved, step),
            ])

    def record_seq_prefill_route(self, step, prompt_tokens, reserved_pages):
        """One admission routed onto the sequence-parallel prefill path:
        the full ``reserved_pages`` page chain is held up front so the
        wide chunks never stall mid-prompt on allocation."""
        self.seq_prefill_routed += 1
        self._write([
            ("serving/seq_prefill/routed", prompt_tokens, step),
            ("serving/seq_prefill/reserved_pages", reserved_pages, step),
        ])

    def record_seq_prefill_chunk(self, step, tokens):
        self.seq_prefill_chunks += 1
        self.seq_prefill_tokens += tokens
        self._write([("serving/seq_prefill/chunk_tokens", tokens, step)])

    def record_seq_prefill_degrade(self, step):
        """A prompt crossed the length threshold but stayed on the
        chunked path (no usable sequence axis, or the up-front page
        reservation self-preempted)."""
        self.seq_prefill_degraded += 1
        self._write([("serving/seq_prefill/degraded", 1, step)])

    def record_seq_prefill_shed(self, step, pages_needed):
        """A long prompt's up-front reservation exceeded the per-request
        cap (prefill_reserve_frac) and the request was shed with reason
        rather than allowed to starve concurrent short requests."""
        self.seq_prefill_shed += 1
        self._write([
            ("serving/seq_prefill/shed_reserve_cap", pages_needed, step)])

    def record_tenants(self, step, *, active, page_seconds, max_share):
        """Per-step tenancy gauges: tenants with live pages, the summed
        page-seconds ledger across all tenants, and the largest single
        tenant's share of the pool (the fairness headline — a weighted
        mix should keep it near its weight fraction).  Names are FIXED
        scalars (taxonomy-pinned); per-tenant detail rides
        ``health()['tenants']``, never dynamic gauge names."""
        self._write([
            ("serving/tenant/active", active, step),
            ("serving/tenant/page_seconds", page_seconds, step),
            ("serving/tenant/max_share", max_share, step),
        ])

    def record_quota_shed(self, step):
        """A request shed because its tenant's page quota could not
        cover it even after draining the tenant's own cached pages."""
        self.quota_shed += 1
        self._write([("serving/tenant/quota_shed", 1, step)])

    def record_cache_eviction(self, step, pages):
        """Cached pages drained back to the free list under pool
        pressure (reclaim, not failure)."""
        self.cache_evictions += pages
        self._write(
                [("serving/prefix_cache/evicted_pages", pages, step)])

    def record_tbt(self, step, gap_s):
        """Time-between-token-bursts at HORIZON granularity: the gap a
        streaming client sees between one request's consecutive token
        deliveries.  With fused horizons tokens arrive in bursts, so
        this — not the intra-burst tpot gap — is the client-visible
        latency cadence."""
        self.tbt_s.append(gap_s)
        self._write(
                [("serving/tbt_ms", gap_s * 1e3, step)])

    def record_horizon(self, step, horizon, tokens, device_wait_s):
        """One fused decode horizon was harvested: its step count, the
        tokens it delivered, and how long the host blocked waiting for
        the device (0 when the overlapped copy had already landed)."""
        self.horizons.append(horizon)
        self._write([
                ("serving/horizon", horizon, step),
                ("serving/horizon_tokens", tokens, step),
                ("serving/horizon_wait_ms", device_wait_s * 1e3, step),
            ])

    def record_spec(self, step, *, proposed, accepted, emitted, rollbacks,
                    rollback_tokens, k, slot_rounds=0):
        """One speculative draft/verify round was harvested: ``proposed``
        draft tokens were scored in one dispatch, ``accepted`` matched
        the target's argmax, ``emitted`` tokens came out (accepted
        prefixes + one bonus token per live slot), and
        ``rollback_tokens`` KV positions written for rejected drafts
        were rolled back across ``rollbacks`` slots."""
        self.spec_dispatches += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.spec_emitted += emitted
        self.spec_rollbacks += rollbacks
        self.spec_rollback_tokens += rollback_tokens
        self.spec_slot_rounds += slot_rounds
        self._write([
                ("serving/spec/k", k, step),
                ("serving/spec/proposed", proposed, step),
                ("serving/spec/accepted", accepted, step),
                ("serving/spec/emitted", emitted, step),
                ("serving/spec/acceptance_rate",
                 accepted / proposed if proposed else 0.0, step),
                ("serving/spec/rollback_tokens", rollback_tokens, step),
            ])

    def record_spec_degrade(self, step, rid=None, reason=None):
        """A drafter exception or injected verify failure was contained:
        the request (or the round) degraded to normal decode.  The
        monitor sinks are scalar-only, so the which/why goes into
        ``spec_degrade_log`` (bounded) for operator inspection."""
        self.spec_degraded += 1
        self.spec_degrade_log.append((step, rid, reason))
        self._write([("serving/spec/degraded", 1, step)])

    def record_spec_wait(self, step, device_wait_s):
        """Host time blocked pulling a verify round's results."""
        self._write(
                [("serving/spec/wait_ms", device_wait_s * 1e3, step)])

    def spec_acceptance_rate(self):
        return self.spec_accepted / self.spec_proposed \
            if self.spec_proposed else 0.0

    def spec_mean_accepted(self):
        """Mean accepted draft tokens per proposing slot per round (the
        speedup driver: each slot-round costs ~one shared target
        forward and yields mean_accepted + 1 tokens)."""
        return self.spec_accepted / self.spec_slot_rounds \
            if self.spec_slot_rounds else 0.0

    def record_mem(self, step, counts, free_frac, page_seconds):
        """One memory-attribution sample (MemTelemetry.on_step): the
        page-state split of the pool (conservation-exact — the states
        sum to num_pages), the free fraction, and the cumulative
        page-seconds integral across all requests."""
        self._write([
                ("serving/mem/slot_pages", counts.get("slot", 0), step),
                ("serving/mem/prefix_shared_pages",
                 counts.get("prefix_shared", 0), step),
                ("serving/mem/prefix_sole_pages",
                 counts.get("prefix_sole", 0), step),
                ("serving/mem/handoff_pages",
                 counts.get("handoff", 0), step),
                ("serving/mem/draft_pages", counts.get("draft", 0), step),
                ("serving/mem/unattributed_pages",
                 counts.get("unattributed", 0), step),
                ("serving/mem/free_pages", counts.get("free", 0), step),
                ("serving/mem/free_frac", free_frac, step),
                ("serving/mem/page_seconds", page_seconds, step),
            ])

    def record_pressure(self, step, trigger):
        """One capacity-decision causal chain was recorded (the
        which/why — trigger, drained pages, victim — lives in the
        MemTelemetry pressure log; the monitor sinks are scalar-only)."""
        self.mem_pressure_events += 1
        self._write([("serving/mem/pressure", 1, step)])

    def record_pressure_episode(self, step):
        """Sustained pool pressure: the free fraction stayed under the
        episode threshold for the configured step window."""
        self.mem_pressure_episodes += 1
        self._write([("serving/mem/pressure_episode", 1, step)])

    # the per-knob gauge set is closed over the online tuner's three
    # safely-re-resolvable knobs (docs/autotuning.md knob table)
    _TUNE_KNOBS = ("decode_horizon", "spec_k", "prefix_cache_pages")

    def record_tune(self, step, knob, value):
        """One online-tuner nudge was applied: ``knob`` moved to
        ``value`` (the new live setting, not a delta).  The which/why
        detail (reason string) lives in the tuner's bounded nudge log
        and the ``tune_nudge`` tracer instant; monitor sinks get the
        counter plus the per-knob gauge."""
        if knob not in self._TUNE_KNOBS:
            raise ValueError(f"unknown tuned knob {knob!r}; the gauge "
                             f"set is closed over {self._TUNE_KNOBS}")
        self.tune_nudges += 1
        self.tune_log.append((step, knob, value))
        self._write([
                ("serving/tune/nudge", 1, step),
                (f"serving/tune/{knob}", value, step),
            ])

    # the serving/comm/axis/* gauge set is closed over MeshConfig's
    # known axes (like serving/mesh/*): scalar sinks get one gauge per
    # axis, joint-axis groups ("data+model") ride health()'s JSON dict
    _COMM_AXES = ("data", "model", "pipe", "expert", "sequence")

    def record_comm(self, step, summary):
        """The HLO comm-ledger summary of the steady-state decode
        dispatch (``ServingScheduler.comm_ledger``): per-device wire
        bytes per step/token, collective count, the per-mesh-axis split
        and the ICI/DCN tier attribution — static-analysis gauges, so
        they re-emit only when the ledger is (re)computed."""
        events = [
            ("serving/comm/bytes_per_step",
             summary["bytes_per_step"], step),
            ("serving/comm/bytes_per_token",
             summary["bytes_per_token"], step),
            ("serving/comm/collectives_per_step",
             summary["collectives_per_step"], step),
            ("serving/comm/ici_bytes_per_step",
             summary["ici_bytes"], step),
            ("serving/comm/dcn_bytes_per_step",
             summary["dcn_bytes"], step),
        ]
        for ax in self._COMM_AXES:
            if ax in summary["per_axis"]:
                events.append(
                    (f"serving/comm/axis/{ax}",
                     summary["per_axis"][ax], step))
        self._write(events)

    def record_recompile(self, step, cumulative):
        """The recompile watchdog detected steady-state jit signature
        churn (the compile-storm class); value = cumulative steady
        recompiles."""
        self._write([("serving/comm/recompile", cumulative, step)])

    def record_policy_request(self, step, *, sampled, grammar):
        """One intake (submit/attach) carried a non-default decoding
        policy: it samples/penalizes (``sampled``) and/or is grammar-
        constrained (``grammar``)."""
        events = []
        if sampled:
            self.sampled_requests += 1
            events.append(("serving/sampling/sampled_requests",
                           self.sampled_requests, step))
        if grammar:
            self.grammar_requests += 1
            events.append(("serving/sampling/grammar_requests",
                           self.grammar_requests, step))
        if events:
            self._write(events)

    def record_policy_dispatch(self, step, slots):
        """One fused dispatch took the policy twins (decode_multi_policy
        / verify_multi_policy) — per-slot traced sampling lanes instead
        of the legacy greedy statics — over ``slots`` running slots."""
        self.policy_dispatches += 1
        self._write([("serving/sampling/policy_dispatch", slots, step)])

    def record_grammar_violation(self, step, rid=None):
        """The host grammar cursor rejected a token the device emitted —
        the device mask makes this unreachable in a healthy loop, so a
        violation means corrupted constraint state; the request fails
        contained."""
        self.grammar_violations += 1
        self._write([("serving/sampling/grammar_violation", 1, step)])

    def record_handoff(self, step, tokens):
        """One prefill->decode KV handoff: ``tokens`` prefilled
        positions changed owners (zero-copy by page id on a shared
        pool; as a chunked chain transfer across pools — see
        :meth:`record_handoff_transport`)."""
        self.handoffs += 1
        self.handoff_tokens += tokens
        self._write([
                ("serving/handoff", 1, step),
                ("serving/handoff_tokens", tokens, step)])

    def record_handoff_transport(self, step, direction, nbytes, chunks,
                                 ms):
        """One completed chain transfer on THIS scheduler's side:
        ``direction`` is ``"out"`` (chain exported off this pool) or
        ``"in"`` (chain imported into it).  ``nbytes`` is exact KV
        payload bytes — ``pages * engine.kv_page_bytes(...)`` — the
        number the comm ledger's DCN tier aggregates (a cross-process
        handoff is host-staged DCN traffic by definition)."""
        if direction == "out":
            self.handoff_bytes_out += int(nbytes)
        else:
            self.handoff_bytes_in += int(nbytes)
        self.handoff_chunks += int(chunks)
        self.handoff_transport_ms += float(ms)
        self._write([
                ("serving/comm/handoff_bytes", int(nbytes), step),
                ("serving/handoff/chunks", int(chunks), step),
                ("serving/handoff/transfer_ms", float(ms), step)])

    def record_handoff_abort(self, step):
        """A chain transfer torn down mid-flight (fault or death on
        either side): partial pages were freed on both pools and the
        request requeued unified."""
        self.handoff_aborted += 1
        self._write([("serving/handoff/aborted", 1, step)])

    def record_first_token(self, step, ttft_s):
        self.ttft_s.append(ttft_s)
        self.tokens_emitted += 1
        self._write(
                [("serving/ttft_ms", ttft_s * 1e3, step)])

    def record_token(self, step, gap_s):
        self.tpot_s.append(gap_s)
        self.tokens_emitted += 1
        self._write(
                [("serving/token_latency_ms", gap_s * 1e3, step)])

    def record_completion(self, step):
        self.completed += 1

    def record_terminal(self, step, state, rid, reason=None):
        """A request left the loop without finishing: ``state`` is
        ``failed`` (contained per-request error), ``shed`` (deadline or
        capacity refusal) or ``cancelled``."""
        if state == "failed":
            self.failed += 1
        elif state == "shed":
            self.shed += 1
        elif state == "cancelled":
            self.cancelled += 1
        self._write([(f"serving/{state}", 1, step)])

    def record_preemption(self, step):
        self.preemptions += 1

    # ----------------------------------------------------------- summary
    def summary(self, wall_s=None):
        out = {
            "completed": self.completed,
            "failed": self.failed,
            "shed": self.shed,
            "cancelled": self.cancelled,
            "tokens_emitted": self.tokens_emitted,
            "preemptions": self.preemptions,
            "ttft_ms_p50": round(_percentile(self.ttft_s, 50) * 1e3, 3),
            "ttft_ms_p90": round(_percentile(self.ttft_s, 90) * 1e3, 3),
            "ttft_ms_p99": round(_percentile(self.ttft_s, 99) * 1e3, 3),
            "tpot_ms_p50": round(_percentile(self.tpot_s, 50) * 1e3, 3),
            "tpot_ms_p90": round(_percentile(self.tpot_s, 90) * 1e3, 3),
            "tpot_ms_p99": round(_percentile(self.tpot_s, 99) * 1e3, 3),
            "tbt_ms_p50": round(_percentile(self.tbt_s, 50) * 1e3, 3),
            "tbt_ms_p90": round(_percentile(self.tbt_s, 90) * 1e3, 3),
            "tbt_ms_p99": round(_percentile(self.tbt_s, 99) * 1e3, 3),
            "horizon_mean": round(float(np.mean(self.horizons)), 3)
            if self.horizons else 0.0,
            "device_wait_frac": round(
                self.device_wait_s / (self.device_wait_s + self.host_s), 4)
            if (self.device_wait_s + self.host_s) > 0 else 0.0,
            "page_util_mean": round(float(np.mean(self.page_util)), 4)
            if self.page_util else 0.0,
            "page_util_peak": round(float(np.max(self.page_util)), 4)
            if self.page_util else 0.0,
            "queue_depth_peak": int(np.max(self.queue_depths))
            if self.queue_depths else 0,
            "prefix_hit_rate": round(
                self.prefix_hits / self.prefix_lookups, 4)
            if self.prefix_lookups else 0.0,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "cache_evictions": self.cache_evictions,
            "spec_dispatches": self.spec_dispatches,
            "spec_draft_tokens": self.spec_proposed,
            "spec_accepted_tokens": self.spec_accepted,
            "spec_acceptance_rate": round(self.spec_acceptance_rate(), 4),
            "spec_mean_accepted": round(self.spec_mean_accepted(), 3),
            "spec_rollbacks": self.spec_rollbacks,
            "spec_rollback_tokens": self.spec_rollback_tokens,
            "spec_degraded": self.spec_degraded,
            "handoffs": self.handoffs,
            "handoff_tokens": self.handoff_tokens,
            "handoff_bytes_out": self.handoff_bytes_out,
            "handoff_bytes_in": self.handoff_bytes_in,
            "handoff_chunks": self.handoff_chunks,
            "handoff_transport_ms": round(self.handoff_transport_ms, 3),
            "handoff_aborted": self.handoff_aborted,
            "seq_prefill_routed": self.seq_prefill_routed,
            "seq_prefill_chunks": self.seq_prefill_chunks,
            "seq_prefill_tokens": self.seq_prefill_tokens,
            "seq_prefill_degraded": self.seq_prefill_degraded,
            "seq_prefill_shed": self.seq_prefill_shed,
            "sampled_requests": self.sampled_requests,
            "grammar_requests": self.grammar_requests,
            "policy_dispatches": self.policy_dispatches,
            "grammar_violations": self.grammar_violations,
            "tune_nudges": self.tune_nudges,
        }
        if wall_s:
            out["tokens_per_sec"] = round(self.tokens_emitted / wall_s, 2)
        return out


class ClusterMetrics:
    """Router-tier counters: what the fleet did with requests, kept
    separate from each replica's own :class:`ServingMetrics` (an
    operator must see "one replica died and its work replayed" even
    when every per-replica summary looks clean).  Events ride the same
    ``write_events`` monitor contract under ``cluster/``."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.submitted = 0            # journal admissions (deduped rids)
        self.duplicate_rids = 0       # idempotent re-submissions absorbed
        self.routed = 0               # request->replica assignments
        self.finished = 0
        self.failed = 0
        self.shed = 0
        self.cancelled = 0
        self.replays = 0              # requests replayed off a dead replica
        self.replayed_tokens = 0      # emitted tokens folded into replays
        self.failovers = 0            # replica deaths detected
        self.retries = 0              # backpressure resubmission attempts
        self.heartbeat_misses = 0
        self.drains = 0               # replica drains completed
        self.restarts = 0
        self.handoffs = 0             # prefill->decode packets delivered
        self.degraded_routes = 0      # routed unified for lack of a
                                      # healthy prefill worker
        # handoff transport aggregates (cross-pool chain transfers)
        self.handoff_transfers = 0    # completed chain transfers
        self.handoff_bytes = 0        # KV payload bytes moved
        self.handoff_chunks = 0       # chunk dispatches
        self.handoff_transfer_ms = 0.0  # wall ms source-send -> adopted
        self.handoff_aborts = 0       # transfers torn down mid-chain
        self.handoff_paths = {"shared_pool": 0, "device_put": 0,
                              "wire": 0}

    def record_handoff_transfer(self, step, path, nbytes, chunks, ms):
        """One chain transfer completed end to end through the router:
        ``path`` is the three-way transport dispatch
        (shared_pool | device_put | wire)."""
        self.handoff_transfers += 1
        self.handoff_bytes += int(nbytes)
        self.handoff_chunks += int(chunks)
        self.handoff_transfer_ms += float(ms)
        self.handoff_paths[path] = self.handoff_paths.get(path, 0) + 1
        self.event(step, "handoff_bytes", int(nbytes))

    def record_handoff_abort(self, step):
        """A chain transfer torn down mid-flight: partial pages freed
        on both pools, request requeued unified."""
        self.handoff_aborts += 1
        self.event(step, "handoff_abort")

    def event(self, step, tag, value=1):
        if self.monitor is not None:
            # same central step>=1 enforcement as ServingMetrics._write
            # (replacing the old inline max(1, step) workaround)
            self.monitor.write_events(clamp_min_step(
                [(f"cluster/{tag}", value, step)], warn=False))

    def record_terminal(self, step, state):
        if state == "finished":
            self.finished += 1
        elif state == "failed":
            self.failed += 1
        elif state == "shed":
            self.shed += 1
        elif state == "cancelled":
            self.cancelled += 1
        self.event(step, state)

    def summary(self):
        return {
            "submitted": self.submitted,
            "duplicate_rids": self.duplicate_rids,
            "routed": self.routed,
            "finished": self.finished,
            "failed": self.failed,
            "shed": self.shed,
            "cancelled": self.cancelled,
            "replays": self.replays,
            "replayed_tokens": self.replayed_tokens,
            "failovers": self.failovers,
            "retries": self.retries,
            "heartbeat_misses": self.heartbeat_misses,
            "drains": self.drains,
            "restarts": self.restarts,
            "handoffs": self.handoffs,
            "degraded_routes": self.degraded_routes,
            "handoff_transfers": self.handoff_transfers,
            "handoff_bytes": self.handoff_bytes,
            "handoff_chunks": self.handoff_chunks,
            "handoff_transfer_ms": round(self.handoff_transfer_ms, 3),
            "handoff_mb_per_s": round(
                self.handoff_bytes / 1e6
                / (self.handoff_transfer_ms / 1e3), 3)
            if self.handoff_transfer_ms > 0 else 0.0,
            "handoff_aborts": self.handoff_aborts,
            "handoff_paths": dict(self.handoff_paths),
        }


class HaMetrics:
    """Router-HA observability (cluster/ha.RouterSupervisor): takeover
    counts and fencing gauges, separate from :class:`ClusterMetrics`
    because they outlive any single router — a takeover retires the
    primary's metrics object but the supervisor's survive.  Events ride
    ``write_events`` under ``router/``."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.failovers = 0         # standby takeovers (router deaths)
        self.epoch = 0             # current lease epoch
        self.fenced_writes = 0     # WAL appends rejected from old epochs
        self.wal_records = 0       # WAL records accepted (lifetime)

    def gauge(self, step, tag, value):
        if self.monitor is not None:
            self.monitor.write_events(clamp_min_step(
                [(f"router/{tag}", value, step)], warn=False))

    def record_takeover(self, step, epoch, fenced_writes, wal_records):
        self.failovers += 1
        self.record_gauges(step, epoch, fenced_writes, wal_records)

    def record_gauges(self, step, epoch, fenced_writes, wal_records):
        self.epoch = int(epoch)
        self.fenced_writes = int(fenced_writes)
        self.wal_records = int(wal_records)
        self.gauge(step, "failovers", self.failovers)
        self.gauge(step, "epoch", self.epoch)
        self.gauge(step, "fenced_writes", self.fenced_writes)
        self.gauge(step, "wal_records", self.wal_records)
