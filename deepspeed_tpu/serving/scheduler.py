"""Iteration-level (continuous-batching) scheduler.

Orca (OSDI '22) scheduling over the paged KV cache: requests join and
leave the running batch at token granularity instead of batch
granularity.  Each ``step()`` is one scheduler iteration:

  1. sweep cancellations and expired deadlines (terminal work leaves at
     step boundaries, never mid-dispatch),
  2. admit waiting requests into free slots (admission control: the pool
     must be able to hold the whole prompt, and a deadline the request
     cannot possibly meet sheds it NOW instead of wasting pool pages),
  3. advance every admitted-but-unprefilled slot by ONE prompt chunk
     (chunked prefill — long prompts never stall running decoders for
     more than a chunk),
  4. run ONE fixed-shape decode step over all running slots,
  5. emit observability events.

All device work goes through the two jit-stable primitives on
``InferenceEngine`` (``prefill_into_slots`` / ``decode_step``); the
scheduler itself is pure host logic.  When the page pool runs dry the
youngest running request is preempted (recompute-style eviction: its
pages recycle, the request re-queues at the queue head with its
already-emitted tokens folded into the prompt).

Failure policy (the serving half of docs/resilience.md):

* **Containment** — an exception attributable to ONE request (its
  prefill dispatch, its token callback, an injected per-request fault)
  fails that request (state ``failed``) and releases its pages; the
  loop and every other request keep going.  Only errors in the shared
  batched decode dispatch — not attributable to a single request — can
  take the loop down.
* **Shedding** — load the system cannot serve is refused distinctly
  from errors (state ``shed``): deadline-infeasible admissions, expired
  deadlines, and page-capacity dead-ends.
* **Cancellation** — ``req.cancel()`` is a flag; the scheduler honors
  it at the next step boundary, releasing pages (state ``cancelled``).
* **Bounded memory** — terminal requests leave the live ``requests``
  map for a bounded ``completed`` history, so a long-running server's
  bookkeeping cannot grow without bound.

All latency accounting uses ``time.monotonic()``: an NTP clock step
must never produce negative or wild TTFT/ITL samples.
"""

import time
from collections import deque

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.page_manager import (PagedKVManager,
                                                PagePoolExhausted)

WAITING, PREFILL, RUNNING, FINISHED = "waiting", "prefill", "running", \
    "finished"
CANCELLED, FAILED, SHED = "cancelled", "failed", "shed"
TERMINAL = (FINISHED, CANCELLED, FAILED, SHED)


class QueueFull(RuntimeError):
    """Backpressure: the waiting queue is at max_queue."""


class Request:
    """One generation request flowing through the scheduler."""

    _next_id = 0

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, rid=None, deadline_s=None):
        if rid is None:
            rid = Request._next_id
            Request._next_id += 1
        self.rid = rid
        self.orig_prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.prompt = list(self.orig_prompt)   # grows on preemption
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.out_tokens = []
        self.state = WAITING
        self.prefill_pos = 0
        self.error = None            # reason string for failed/shed
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.deadline = None if deadline_s is None \
            else self.t_submit + float(deadline_s)
        self.t_admit = None
        self.t_first = None
        self.t_last = None

    @property
    def remaining_new(self):
        return self.max_new_tokens - len(self.out_tokens)

    def cancel(self):
        """Request cancellation; honored at the next step boundary (the
        scheduler releases the pages then). Idempotent; a no-op once
        the request is terminal."""
        self.cancelled = True

    def past_deadline(self, now):
        return self.deadline is not None and now > self.deadline

    def _finished_by(self, tok):
        return (self.eos_token_id is not None and
                tok == self.eos_token_id) or self.remaining_new <= 0


class ServingScheduler:
    """Continuous-batching serving loop over an ``InferenceEngine``."""

    def __init__(self, engine, *, num_slots=8, num_pages=64, page_size=None,
                 max_pages_per_slot=None, prefill_chunk=16, max_queue=256,
                 monitor=None, do_sample=False, temperature=1.0, top_k=0,
                 top_p=1.0, completed_history=4096):
        if page_size is None:
            # the paged Pallas decode kernel needs 128-multiple pages
            # (TPU lane tiling); anything smaller silently drops every
            # decode step to the gather fallback. Off-TPU the gather
            # fallback runs regardless, so small pages (finer-grained
            # pool sharing) are the better default there.
            import jax
            page_size = 128 if jax.default_backend() == "tpu" else 16
        self.engine = engine
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.max_queue = int(max_queue)
        if max_pages_per_slot is None:
            max_pages_per_slot = -(-num_pages // 2) or 1
        self.kv = PagedKVManager(num_pages, page_size, num_slots,
                                 max_pages_per_slot)
        self.pools = engine.init_paged_cache(num_pages, page_size)
        self.lengths = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.slot_req = [None] * num_slots
        self.waiting = deque()
        self.requests = {}           # rid -> LIVE request only
        # bounded terminal history: a long-running server retires
        # requests out of the live map instead of keeping them forever
        self.completed = deque(maxlen=int(completed_history))
        self._collect = None         # active run()'s result accumulator
        self.metrics = ServingMetrics(monitor)
        self.step_idx = 0
        self._ema_step_s = None      # EWMA of step wall time (health)
        # admission feasibility uses the MEDIAN of a recent window, not
        # the EWMA: one jit-compile step (seconds) would otherwise
        # dominate the estimate for dozens of steps and shed perfectly
        # serviceable deadline-bearing requests after every cold start
        self._step_window = deque(maxlen=16)
        self._last_error = None
        self.sampling = dict(do_sample=do_sample, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    # ------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None, deadline_s=None):
        """Queue a request; raises :class:`QueueFull` at max_queue (the
        backpressure signal callers turn into 429/retry). ``deadline_s``
        is a relative budget: a request that cannot finish inside it is
        shed instead of served late."""
        if len(self.waiting) >= self.max_queue:
            raise QueueFull(
                f"waiting queue at max_queue={self.max_queue}")
        need = len(np.asarray(prompt).reshape(-1)) + int(max_new_tokens)
        cap = min(self.kv.max_tokens_per_slot(),
                  self.kv.pool.num_pages * self.kv.page_size)
        if need > cap:
            raise ValueError(
                f"request of {need} tokens exceeds per-slot capacity {cap} "
                "(min(max_pages_per_slot, num_pages) * page_size)")
        req = Request(prompt, max_new_tokens, eos_token_id, on_token,
                      deadline_s=deadline_s)
        if req.max_new_tokens <= 0:
            # parity with generate(max_new_tokens=0): nothing to emit —
            # but it still counts as completed, so health()/summary
            # reconcile with the per-request rows ds_serve reports
            req.state = FINISHED
            self.completed.append(req)
            self.metrics.record_completion(self.step_idx)
            return req
        self.requests[req.rid] = req
        self.waiting.append(req)
        return req

    # --------------------------------------------------------- accounting
    def _emit(self, req, tok):
        # fault point: a raised exception here is attributable to THIS
        # request — the containment wrappers fail it, not the loop
        faults.fire("serve.request", step=self.step_idx, rid=req.rid)
        now = time.monotonic()
        tok = int(tok)
        req.out_tokens.append(tok)
        if req.t_first is None:
            req.t_first = now
            self.metrics.record_first_token(self.step_idx,
                                            now - req.t_submit)
        else:
            self.metrics.record_token(self.step_idx, now - req.t_last)
        req.t_last = now
        if req.on_token is not None:
            req.on_token(req, tok)

    def _finalize(self, req, state, reason=None):
        """Move a request from live bookkeeping to the bounded terminal
        history ("drain on retire")."""
        req.state = state
        if reason is not None:
            req.error = reason
        self.requests.pop(req.rid, None)
        self.completed.append(req)

    def _retire(self, slot):
        req = self.slot_req[slot]
        self.kv.release_slot(slot)
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        self._finalize(req, FINISHED)
        if self._collect is not None:
            # run()'s result set stays complete even after the bounded
            # history evicts this request
            self._collect[req.rid] = list(req.out_tokens)
        self.metrics.record_completion(self.step_idx)

    def _close_slot(self, slot, state, reason):
        """Terminal removal of a live slot for cancel/shed/fail: release
        pages at the step boundary, record the reason distinctly."""
        req = self.slot_req[slot]
        self.kv.release_slot(slot)
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        self._finalize(req, state, reason)
        self.metrics.record_terminal(self.step_idx, state, req.rid, reason)
        if state == FAILED:
            self._last_error = f"rid={req.rid}: {reason}"

    def _drop_waiting(self, req, state, reason):
        self._finalize(req, state, reason)
        self.metrics.record_terminal(self.step_idx, state, req.rid, reason)

    def _preempt_youngest(self, protect=None):
        """Evict the most recently admitted live request (vLLM's
        recompute preemption), re-queueing it at the queue head. Returns
        the freed slot or None if there was nothing to evict."""
        candidates = [s for s in range(self.num_slots)
                      if self.slot_req[s] is not None and s != protect]
        if not candidates:
            candidates = [protect] if protect is not None and \
                self.slot_req[protect] is not None else []
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: self.slot_req[s].t_admit)
        req = self.slot_req[victim]
        self.kv.release_slot(victim)
        self.slot_req[victim] = None
        self.lengths[victim] = 0
        req.state = WAITING
        req.prompt = req.orig_prompt + req.out_tokens
        req.prefill_pos = 0
        self.waiting.appendleft(req)
        self.metrics.record_preemption(self.step_idx)
        return victim

    def _grow_or_evict(self, slot, target_len):
        """ensure_capacity with the eviction policy behind it. Returns
        False when ``slot`` itself was preempted. Raises
        :class:`PagePoolExhausted` on a genuine dead-end (no evictable
        victim) — callers shed the slot's request rather than letting
        the loop die."""
        req = self.slot_req[slot]
        faults.fire("serve.page_alloc", step=self.step_idx, slot=slot,
                    rid=None if req is None else req.rid)
        while not self.kv.ensure_capacity(slot, target_len):
            victim = self._preempt_youngest(protect=slot)
            if victim is None:
                raise PagePoolExhausted(
                    f"cannot grow slot {slot} to {target_len} tokens: "
                    "pool exhausted with no evictable request")
            if victim == slot:
                return False
        return True

    # ----------------------------------------------------- failure policy
    def _estimated_service_steps(self, req):
        """Scheduler iterations this request still needs if admitted
        now: remaining prefill chunks + one decode step per remaining
        token (ignores queueing ahead of it — a deliberately optimistic
        bound, so shedding only fires on certainly-hopeless requests)."""
        prefill = -(-max(0, len(req.prompt) - req.prefill_pos)
                    // self.prefill_chunk)
        return prefill + max(1, req.remaining_new)

    def _step_s_estimate(self):
        """Robust per-step wall-time estimate for admission decisions:
        median over a recent window (compile spikes must not starve
        admissions), None until there are at least two samples."""
        if len(self._step_window) < 2:
            return None
        return float(np.median(self._step_window))

    def _infeasible(self, req, now):
        est = self._step_s_estimate()
        if req.deadline is None or est is None:
            return False
        eta = now + self._estimated_service_steps(req) * est
        return eta > req.deadline

    def _sweep(self):
        """Step-boundary honoring of cancellations and deadlines, for
        both queued and running requests."""
        now = time.monotonic()
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if req.cancelled:
                self._close_slot(slot, CANCELLED, "cancelled")
            elif req.past_deadline(now):
                self._close_slot(slot, SHED, "deadline expired mid-flight")
        if any(r.cancelled or r.past_deadline(now) for r in self.waiting):
            keep = deque()
            for req in self.waiting:
                if req.cancelled:
                    self._drop_waiting(req, CANCELLED, "cancelled")
                elif req.past_deadline(now):
                    self._drop_waiting(req, SHED,
                                       "deadline expired in queue")
                else:
                    keep.append(req)
            self.waiting = keep

    # -------------------------------------------------------------- step
    def step(self):
        """One scheduler iteration; returns True if any work remains."""
        self.step_idx += 1
        t_step = time.monotonic()
        # fault point: slow-step / loop-level fault injection
        faults.fire("serve.step", step=self.step_idx)

        # 1. cancellations + deadlines leave at the boundary
        self._sweep()

        # 2. admit waiting requests into free slots (retirement happens
        # inline as tokens are observed, so slots are already recycled)
        now = time.monotonic()
        for slot in range(self.num_slots):
            if self.slot_req[slot] is not None:
                continue
            # deadline-aware admission: shed what cannot finish in time
            # instead of admitting it and wasting pool pages
            while self.waiting and self._infeasible(self.waiting[0], now):
                req = self.waiting.popleft()
                self._drop_waiting(
                    req, SHED,
                    f"deadline infeasible at admission "
                    f"(needs ~{self._estimated_service_steps(req)} steps "
                    f"at {self._step_s_estimate() * 1e3:.1f} ms/step)")
            if not self.waiting:
                break
            req = self.waiting[0]
            if not self.kv.pool.can_allocate(
                    self.kv.pool.pages_for_tokens(len(req.prompt))):
                break   # admission control: whole prompt must fit now
            self.waiting.popleft()
            self.slot_req[slot] = req
            req.state = PREFILL
            req.t_admit = time.monotonic()
            self.lengths[slot] = 0

        # 3. one prompt chunk per prefilling slot (chunked prefill).
        # The whole body is attributable to ONE request, so containment
        # wraps it: a per-request failure frees the slot and moves on.
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.state != PREFILL:
                continue
            try:
                chunk = req.prompt[req.prefill_pos:
                                   req.prefill_pos + self.prefill_chunk]
                n_valid = len(chunk)
                if not self._grow_or_evict(slot, req.prefill_pos + n_valid):
                    continue      # self-preempted: back in the queue
                ids = np.zeros((1, self.prefill_chunk), np.int32)
                ids[0, :n_valid] = chunk
                logits, self.pools = self.engine.prefill_into_slots(
                    ids, slot, n_valid, self.kv.table, self.lengths,
                    self.pools)
                self.lengths[slot] += n_valid
                req.prefill_pos += n_valid
                if req.prefill_pos == len(req.prompt):
                    tok = self.engine.sample_from_logits(logits,
                                                         **self.sampling)
                    self._emit(req, tok)
                    if req._finished_by(tok):
                        self._retire(slot)
                    else:
                        self.last_tok[slot] = tok
                        req.state = RUNNING
            except PagePoolExhausted as e:
                self._close_slot(slot, SHED, f"page capacity: {e}")
            except Exception as e:   # containment: fail one, not all
                self._close_slot(slot, FAILED,
                                 f"{type(e).__name__}: {e}")

        # 4. one decode step over every running slot
        candidates = [s for s in range(self.num_slots)
                      if self.slot_req[s] is not None and
                      self.slot_req[s].state == RUNNING]
        kept = []
        for slot in candidates:
            if self.slot_req[slot] is None or \
                    self.slot_req[slot].state != RUNNING:
                continue   # evicted by an earlier slot's growth
            # the pending token writes at position lengths[slot] — make
            # sure its page exists (this is where decode-time growth and
            # eviction happen)
            try:
                if self._grow_or_evict(slot, int(self.lengths[slot]) + 1):
                    kept.append(slot)
            except PagePoolExhausted as e:
                self._close_slot(slot, SHED, f"page capacity: {e}")
            except Exception as e:   # same containment as prefill: the
                self._close_slot(slot, FAILED,  # growth is per-slot work
                                 f"{type(e).__name__}: {e}")
        # a later slot's growth can evict an earlier kept slot too
        running = [s for s in kept if self.slot_req[s] is not None and
                   self.slot_req[s].state == RUNNING]
        if running:
            # the batched dispatch is shared — an error here is NOT
            # attributable to one request and must surface loudly
            active = np.zeros(self.num_slots, bool)
            active[running] = True
            toks, self.pools = self.engine.decode_step(
                self.last_tok, active, self.kv.table, self.lengths,
                self.pools, **self.sampling)
            toks = np.asarray(toks)
            self.lengths[running] += 1
            for slot in running:
                req = self.slot_req[slot]
                tok = int(toks[slot])
                try:
                    self._emit(req, tok)
                except Exception as e:  # per-request emit/callback fault
                    self._close_slot(slot, FAILED,
                                     f"{type(e).__name__}: {e}")
                    continue
                if req._finished_by(tok):
                    self._retire(slot)
                else:
                    self.last_tok[slot] = tok

        # 5. observability
        dt = time.monotonic() - t_step
        self._step_window.append(dt)
        self._ema_step_s = dt if self._ema_step_s is None \
            else 0.8 * self._ema_step_s + 0.2 * dt
        n_running = sum(r is not None for r in self.slot_req)
        self.metrics.record_step(
            self.step_idx, queue_depth=len(self.waiting),
            running=n_running, waiting=len(self.waiting),
            page_utilization=self.kv.utilization())
        return bool(self.waiting) or n_running > 0

    def run(self, max_steps=100000):
        """Drive step() until idle; returns {rid: generated tokens} for
        requests that FINISHED (failed/shed/cancelled requests are
        reported distinctly — see ``health()`` and each request's
        ``.state``/``.error``). The result set is exact for everything
        that finished during (or before) this call even when the bounded
        ``completed`` history has rotated old entries out."""
        self._collect = {r.rid: list(r.out_tokens) for r in self.completed
                         if r.state == FINISHED}
        t0 = time.monotonic()
        try:
            for _ in range(max_steps):
                if not self.step():
                    break
        finally:
            results, self._collect = self._collect, None
        self._wall_s = time.monotonic() - t0
        # max_steps exhausted with live work is a legitimate outcome (a
        # bounded drain): finished requests are returned, the rest stay
        # queued/running for further step() calls
        return results

    # ------------------------------------------------------------- health
    def health(self):
        """Liveness/saturation snapshot for operators (exposed by
        ``bin/ds_serve``): current load, pool pressure, step latency,
        and terminal counts by kind."""
        m = self.metrics
        return {
            "step": self.step_idx,
            "running": sum(r is not None for r in self.slot_req),
            "waiting": len(self.waiting),
            "live_requests": len(self.requests),
            "queue_capacity": self.max_queue,
            "free_pages": self.kv.pool.free_pages,
            "page_utilization": round(self.kv.utilization(), 4),
            "ema_step_ms": None if self._ema_step_s is None
            else round(self._ema_step_s * 1e3, 3),
            "completed": m.completed,
            "failed": m.failed,
            "shed": m.shed,
            "cancelled": m.cancelled,
            "preemptions": m.preemptions,
            "tokens_emitted": m.tokens_emitted,
            "last_error": self._last_error,
        }

    def summary(self):
        return self.metrics.summary(getattr(self, "_wall_s", None))
