"""Iteration-level (continuous-batching) scheduler.

Orca (OSDI '22) scheduling over the paged KV cache: requests join and
leave the running batch at token granularity instead of batch
granularity.  Each ``step()`` is one scheduler iteration:

  1. retire finished slots and recycle their pages,
  2. admit waiting requests into free slots (admission control: the pool
     must be able to hold the whole prompt),
  3. advance every admitted-but-unprefilled slot by ONE prompt chunk
     (chunked prefill — long prompts never stall running decoders for
     more than a chunk),
  4. run ONE fixed-shape decode step over all running slots,
  5. emit observability events.

All device work goes through the two jit-stable primitives on
``InferenceEngine`` (``prefill_into_slots`` / ``decode_step``); the
scheduler itself is pure host logic.  When the page pool runs dry the
youngest running request is preempted (recompute-style eviction: its
pages recycle, the request re-queues at the queue head with its
already-emitted tokens folded into the prompt).
"""

import time
from collections import deque

import numpy as np

from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.page_manager import (PagedKVManager,
                                                PagePoolExhausted)

WAITING, PREFILL, RUNNING, FINISHED = "waiting", "prefill", "running", \
    "finished"


class QueueFull(RuntimeError):
    """Backpressure: the waiting queue is at max_queue."""


class Request:
    """One generation request flowing through the scheduler."""

    _next_id = 0

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, rid=None):
        if rid is None:
            rid = Request._next_id
            Request._next_id += 1
        self.rid = rid
        self.orig_prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.prompt = list(self.orig_prompt)   # grows on preemption
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.out_tokens = []
        self.state = WAITING
        self.prefill_pos = 0
        self.t_submit = time.time()
        self.t_admit = None
        self.t_first = None
        self.t_last = None

    @property
    def remaining_new(self):
        return self.max_new_tokens - len(self.out_tokens)

    def _finished_by(self, tok):
        return (self.eos_token_id is not None and
                tok == self.eos_token_id) or self.remaining_new <= 0


class ServingScheduler:
    """Continuous-batching serving loop over an ``InferenceEngine``."""

    def __init__(self, engine, *, num_slots=8, num_pages=64, page_size=None,
                 max_pages_per_slot=None, prefill_chunk=16, max_queue=256,
                 monitor=None, do_sample=False, temperature=1.0, top_k=0,
                 top_p=1.0):
        if page_size is None:
            # the paged Pallas decode kernel needs 128-multiple pages
            # (TPU lane tiling); anything smaller silently drops every
            # decode step to the gather fallback. Off-TPU the gather
            # fallback runs regardless, so small pages (finer-grained
            # pool sharing) are the better default there.
            import jax
            page_size = 128 if jax.default_backend() == "tpu" else 16
        self.engine = engine
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.max_queue = int(max_queue)
        if max_pages_per_slot is None:
            max_pages_per_slot = -(-num_pages // 2) or 1
        self.kv = PagedKVManager(num_pages, page_size, num_slots,
                                 max_pages_per_slot)
        self.pools = engine.init_paged_cache(num_pages, page_size)
        self.lengths = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.slot_req = [None] * num_slots
        self.waiting = deque()
        self.requests = []
        self.metrics = ServingMetrics(monitor)
        self.step_idx = 0
        self.sampling = dict(do_sample=do_sample, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    # ------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None):
        """Queue a request; raises :class:`QueueFull` at max_queue (the
        backpressure signal callers turn into 429/retry)."""
        if len(self.waiting) >= self.max_queue:
            raise QueueFull(
                f"waiting queue at max_queue={self.max_queue}")
        need = len(np.asarray(prompt).reshape(-1)) + int(max_new_tokens)
        cap = min(self.kv.max_tokens_per_slot(),
                  self.kv.pool.num_pages * self.kv.page_size)
        if need > cap:
            raise ValueError(
                f"request of {need} tokens exceeds per-slot capacity {cap} "
                "(min(max_pages_per_slot, num_pages) * page_size)")
        req = Request(prompt, max_new_tokens, eos_token_id, on_token)
        self.requests.append(req)
        if req.max_new_tokens <= 0:
            # parity with generate(max_new_tokens=0): nothing to emit
            req.state = FINISHED
            return req
        self.waiting.append(req)
        return req

    # --------------------------------------------------------- accounting
    def _emit(self, req, tok):
        now = time.time()
        tok = int(tok)
        req.out_tokens.append(tok)
        if req.t_first is None:
            req.t_first = now
            self.metrics.record_first_token(self.step_idx,
                                            now - req.t_submit)
        else:
            self.metrics.record_token(self.step_idx, now - req.t_last)
        req.t_last = now
        if req.on_token is not None:
            req.on_token(req, tok)

    def _retire(self, slot):
        req = self.slot_req[slot]
        self.kv.release_slot(slot)
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        req.state = FINISHED
        self.metrics.record_completion(self.step_idx)

    def _preempt_youngest(self, protect=None):
        """Evict the most recently admitted live request (vLLM's
        recompute preemption), re-queueing it at the queue head. Returns
        the freed slot or None if there was nothing to evict."""
        candidates = [s for s in range(self.num_slots)
                      if self.slot_req[s] is not None and s != protect]
        if not candidates:
            candidates = [protect] if protect is not None and \
                self.slot_req[protect] is not None else []
        if not candidates:
            return None
        victim = max(candidates, key=lambda s: self.slot_req[s].t_admit)
        req = self.slot_req[victim]
        self.kv.release_slot(victim)
        self.slot_req[victim] = None
        self.lengths[victim] = 0
        req.state = WAITING
        req.prompt = req.orig_prompt + req.out_tokens
        req.prefill_pos = 0
        self.waiting.appendleft(req)
        self.metrics.record_preemption(self.step_idx)
        return victim

    def _grow_or_evict(self, slot, target_len):
        """ensure_capacity with the eviction policy behind it. Returns
        False when ``slot`` itself was preempted."""
        while not self.kv.ensure_capacity(slot, target_len):
            victim = self._preempt_youngest(protect=slot)
            if victim is None:
                raise PagePoolExhausted(
                    f"cannot grow slot {slot} to {target_len} tokens: "
                    "pool exhausted with no evictable request")
            if victim == slot:
                return False
        return True

    # -------------------------------------------------------------- step
    def step(self):
        """One scheduler iteration; returns True if any work remains."""
        self.step_idx += 1

        # 1+2. admit waiting requests into free slots (retirement happens
        # inline as tokens are observed, so slots are already recycled)
        for slot in range(self.num_slots):
            if not self.waiting:
                break
            if self.slot_req[slot] is not None:
                continue
            req = self.waiting[0]
            if not self.kv.pool.can_allocate(
                    self.kv.pool.pages_for_tokens(len(req.prompt))):
                break   # admission control: whole prompt must fit now
            self.waiting.popleft()
            self.slot_req[slot] = req
            req.state = PREFILL
            req.t_admit = time.time()
            self.lengths[slot] = 0

        # 3. one prompt chunk per prefilling slot (chunked prefill)
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.state != PREFILL:
                continue
            chunk = req.prompt[req.prefill_pos:
                               req.prefill_pos + self.prefill_chunk]
            n_valid = len(chunk)
            if not self._grow_or_evict(slot, req.prefill_pos + n_valid):
                continue      # self-preempted: back in the queue
            ids = np.zeros((1, self.prefill_chunk), np.int32)
            ids[0, :n_valid] = chunk
            logits, self.pools = self.engine.prefill_into_slots(
                ids, slot, n_valid, self.kv.table, self.lengths, self.pools)
            self.lengths[slot] += n_valid
            req.prefill_pos += n_valid
            if req.prefill_pos == len(req.prompt):
                tok = self.engine.sample_from_logits(logits, **self.sampling)
                self._emit(req, tok)
                if req._finished_by(tok):
                    self._retire(slot)
                else:
                    self.last_tok[slot] = tok
                    req.state = RUNNING

        # 4. one decode step over every running slot
        candidates = [s for s in range(self.num_slots)
                      if self.slot_req[s] is not None and
                      self.slot_req[s].state == RUNNING]
        kept = []
        for slot in candidates:
            if self.slot_req[slot] is None or \
                    self.slot_req[slot].state != RUNNING:
                continue   # evicted by an earlier slot's growth
            # the pending token writes at position lengths[slot] — make
            # sure its page exists (this is where decode-time growth and
            # eviction happen)
            if self._grow_or_evict(slot, int(self.lengths[slot]) + 1):
                kept.append(slot)
        # a later slot's growth can evict an earlier kept slot too
        running = [s for s in kept if self.slot_req[s] is not None and
                   self.slot_req[s].state == RUNNING]
        if running:
            active = np.zeros(self.num_slots, bool)
            active[running] = True
            toks, self.pools = self.engine.decode_step(
                self.last_tok, active, self.kv.table, self.lengths,
                self.pools, **self.sampling)
            toks = np.asarray(toks)
            self.lengths[running] += 1
            for slot in running:
                req = self.slot_req[slot]
                tok = int(toks[slot])
                self._emit(req, tok)
                if req._finished_by(tok):
                    self._retire(slot)
                else:
                    self.last_tok[slot] = tok

        # 5. observability
        n_running = sum(r is not None for r in self.slot_req)
        self.metrics.record_step(
            self.step_idx, queue_depth=len(self.waiting),
            running=n_running, waiting=len(self.waiting),
            page_utilization=self.kv.utilization())
        return bool(self.waiting) or n_running > 0

    def run(self, max_steps=100000):
        """Drive step() until idle; returns {rid: generated tokens}."""
        t0 = time.time()
        for _ in range(max_steps):
            if not self.step():
                break
        self._wall_s = time.time() - t0
        # max_steps exhausted with live work is a legitimate outcome (a
        # bounded drain): finished requests are returned, the rest stay
        # queued/running for further step() calls
        return {r.rid: list(r.out_tokens) for r in self.requests
                if r.state == FINISHED}

    def summary(self):
        return self.metrics.summary(getattr(self, "_wall_s", None))
