"""Iteration-level (continuous-batching) scheduler.

Orca (OSDI '22) scheduling over the paged KV cache: requests join and
leave the running batch at token granularity instead of batch
granularity.  Each ``step()`` is one scheduler iteration:

  1. sweep cancellations and expired deadlines (terminal work leaves at
     step boundaries, never mid-dispatch),
  2. admit waiting requests into free slots (admission control: the pool
     must be able to hold the whole prompt, and a deadline the request
     cannot possibly meet sheds it NOW instead of wasting pool pages),
  3. advance every admitted-but-unprefilled slot by ONE prompt chunk
     (chunked prefill — long prompts never stall running decoders for
     more than a chunk),
  4. run ONE fused multi-step decode ("horizon") over all running
     slots: up to ``decode_horizon_steps`` tokens per slot in a single
     ``decode_multi`` dispatch, with token feedback, EOS detection and
     length advancement all on device,
  5. emit observability events.

All device work goes through the jit-stable primitives on
``InferenceEngine`` (``prefill_into_slots`` / ``decode_multi``); the
scheduler itself is pure host logic.  When the page pool runs dry,
refcount-free pages held by the prefix cache drain first (they are
reclaimable capacity, not live state); only then is the youngest
running request preempted (recompute-style eviction: its pages
recycle, the request re-queues at the queue head with its
already-emitted tokens folded into the prompt).

**Prefix cache.**  With ``prefix_cache=True`` the scheduler keeps a
radix index (``serving/prefix_cache.py``) over pages donated by
finished requests.  Admission longest-prefix matches each prompt:
matched full pages are shared read-only into the slot's table
(``PagePool`` refcounts), a partially matched page is copied into a
fresh private page on-device (copy-on-write) so the cached original
stays immutable, and chunked prefill resumes from the cached boundary
(``lengths[slot]`` seeds the positions — no new jit signatures).
Prefill compute and page footprint scale with UNIQUE tokens, not total
tokens, on shared-prefix traffic.

**The horizon model.**  A horizon of H steps costs ONE dispatch and one
host round-trip for H tokens — the per-token host loop that dominates
decode latency over a TPU relay is amortized H-fold (the same trick
``generate()`` plays with its bucketed ``lax.scan``).  The price is
granularity: scheduler interventions — admission, cancellation,
deadline shedding, eviction — take effect at horizon boundaries, so H
bounds added reaction latency at roughly H x per-token time.  Horizons
are quantized to a small power-of-two bucket set (compile count stays
bounded) and adapt down when remaining token budgets, the tightest
admitted deadline, or page-pool pressure make a full horizon wasteful
or unaffordable.  Before each dispatch every running slot's pages for
the whole horizon are pre-reserved, so allocation never interrupts the
fused scan.

**Speculative decoding.**  With ``spec_decode="ngram"`` (or ``"draft"``
plus a :class:`~deepspeed_tpu.serving.spec_decode.DraftModelDrafter`)
greedy decode dispatches become draft/verify rounds: a pluggable
drafter proposes up to K tokens per slot (adaptive per-request K,
shrunk on low acceptance and capped under page-pool pressure through
the same pre-reservation path as horizons), one teacher-forced
``verify_multi`` dispatch scores them all, the longest greedy-matching
prefix plus the target's bonus token is emitted, and KV written past
the rejection point rolls back (``truncate_slot``).  Greedy
verification compares against the exact ``temperature=0`` argmax
contract, so output is token-exact vs ``generate()`` and vs
``spec_decode=off`` regardless of drafter quality.  Sampled slots
verify by *lossless* leftover-probability rejection sampling
(``verify_multi_policy``): each draft token is accepted with the
target's probability for it and a rejection resamples the residual, so
the emitted stream is distribution-exact — identical in law to
unspeculated sampling — for ANY drafter that opts in
(``supports_sampling``).

**Decoding policy.**  Every request carries a
:class:`~deepspeed_tpu.serving.sampling.SamplingParams` (temperature /
top-k / top-p / repetition / presence / frequency penalties), a PRNG
seed keying a position-indexed sample stream, and optionally a
grammar constraint (regex / JSON-schema) compiled host-side to a
per-step allowed-token mask.  Policy knobs are traced per-slot device
lanes — a mixed greedy/sampled/penalized batch shares ONE compiled
signature per horizon/K bucket — while a pure-greedy batch under a
greedy default keeps riding the legacy signatures byte-identically.
Constrained slots run horizon-1 barrier steps (their mask is a host
function of emitted tokens) and never draft, but may ride verify
rounds as width-0 one-token decodes.  Spec rounds need host-authoritative
token history to draft from, so every step runs as a barrier step
while a drafter is configured (no horizon chaining — a chained round
never consults the drafter, and chaining plain rounds would starve it
in exactly the steady state spec decode targets); slots with nothing
to propose ride the verify dispatch as plain one-token decodes, and
when NO slot has a proposal the step falls back to the normal fused
horizon dispatch.  ``spec_decode=off`` leaves the PR-3/PR-4 loop
byte-identical.

**Overlap.**  With ``overlap=True`` the scheduler keeps one horizon in
flight: when membership is provably frozen (nothing waiting, nothing
prefilling, no cancel/deadline pressure, next horizon's pages free), it
dispatches horizon k+1 directly off horizon k's on-device carries
(token/active/lengths/emitted), *then* pulls k's token block (started
as an async host copy at dispatch) and runs emit/retire bookkeeping
while the device crunches k+1.  Any membership change falls back to a
conservative barrier: drain in-flight work, apply host-authoritative
state, dispatch fresh.  Per-request terminations discovered while a
chained horizon is in flight (a failing emit callback, a cancel, an
expired deadline) close the request immediately but defer the page
release until the in-flight horizon is harvested — the device may still
be writing that slot's pages.

Failure policy (the serving half of docs/resilience.md):

* **Containment** — an exception attributable to ONE request (its
  prefill dispatch, its token callback, an injected per-request fault)
  fails that request (state ``failed``) and releases its pages; the
  loop and every other request keep going.  Only errors in the shared
  batched decode dispatch — not attributable to a single request — can
  take the loop down.
* **Shedding** — load the system cannot serve is refused distinctly
  from errors (state ``shed``): deadline-infeasible admissions, expired
  deadlines, and page-capacity dead-ends.
* **Cancellation** — ``req.cancel()`` is a flag; the scheduler honors
  it at the next step boundary, releasing pages (state ``cancelled``).
* **Bounded memory** — terminal requests leave the live ``requests``
  map for a bounded ``completed`` history, so a long-running server's
  bookkeeping cannot grow without bound.

All latency accounting uses ``time.monotonic()``: an NTP clock step
must never produce negative or wild TTFT/ITL samples.
"""

import json
import re
import time
from collections import deque

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving import mem_telemetry as memtel
from deepspeed_tpu.serving.mem_telemetry import NULL_MEM, MemTelemetry
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.page_manager import (PagedKVManager,
                                                PagePoolExhausted,
                                                default_page_size)
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.sampling import (GREEDY, GrammarConstraintError,
                                            SamplingParams, compile_grammar,
                                            request_key)
from deepspeed_tpu.serving.trace import NULL_TRACER

WAITING, PREFILL, RUNNING, FINISHED = "waiting", "prefill", "running", \
    "finished"
CANCELLED, FAILED, SHED = "cancelled", "failed", "shed"
# HANDOFF: a prefill-worker request whose finished prompt KV (page
# chain + first token) was handed to a decode worker — terminal for
# THIS scheduler, live for the cluster request it belongs to
HANDOFF = "handoff"
TERMINAL = (FINISHED, CANCELLED, FAILED, SHED, HANDOFF)


class _PoolsRef:
    """Mutable holder for the device-resident KV pools.  The jitted
    primitives are functional — every dispatch consumes the pools and
    returns replacements — so two schedulers sharing one physical pool
    (a disaggregated prefill/decode pair) must also share ONE mutable
    reference to the current arrays, or one side would keep dispatching
    against donated-away buffers."""

    __slots__ = ("pools",)

    def __init__(self, pools):
        self.pools = pools


class QueueFull(RuntimeError):
    """Backpressure: the waiting queue is at max_queue."""


class Request:
    """One generation request flowing through the scheduler."""

    _next_id = 0

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 on_token=None, rid=None, deadline_s=None):
        if rid is None:
            rid = Request._next_id
            Request._next_id += 1
        self.rid = rid
        # span identity: the id every trace span of this request
        # carries.  Locally it is the rid; the cluster router overrides
        # it (via submit's trace_ctx) with the journal rid so one client
        # request's spans share one id across replicas and processes
        self.trace_rid = rid
        self.orig_prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        self.prompt = list(self.orig_prompt)   # grows on preemption
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.on_token = on_token
        self.out_tokens = []
        self.state = WAITING
        self.prefill_pos = 0
        self.cached_prefix_tokens = 0   # prefix-cache reuse at last admit
        # per-request memory attribution (MemTelemetry; 0 when off):
        # pages-held high-water mark and the page-seconds integral —
        # the unit the autotuner's cost model and per-tenant quotas
        # will bill in (reported in ds_serve rows and summary())
        self.pages_hwm = 0
        self.page_seconds = 0.0
        self.error = None            # reason string for failed/shed
        self.handoff = False         # prefill-worker mode (see submit)
        # decoding policy (serving/sampling/): per-request params, PRNG
        # seed, grammar cursor, and the position base for the
        # position-keyed sample stream.  Token n of the request draws
        # from fold_in(PRNGKey(seed), sample_offset + n) — sample_offset
        # counts tokens emitted in a PREVIOUS life of this request
        # (replica failover folds them into the prompt), so replay
        # continues the exact stream instead of restarting it.
        self.sampling = GREEDY
        self.seed = 0
        self.sample_offset = 0
        self.grammar = None          # GrammarConstraint cursor or None
        self.cancelled = False
        self.t_submit = time.monotonic()
        self.deadline = None if deadline_s is None \
            else self.t_submit + float(deadline_s)
        self.t_admit = None
        self.t_first = None
        self.t_last = None
        # multi-tenant serving tier (serving/tenancy/): the owning
        # tenant, the named LoRA adapter it asked for, and the dense
        # adapter-store id (-1 = base model).  All None/-1 with
        # tenancy off — no path reads them then.
        self.tenant = None
        self.adapter = None
        self.adapter_id = -1

    @property
    def remaining_new(self):
        return self.max_new_tokens - len(self.out_tokens)

    def cancel(self):
        """Request cancellation; honored at the next step boundary (the
        scheduler releases the pages then). Idempotent; a no-op once
        the request is terminal."""
        self.cancelled = True

    def past_deadline(self, now):
        return self.deadline is not None and now > self.deadline

    def _finished_by(self, tok):
        return (self.eos_token_id is not None and
                tok == self.eos_token_id) or self.remaining_new <= 0


class ServingScheduler:
    """Continuous-batching serving loop over an ``InferenceEngine``."""

    def __init__(self, engine, *, num_slots=8, num_pages=64, page_size=None,
                 max_pages_per_slot=None, prefill_chunk=16,
                 seq_parallel_threshold=None, prefill_reserve_frac=None,
                 max_queue=256,
                 monitor=None, do_sample=False, temperature=1.0, top_k=0,
                 top_p=1.0, completed_history=4096, decode_horizon_steps=8,
                 overlap=True, prefix_cache=False, prefix_cache_pages=None,
                 spec_decode=None, spec_k=8, spec_drafter=None,
                 kv_dtype=None,
                 shared_pool=None, pools_ref=None, on_handoff=None,
                 tracer=None, mem_telemetry=False, audit_every=None,
                 comm_telemetry=False, compile_watchdog=None,
                 online_tuner=None, tuned_from=None, tenancy=None):
        if page_size is None:
            page_size = default_page_size()
        self.engine = engine
        # per-request span tracing (serving/trace.py).  The default is
        # the shared no-op tracer: with tracing off every call site
        # costs one attribute load and a falsy check — tokens, compile
        # signatures and the hot loop are byte-identical (pinned by
        # tests/unit/test_trace.py).  Tracing is pure host bookkeeping:
        # no device op, no new jit signature, ever.
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self._t_start = time.monotonic()
        self.num_slots = int(num_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.max_queue = int(max_queue)
        # multi-tenant serving tier (serving/tenancy/): a TenantRegistry
        # turns on per-tenant quotas, weighted-fair admission, adapter
        # entitlements and prefix-cache namespaces.  tenancy=None (the
        # default) keeps every scheduler path byte-identical to the
        # pre-tenancy code: no extra arrays, no extra jit signatures
        # (pinned by tests/unit/test_tenancy.py).
        self.tenancy = tenancy if tenancy else None
        if self.tenancy is not None and not mem_telemetry:
            # quotas bill in page-seconds: the PR-11 meter must run
            mem_telemetry = True
        self._adapter_ids = None if self.tenancy is None \
            else np.full(num_slots, -1, np.int32)
        if max_pages_per_slot is None:
            max_pages_per_slot = -(-num_pages // 2) or 1
        self.kv = PagedKVManager(num_pages, page_size, num_slots,
                                 max_pages_per_slot, pool=shared_pool)
        # radix prefix cache: finished requests donate their full pages
        # to a token-keyed index; admissions longest-prefix match and
        # share the chain read-only. Cached pages are reclaimable
        # capacity (LRU-drained under pool pressure), never a leak.
        self.prefix_cache = None if not prefix_cache else PrefixCache(
            self.kv.pool, max_pages=prefix_cache_pages)
        # the device pools live behind a mutable ref so a disaggregated
        # prefill/decode pair (two schedulers, one physical pool) sees
        # each other's functional updates; standalone schedulers own a
        # private ref and behave exactly as before
        if pools_ref is None:
            # kv_dtype overrides the engine's configured kv_cache_dtype
            # for THIS scheduler's pools ("float32"/"bfloat16"/"int8"/
            # "fp8") — the serving autotuner varies it per trial on one
            # engine.  int8/fp8 pools carry parallel per-row f32 scale
            # pools; every host mechanism (COW, donation, truncate,
            # handoff) is dtype-blind because it moves page IDS
            pools_ref = _PoolsRef(engine.init_paged_cache(
                num_pages, page_size, kv_dtype=kv_dtype))
        elif kv_dtype is not None:
            raise ValueError(
                "kv_dtype cannot be set on a scheduler adopting shared "
                "pools (pools_ref=): the dtype is baked into the shared "
                "arrays — set it where the pools are built")
        self._pools_ref = pools_ref
        # live truth for health()/operators: derived from the allocated
        # leaves, not from config (a shared pool reports what it IS)
        from deepspeed_tpu.ops.quant.kv import kv_dtype_name
        self.kv_dtype_name = kv_dtype_name(
            self._pools_ref.pools["layers"][0])
        # prefill-worker hook: a request submitted with handoff=True
        # finishes its prompt, emits the boundary token, and hands its
        # page chain to this callback instead of decoding on
        self.on_handoff = on_handoff
        self._pending_attach = deque()  # handoff chains awaiting a slot
        self.draining = False
        # mesh topology snapshot: the pools (and weights) are live on
        # the engine's device mesh now — record the shape and per-device
        # KV footprint once so health()/monitor sinks expose the actual
        # serving topology (page bookkeeping below stays mesh-agnostic:
        # page ids are global, only the KV arrays shard)
        self.mesh_info = engine.serving_mesh_info(
            self.pools, num_slots=num_slots) \
            if hasattr(engine, "serving_mesh_info") else {}
        self.lengths = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.slot_req = [None] * num_slots
        self.waiting = deque()
        self.requests = {}           # rid -> LIVE request only
        # bounded terminal history: a long-running server retires
        # requests out of the live map instead of keeping them forever
        self.completed = deque(maxlen=int(completed_history))
        self._collect = None         # active run()'s result accumulator
        self.metrics = ServingMetrics(monitor)
        # memory telemetry (serving/mem_telemetry.py): page-state
        # attribution, per-request page-seconds, pressure forensics.
        # Off is the shared NULL_MEM singleton — one attribute load and
        # a falsy check per call site, tokens and compile counts
        # byte-identical (pinned by tests/unit/test_mem_telemetry.py).
        # Pass True for defaults or a MemTelemetry instance for custom
        # pressure thresholds / an attached FlightRecorder.
        if isinstance(mem_telemetry, MemTelemetry):
            if mem_telemetry.metrics is not None:
                # an instance shared by two schedulers would cross-wire
                # their gauges and corrupt both page-seconds clocks —
                # one MemTelemetry per scheduler, always
                raise ValueError(
                    "this MemTelemetry instance is already bound to "
                    "another scheduler; pass mem_telemetry=True (or a "
                    "fresh instance) per scheduler")
            self.mem = mem_telemetry
        elif mem_telemetry:
            self.mem = MemTelemetry()
        else:
            self.mem = NULL_MEM
        if self.mem.enabled:
            self.mem.bind(self.metrics, self.tracer)
            # page-granular churn events ride the pool's observer hook
            # (None when telemetry is off — the zero-cost path)
            self.kv.pool.observer = self.mem.on_pool_event
        # refcount invariant auditor: with audit_every=N every N-th
        # BARRIER step cross-checks pool refcounts against the slot
        # tables + prefix trie + parked handoff chains and raises
        # AuditError on a leak/double-free/orphan.  A shared
        # (disaggregated) pool is audited structurally only — peers
        # hold references this scheduler cannot see; the exact census
        # runs fleet-side via ClusterRouter.audit().
        self.audit_every = None if not audit_every else int(audit_every)
        self._pool_shared = shared_pool is not None
        # COMMS+COMPILE observability (the third telemetry axis after
        # time [PR 8/9] and memory [PR 11]).  comm_telemetry=True arms
        # (a) the engine's HLO comm-ledger capture — the static bytes-
        # per-axis analysis comm_ledger() computes on demand — and (b)
        # a recompile watchdog: every jit cache miss becomes a
        # `compile` span, and signature churn after warmup fires a
        # tracer instant + flight dump (compile-storm detection).  Off
        # is a None check per dispatch; tokens and compile counts are
        # byte-identical (pinned by tests/unit/test_comm_telemetry.py).
        # Pass a tracing.CompileWatchdog instance for custom warmup /
        # an attached FlightRecorder.
        from deepspeed_tpu.tracing import CompileWatchdog
        self.comm_telemetry = bool(comm_telemetry)
        if isinstance(compile_watchdog, CompileWatchdog):
            wd = compile_watchdog
            if wd.tracer is NULL_TRACER:
                wd.tracer = self.tracer
            if wd.metrics is None:
                wd.metrics = self.metrics
        elif compile_watchdog or comm_telemetry:
            # REUSE the engine's existing watchdog when one is armed:
            # compile counters, steady state and the flight-recorder
            # wiring are ENGINE-lifetime facts — a replica fleet (or a
            # rolling restart) sharing one engine must not reset storm
            # detection or orphan the counts with every fresh
            # scheduler.  The tracer/metrics funnels rebind to the
            # newest scheduler (last-wins, like the capture itself).
            wd = getattr(engine, "_compile_watchdog", None)
            if wd is None:
                wd = CompileWatchdog(tracer=self.tracer,
                                     metrics=self.metrics)
            else:
                wd.bind(tracer=self.tracer
                        if self.tracer is not NULL_TRACER else None,
                        metrics=self.metrics)
        else:
            wd = None
        self.compile_watchdog = wd
        # the watchdog/capture live on the (possibly shared) ENGINE:
        # last scheduler wins, and a telemetry-OFF scheduler DISARMS
        # stale state a dropped telemetry-on scheduler left behind —
        # otherwise its dispatches would keep paying the probes and
        # feeding a dead scheduler's watchdog (zero-cost-off contract)
        if hasattr(engine, "set_compile_watchdog"):
            if wd is not None or \
                    getattr(engine, "_compile_watchdog", None) is not None:
                engine.set_compile_watchdog(wd)
        if hasattr(engine, "enable_comm_telemetry"):
            if self.comm_telemetry:
                engine.enable_comm_telemetry()
            elif getattr(engine, "_comm_capture", None) is not None:
                engine.enable_comm_telemetry(False)
        self._comm_summary = None       # comm_ledger()'s health cache
        if self.mesh_info:
            self.metrics.record_mesh(self.mesh_info)
        self.step_idx = 0
        self._ema_step_s = None      # EWMA of step wall time (health)
        # admission feasibility uses the MEDIAN of a recent window, not
        # the EWMA: one jit-compile step (seconds) would otherwise
        # dominate the estimate for dozens of steps and shed perfectly
        # serviceable deadline-bearing requests after every cold start
        self._step_window = deque(maxlen=16)
        self._last_error = None
        # Router-HA fence state, set by the owning replica/worker:
        # the highest router epoch this scheduler has served under and
        # how many stale-epoch dispatches/requests were fenced off
        self.ha_epoch = None
        self.ha_fenced = 0
        self.sampling = dict(do_sample=do_sample, temperature=temperature,
                             top_k=top_k, top_p=top_p)
        # Decoding-policy subsystem (serving/sampling/): `self.sampling`
        # stays the LEGACY greedy path's static kwargs; every request
        # additionally carries a per-request SamplingParams (defaulting
        # to the scheduler-level knobs above).  A dispatch whose batch
        # is pure greedy — and whose scheduler default is greedy — rides
        # the legacy signatures byte-identically; anything else routes
        # through the policy twins (decode_multi_policy /
        # verify_multi_policy), where every knob is a traced per-slot
        # lane: ONE compiled signature per horizon/K bucket regardless
        # of the greedy/sampled/penalized/constrained mix.
        self.default_sampling = SamplingParams(
            do_sample=do_sample, temperature=temperature, top_k=top_k,
            top_p=top_p)
        self._default_greedy = self.default_sampling.is_greedy
        # per-slot policy mirrors, staged into device lanes at dispatch
        # (no-op encodings for greedy slots — see sampling/params.py)
        self._samp_temps = np.zeros(num_slots, np.float32)
        self._samp_topk = np.zeros(num_slots, np.int32)
        self._samp_topp = np.ones(num_slots, np.float32)
        self._samp_rep = np.ones(num_slots, np.float32)
        self._samp_pres = np.zeros(num_slots, np.float32)
        self._samp_freq = np.zeros(num_slots, np.float32)
        self._samp_keys = np.zeros((num_slots, 2), np.uint32)
        self._tok_counts = None      # lazy [num_slots, vocab] int32
        self._grammar_masks = None   # lazy [num_slots, vocab] bool
        self._grammar_cache = {}     # spec json -> prototype cursor
        # fused decode horizons: power-of-two buckets up to the max so
        # varying horizon choices share a bounded set of compiled
        # signatures (decode_horizon_steps=1 recovers the legacy
        # one-token-per-step loop exactly)
        self.decode_horizon_steps = max(1, int(decode_horizon_steps))
        buckets, b = {1}, 1
        while b < self.decode_horizon_steps:
            b = min(b * 2, self.decode_horizon_steps)
            buckets.add(b)
        self.horizon_buckets = sorted(buckets)
        # ---- sequence-parallel prefill routing (long-context path) ----
        # prompts with >= seq_parallel_threshold tokens left to prefill
        # route through engine.prefill_sequence_parallel: the chunk
        # shards over the mesh's `sequence` axis, so one step retires
        # axis_size x the per-device chunk rows.  The transport
        # (ulysses vs ring) was resolved ONCE by the engine against the
        # mesh + model (sharding.resolve_sequence_plan); an unusable
        # axis degrades every routed prompt to the chunked loop with a
        # `serving/seq_prefill/degraded` breadcrumb instead of failing.
        # Chunk lengths quantize to power-of-two multiples of the axis
        # size up to prefill_chunk * axis_size, so the compile count is
        # pinned by the bucket set exactly like decode horizons.
        self.seq_parallel_threshold = int(seq_parallel_threshold or 0)
        self.seq_plan = None
        self.sp_chunk_buckets = []
        self._sp_degrade_reason = None
        if self.seq_parallel_threshold > 0:
            plan = getattr(engine, "seq_parallel_plan", lambda: None)()
            if plan is not None and plan.usable:
                self.seq_plan = plan
                buckets, b = {plan.size}, plan.size
                top = self.prefill_chunk * plan.size
                while b < top:
                    b = min(b * 2, top)
                    buckets.add(b)
                self.sp_chunk_buckets = sorted(buckets)
            else:
                self._sp_degrade_reason = None if plan is None \
                    else plan.reason
        # fairness: cap the pages ONE prefilling request may pre-reserve
        # up front to this fraction of the pool (None = num_pages — the
        # admission-time free-pages check is then the only gate).  A
        # routed prompt whose full chain exceeds the cap is shed with
        # an explicit reason instead of starving every waiting admission
        # behind a monopolized pool.
        self.prefill_reserve_frac = None if prefill_reserve_frac is None \
            else float(prefill_reserve_frac)
        self.prefill_reserve_cap = self.kv.pool.num_pages \
            if self.prefill_reserve_frac is None else \
            max(1, int(self.kv.pool.num_pages * self.prefill_reserve_frac))
        self.overlap = bool(overlap)
        self._inflight = deque()       # dispatched horizons, FIFO, depth<=2
        self._zombies = set()          # slots terminated host-side while a
                                       # chained horizon still runs them
        self._chain_budgets = None     # budgets baseline for the live chain
        self._eos_ids = np.full(num_slots, -1, np.int32)
        self._tok_window = deque(maxlen=32)   # per-token wall time samples
        # speculative decoding: a drafter proposes K tokens per slot,
        # ONE verify_multi dispatch scores them (greedy-only — the
        # acceptance test replays the temperature=0 argmax contract, so
        # sampled mode disables spec rather than silently changing the
        # sampled stream)
        self.spec_k = max(1, int(spec_k))
        buckets, b = {1}, 1
        while b < self.spec_k:
            b = min(b * 2, self.spec_k)
            buckets.add(b)
        self.spec_k_buckets = sorted(buckets)
        self._spec = None
        self.spec_mode = "off"
        greedy = not do_sample or not temperature
        if spec_decode not in (None, False, "off", "ngram", "draft"):
            # validate the mode string unconditionally — a typo must not
            # slip through just because a custom drafter was supplied
            # (custom drafters pass spec_decode=None and name themselves
            # via their .name attribute)
            raise ValueError(f"unknown spec_decode mode {spec_decode!r}; "
                             "pick 'ngram', 'draft' (+spec_drafter) or "
                             "'off'")
        if spec_decode in ("off", False):
            pass  # explicit off wins even when a drafter is supplied
        elif spec_drafter is not None:
            self._spec = spec_drafter
            self.spec_mode = spec_decode or getattr(spec_drafter, "name",
                                                    "custom")
        elif spec_decode in ("ngram",):
            from deepspeed_tpu.serving.spec_decode import NgramDrafter
            self._spec = NgramDrafter()
            self.spec_mode = "ngram"
        elif spec_decode == "draft":
            raise ValueError(
                "spec_decode='draft' needs a spec_drafter="
                "DraftModelDrafter(...) carrying the draft engine")
        # Capability gate (replacing the old greedy-only gate): lossless
        # leftover-probability verification makes speculation
        # distribution-exact under ANY sampling policy, so sampled+spec
        # composes whenever the drafter opts in (`supports_sampling` —
        # True for the stock point-mass drafters).  A drafter without
        # the capability only loses SAMPLED slots' proposals; with a
        # sampled scheduler-wide default that is every slot, so spec is
        # disabled up front with a distinct reason.
        if self._spec is not None and not greedy and \
                not getattr(self._spec, "supports_sampling", False):
            self._spec = None
            self.spec_mode = "off (drafter lacks supports_sampling)"
        # online autotuner (autotuning/serving/online.py): bounded
        # nudges of the safely-re-resolvable knobs (decode horizon,
        # spec-K ceiling, prefix-cache retention split) from the live
        # gauges, applied at BARRIER steps only.  Off is None — one
        # falsy check per step, tokens and compile counts byte-identical
        # (pinned by tests/unit/test_serving_autotune.py).  Pass True
        # for defaults or an OnlineTuner instance for custom
        # thresholds; an instance already bound elsewhere is rejected
        # at bind (the MemTelemetry sharing rule).
        if online_tuner is True:
            from deepspeed_tpu.autotuning.serving.online import OnlineTuner
            online_tuner = OnlineTuner()
        self.online = online_tuner if online_tuner else None
        if self.online is not None:
            self.online.bind(self)
        # provenance of a tuner-emitted config (ds_serve --tuned-config
        # PATH): echoed through health() so an operator can tell a
        # hand-set config from a searched one
        self.tuned_from = tuned_from

    @property
    def pools(self):
        return self._pools_ref.pools

    @pools.setter
    def pools(self, value):
        self._pools_ref.pools = value

    # ------------------------------------------------------------- intake
    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               on_token=None, deadline_s=None, handoff=False,
               trace_ctx=None, sampling=None, seed=None, grammar=None,
               sample_offset=0, tenant=None, adapter=None):
        """Queue a request; raises :class:`QueueFull` at max_queue (the
        backpressure signal callers turn into 429/retry). ``deadline_s``
        is a relative budget: a request that cannot finish inside it is
        shed instead of served late.  ``handoff=True`` marks a
        prefill-worker request: it stops after the boundary token and
        hands its KV page chain to ``on_handoff`` (disaggregated
        serving).  ``trace_ctx`` (``{"trace_id": ..., "attempt": n}``)
        propagates a cluster-level trace id so this scheduler's spans
        for the request share the journal rid across replicas.

        Decoding policy (per request): ``sampling`` is a
        :class:`~deepspeed_tpu.serving.sampling.SamplingParams` or wire
        dict overriding the scheduler-level default; ``seed`` keys the
        request's position-keyed PRNG stream (default 0 — deterministic
        and replayable); ``grammar`` is a constraint spec
        (``{"regex": ...}`` / ``{"json_schema": ...}`` /
        ``{"response_format": "json_object"}``) compiled host-side to a
        per-step allowed-token mask; ``sample_offset`` counts tokens a
        previous life of this request already emitted (failover replay
        folds them into the prompt), so the PRNG stream and grammar
        cursor CONTINUE instead of restarting.

        Tenancy (``tenancy=`` on the scheduler): ``tenant`` names the
        owning :class:`~deepspeed_tpu.serving.tenancy.TenantConfig`
        (required — every request must be attributable for quota and
        billing); ``adapter`` optionally names a LoRA adapter from the
        tenant's entitlement set (None = base model)."""
        if self.draining:
            raise QueueFull("scheduler is draining (shutdown/restart in "
                            "progress); resubmit elsewhere")
        t_cfg, adapter_id = self._resolve_tenant(tenant, adapter)
        if len(self.waiting) >= self.max_queue:
            raise QueueFull(
                f"waiting queue at max_queue={self.max_queue}")
        need = len(np.asarray(prompt).reshape(-1)) + int(max_new_tokens)
        cap = min(self.kv.max_tokens_per_slot(),
                  self.kv.pool.num_pages * self.kv.page_size)
        if need > cap:
            raise ValueError(
                f"request of {need} tokens exceeds per-slot capacity {cap} "
                "(min(max_pages_per_slot, num_pages) * page_size)")
        req = Request(prompt, max_new_tokens, eos_token_id, on_token,
                      deadline_s=deadline_s)
        req.handoff = bool(handoff)
        if t_cfg is not None:
            req.tenant = t_cfg.name
            req.adapter = adapter
            req.adapter_id = adapter_id
        if trace_ctx is not None and trace_ctx.get("trace_id") is not None:
            req.trace_rid = trace_ctx["trace_id"]
        self._apply_policy(req, sampling, seed, grammar, sample_offset)
        self._check_adapter_policy(req)
        if req.max_new_tokens <= 0:
            # parity with generate(max_new_tokens=0): nothing to emit —
            # but it still counts as completed, so health()/summary
            # reconcile with the per-request rows ds_serve reports
            req.state = FINISHED
            self.completed.append(req)
            self.metrics.record_completion(self.step_idx)
            return req
        self.requests[req.rid] = req
        self.waiting.append(req)
        return req

    # ------------------------------------------------- decoding policy
    def _apply_policy(self, req, sampling, seed, grammar, sample_offset):
        """Attach the per-request decoding policy at intake (submit /
        attach_handoff).  Grammar compilation is host work and can
        raise — intake is the right place to reject a bad spec, before
        any pages are held.  A replayed request (``sample_offset > 0``,
        or handoff tokens already in ``out_tokens``) advances the fresh
        grammar cursor through everything previously emitted, so the
        constraint state survives preemption and failover exactly."""
        req.sampling = SamplingParams.from_dict(
            sampling, defaults=self.default_sampling)
        req.seed = 0 if seed is None else int(seed)
        req.sample_offset = max(0, int(sample_offset))
        if grammar is not None:
            req.grammar = self._compile_grammar(grammar, req.eos_token_id)
            if req.sample_offset:
                req.grammar.replay(req.prompt[-req.sample_offset:])
            if req.out_tokens:
                req.grammar.replay(req.out_tokens)
        if req.sampling.needs_policy or req.grammar is not None:
            self.metrics.record_policy_request(
                self.step_idx, sampled=not req.sampling.is_greedy,
                grammar=req.grammar is not None)

    def _compile_grammar(self, spec, eos_token_id):
        """Spec dict -> fresh :class:`GrammarConstraint` cursor.  The
        DFA + token-mask compilation is cached per (spec, eos) — many
        requests sharing one schema share one TokenDFA (and its lazily
        built per-state mask rows); each request gets its own cursor."""
        if hasattr(spec, "token_mask"):     # pre-built cursor
            return spec
        key = (json.dumps(spec, sort_keys=True),
               None if eos_token_id is None else int(eos_token_id))
        proto = self._grammar_cache.get(key)
        if proto is None:
            proto = compile_grammar(spec, self._vocab_size(),
                                    eos_token_id=eos_token_id)
            self._grammar_cache[key] = proto
        return proto.fresh()

    def _vocab_size(self):
        v = self.mesh_info.get("vocab_size")
        if v is None:
            cfg = getattr(getattr(self.engine, "module", None), "cfg",
                          None)
            v = getattr(cfg, "vocab_size", None)
        if v is None:
            raise RuntimeError(
                "engine does not expose vocab_size; the decoding-policy "
                "tables (token counts / grammar masks) need it")
        return int(v)

    @staticmethod
    def _req_needs_policy(req):
        return req.sampling.needs_policy or req.grammar is not None

    def _batch_needs_policy(self, slots):
        """True when this dispatch must take the policy twins: any
        request samples/penalizes/constrains, or the scheduler-wide
        default is sampled (explicit-greedy requests under a sampled
        default still ride the policy path — its greedy lanes are
        argmax-exact — so the legacy kwargs are never repurposed)."""
        return (not self._default_greedy) or any(
            self._req_needs_policy(self.slot_req[s]) for s in slots)

    def _ensure_policy_tables(self):
        if self._tok_counts is None:
            v = self._vocab_size()
            self._tok_counts = np.zeros((self.num_slots, v), np.int32)
            self._grammar_masks = np.ones((self.num_slots, v), bool)

    def _seed_slot_policy(self, slot, req):
        """Stage one admitted request's policy into the slot mirrors.
        Counts seed from the request's TRUE token history
        (``orig_prompt + out_tokens`` — after a preemption the folded
        prompt already contains the emitted tokens, after a handoff the
        boundary token lives only in ``out_tokens``; the union covers
        both without double counting)."""
        if not (self._req_needs_policy(req) or
                self._tok_counts is not None):
            return
        self._ensure_policy_tables()
        sp = req.sampling
        self._samp_temps[slot] = sp.staged_temperature
        self._samp_topk[slot] = 0 if sp.is_greedy else sp.top_k
        self._samp_topp[slot] = 1.0 if sp.is_greedy else sp.top_p
        self._samp_rep[slot] = sp.repetition_penalty
        self._samp_pres[slot] = sp.presence_penalty
        self._samp_freq[slot] = sp.frequency_penalty
        self._samp_keys[slot] = request_key(req.seed)
        v = self._tok_counts.shape[1]
        hist = np.asarray(req.orig_prompt + req.out_tokens, np.int64)
        hist = hist[(hist >= 0) & (hist < v)]
        self._tok_counts[slot] = np.bincount(hist, minlength=v)[:v]
        self._grammar_masks[slot] = True if req.grammar is None \
            else req.grammar.token_mask()

    def _policy_args(self, running):
        """The staged per-slot policy arrays one dispatch consumes.
        ``tok_base`` is each request's absolute position base —
        ``sample_offset + len(out_tokens)`` — so the device's in-scan
        fold index (``tok_base + emitted``) is position-keyed across
        batching, chaining, preemption and failover."""
        self._ensure_policy_tables()
        base = np.zeros(self.num_slots, np.int32)
        for s in running:
            req = self.slot_req[s]
            base[s] = req.sample_offset + len(req.out_tokens)
        return dict(keys=self._samp_keys, tok_base=base,
                    temps=self._samp_temps, top_ks=self._samp_topk,
                    top_ps=self._samp_topp, rep_pens=self._samp_rep,
                    pres_pens=self._samp_pres, freq_pens=self._samp_freq,
                    counts=self._tok_counts, mask=self._grammar_masks)

    def _note_emitted(self, slot, req, tok):
        """Host policy bookkeeping for ONE delivered token: the count
        mirror and the grammar cursor.  A grammar rejection raises
        GrammarConstraintError into the caller's per-request
        containment (it is attributable to exactly this request)."""
        if self._tok_counts is not None and \
                0 <= tok < self._tok_counts.shape[1]:
            self._tok_counts[slot, tok] += 1
        if req.grammar is not None and not req.grammar.finished:
            try:
                req.grammar.advance(tok)
            except GrammarConstraintError:
                self.metrics.record_grammar_violation(self.step_idx,
                                                      req.rid)
                raise

    def _grammar_finished(self, req):
        """A constrained request finishes when its cursor is done (eos
        consumed, or the DFA has no continuation left) — even if the
        model never emits eos."""
        return req.grammar is not None and req.grammar.done

    # ----------------------------------------------------------- tenancy
    def _resolve_tenant(self, tenant, adapter):
        """Intake-side tenancy resolution -> (TenantConfig, adapter_id).
        With tenancy on every request must name a registered tenant (an
        unattributable request cannot be quota-gated or billed); with
        tenancy off the kwargs must stay unused."""
        if self.tenancy is None:
            if tenant is not None or adapter is not None:
                raise ValueError(
                    "tenant=/adapter= need ServingScheduler(tenancy="
                    "TenantRegistry(...)); this scheduler has no tenancy")
            return None, -1
        if tenant is None:
            raise ValueError(
                "tenancy is on: every submit()/attach_handoff() must "
                "name its tenant= for quota accounting and billing")
        return self.tenancy.resolve(tenant, adapter)

    def _check_adapter_policy(self, req):
        """Multi-LoRA rides the LEGACY greedy signatures only (the
        per-slot adapter gather is threaded through prefill /
        decode_multi / verify_multi, not the policy twins).  With
        adapters loaded, a policy-needing request — or a sampled
        scheduler default — would force the whole batch onto the policy
        path and silently drop its peers' adapter deltas, so it is
        rejected at intake instead."""
        if self.tenancy is None or self.tenancy.store is None or \
                not len(self.tenancy.store):
            return
        if self._req_needs_policy(req) or not self._default_greedy:
            raise ValueError(
                "multi-LoRA serving rides the greedy decode path: "
                "per-request sampling/grammar (and a sampled scheduler "
                "default) cannot batch with adapter slots — serve "
                "policy traffic from a scheduler without adapters")

    def _req_ns(self, req):
        """Prefix-cache namespace for one request: ``None`` (the legacy
        shared root) with tenancy off, else ``(tenant namespace,
        adapter)`` — cached KV depends on the adapter weights that
        wrote it, so the adapter is part of the key (the isolation
        oracle in tests/unit/test_tenancy.py)."""
        if self.tenancy is None or req.tenant is None:
            return None
        return self.tenancy.namespace(req.tenant, req.adapter)

    def _tenant_namespaces(self, tenant):
        """Every radix namespace a tenant's pages can live under: the
        base-model namespace plus one per entitled adapter."""
        t = self.tenancy.get(tenant)
        return [self.tenancy.namespace(t, a)
                for a in (None,) + tuple(t.adapters)]

    def _tenant_pages(self, tenant):
        """A tenant's CONCURRENT page footprint — the unit its
        ``page_quota`` caps: live slot pages + parked handoff chains +
        its namespaces' cached prefix pages, each physical page counted
        once (a cache page a live slot shares is still one page)."""
        held = set()
        for s in range(self.num_slots):
            r = self.slot_req[s]
            if r is not None and r.tenant == tenant:
                held.update(self.kv._slot_pages[s])
        for r in self._pending_attach:
            if r.tenant == tenant:
                held.update(r._attach[0])
        if self.prefix_cache is not None:
            for ns in self._tenant_namespaces(tenant):
                held.update(self.prefix_cache.ns_iter_pages(ns))
        return len(held)

    def _tenant_live(self, tenant):
        """True while the tenant has pages that will free on their own
        (running slots or parked handoff chains) — the at-quota case
        where its queue head WAITS instead of being shed."""
        return any(r is not None and r.tenant == tenant
                   for r in self.slot_req) or \
            any(r.tenant == tenant for r in self._pending_attach)

    def _adapter_args(self):
        """The (adapter_ids, device pack) side inputs one legacy
        dispatch carries.  (None, None) — the pre-tenancy leafless
        pytree, SAME jit signature — unless tenancy is on with a
        non-empty adapter store; with adapters loaded every dispatch
        carries the pack (ids are traced data, so adapter churn and
        base-only batches share one signature per horizon bucket)."""
        if self.tenancy is None or self.tenancy.store is None or \
                not len(self.tenancy.store):
            return None, None
        return self._adapter_ids, self.tenancy.store.pack()

    def _release_adapter(self, slot):
        if self._adapter_ids is not None:
            self._adapter_ids[slot] = -1

    def _pick_waiting(self, skip=frozenset()):
        """The next admission candidate (still IN ``self.waiting``):
        plain FIFO head with tenancy off; with tenancy on, weighted
        deficit round-robin over the per-tenant FIFO heads, costed in
        pages (``skip`` holds tenants parked at quota this round), so a
        burst tenant converges to its weight share of admissions and
        cannot starve a lighter one (the starvation oracle)."""
        if self.tenancy is None:
            return self.waiting[0] if self.waiting else None
        heads = {}
        for r in self.waiting:
            if r.tenant not in skip and r.tenant not in heads:
                heads[r.tenant] = r
        if not heads:
            return None
        costs = {t: max(1, self.kv.pool.pages_for_tokens(len(r.prompt)))
                 for t, r in heads.items()}
        return heads[self.tenancy.next_tenant(costs)]

    def _check_quota(self, req, need, protect):
        """Quota gate for one candidate admission.  Returns ``"admit"``,
        ``"wait"`` (at quota, but the tenant's own live/parked work
        will free pages — park its queue this round), or a shed-reason
        string (the request can never fit the quota).  A tenant over
        quota drains its OWN namespaces' cached pages first; it can
        never evict another tenant's pages (capacity isolation)."""
        if self.tenancy is None:
            return "admit"
        quota = self.tenancy.get(req.tenant).page_quota
        if quota is None:
            return "admit"
        if need > quota:
            return (f"tenant page quota: request needs {need} pages, "
                    f"{req.tenant}'s quota is {quota}")
        held = self._tenant_pages(req.tenant)
        over = held + need - quota
        if over > 0 and self.prefix_cache is not None:
            drained = 0
            for ns in self._tenant_namespaces(req.tenant):
                drained += self.prefix_cache.evict(over - drained,
                                                   protect, ns=ns)
                if drained >= over:
                    break
            if drained:
                self.metrics.record_cache_eviction(self.step_idx, drained)
                over -= drained
        if over <= 0:
            return "admit"
        if self._tenant_live(req.tenant):
            return "wait"
        return (f"tenant page quota: {req.tenant} holds {held} page(s) "
                f"+ {need} needed > quota {quota} with nothing left "
                "to drain")

    # --------------------------------------------------------- accounting
    def _emit(self, req, tok):
        # fault point: a raised exception here is attributable to THIS
        # request — the containment wrappers fail it, not the loop
        faults.fire("serve.request", step=self.step_idx, rid=req.rid)
        now = time.monotonic()
        tok = int(tok)
        req.out_tokens.append(tok)
        if req.t_first is None:
            req.t_first = now
            self.metrics.record_first_token(self.step_idx,
                                            now - req.t_submit)
        else:
            self.metrics.record_token(self.step_idx, now - req.t_last)
        req.t_last = now
        if req.on_token is not None:
            req.on_token(req, tok)

    def _finalize(self, req, state, reason=None):
        """Move a request from live bookkeeping to the bounded terminal
        history ("drain on retire")."""
        req.state = state
        if reason is not None:
            req.error = reason
        self.requests.pop(req.rid, None)
        self.completed.append(req)
        if self.tenancy is not None and req.tenant is not None:
            # chargeback at retirement: the PR-11 page-seconds integral
            # (and the hwm/token counters) land on the tenant's ledger
            # exactly once, whatever the terminal state
            self.tenancy.bill(req.tenant, page_seconds=req.page_seconds,
                              pages_hwm=req.pages_hwm,
                              tokens=len(req.out_tokens))
            if state in (FINISHED, HANDOFF):
                self.tenancy.note(req.tenant, "completed")
            elif state == SHED:
                self.tenancy.note(req.tenant, "shed")
        if self.tracer.enabled:
            # one span per request covering its whole scheduler life —
            # the top-level row a per-request trace view groups under
            args = {"state": state, "tokens": len(req.out_tokens)}
            if reason is not None:
                args["reason"] = reason
            self.tracer.complete("request", req.t_submit, time.monotonic(),
                                 cat="request", rid=req.trace_rid,
                                 args=args)

    def _donate_pages(self, slot, req):
        """Retirement hands the slot's FULL pages to the prefix cache
        instead of freeing them.  The true token sequence is
        ``orig_prompt + out_tokens`` — NOT ``req.prompt``, which after a
        preemption already contains the then-emitted tokens folded in
        (keying on it would duplicate them and donate pages under keys
        their KV does not match).  The KV-valid length drops the final
        sampled token (eos / budget boundary): it was never fed back, so
        its KV was never written — donating past it would break the
        coherence invariant.  Pages the cache declines (duplicate
        chains, cap) and the partial tail are released normally."""
        seq = req.orig_prompt + req.out_tokens
        n_full = max(0, len(seq) - 1) // self.kv.page_size
        pages = self.kv.take_slot_pages(slot)
        keep, tail = pages[:n_full], pages[n_full:]
        leftover = self.prefix_cache.insert(
            seq, keep, ns=self._req_ns(req)) if keep else []
        self.kv.pool.free(leftover + tail)

    def _spec_release(self, slot, req):
        """Drop any drafter state for a vacated slot (every terminal and
        preemption path funnels through here, so a stateful drafter —
        the draft model's private KV pages — cannot leak)."""
        if self._spec is not None and req is not None:
            try:
                self._spec.on_release(slot, req)
            except Exception:   # a broken drafter must not break retire
                pass

    def _retire(self, slot):
        req = self.slot_req[slot]
        self._spec_release(slot, req)
        if self.prefix_cache is not None:
            self._donate_pages(slot, req)
        else:
            self.kv.release_slot(slot)
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        self._release_adapter(slot)
        self._finalize(req, FINISHED)
        if self._collect is not None:
            # run()'s result set stays complete even after the bounded
            # history evicts this request
            self._collect[req.rid] = list(req.out_tokens)
        self.metrics.record_completion(self.step_idx)

    def _close_slot(self, slot, state, reason):
        """Terminal removal of a live slot for cancel/shed/fail: release
        pages at the step boundary, record the reason distinctly."""
        req = self.slot_req[slot]
        self._spec_release(slot, req)
        self.kv.release_slot(slot)
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        self._release_adapter(slot)
        self._finalize(req, state, reason)
        self.metrics.record_terminal(self.step_idx, state, req.rid, reason)
        if state == FAILED:
            self._last_error = f"rid={req.rid}: {reason}"

    def _drop_waiting(self, req, state, reason):
        self._finalize(req, state, reason)
        self.metrics.record_terminal(self.step_idx, state, req.rid, reason)

    def _preempt_youngest(self, protect=None, chain=None):
        """Evict the most recently admitted live request (vLLM's
        recompute preemption), re-queueing it at the queue head. Returns
        the freed slot or None if there was nothing to evict.
        ``chain`` is the caller's pressure causal chain: the eviction is
        recorded on it with the victim's rid, so a forensics reader can
        answer "who was evicted, for whom, and after what"."""
        candidates = [s for s in range(self.num_slots)
                      if self.slot_req[s] is not None and s != protect]
        if not candidates:
            candidates = [protect] if protect is not None and \
                self.slot_req[protect] is not None else []
        if not candidates:
            return None
        if self.tenancy is not None and protect is not None and \
                self.slot_req[protect] is not None:
            # capacity isolation: a grower whose tenant is at/over its
            # quota preempts ITS OWN youngest request when it has one —
            # a quota-capped tenant never evicts another tenant's work
            grower = self.slot_req[protect].tenant
            quota = None if grower is None \
                else self.tenancy.get(grower).page_quota
            if quota is not None and self._tenant_pages(grower) >= quota:
                own = [s for s in candidates
                       if self.slot_req[s].tenant == grower]
                if own:
                    candidates = own
        victim = max(candidates, key=lambda s: self.slot_req[s].t_admit)
        req = self.slot_req[victim]
        if chain is not None:
            chain.add("evict", victim_slot=victim, victim_rid=req.rid,
                      pages_freed=len(self.kv._slot_pages[victim]))
        self._spec_release(victim, req)
        self.kv.release_slot(victim)
        self.slot_req[victim] = None
        self.lengths[victim] = 0
        self._release_adapter(victim)
        req.state = WAITING
        req.prompt = req.orig_prompt + req.out_tokens
        req.prefill_pos = 0
        self.waiting.appendleft(req)
        self.metrics.record_preemption(self.step_idx)
        if self.tenancy is not None and req.tenant is not None:
            self.tenancy.note(req.tenant, "preempted")
        return victim

    def _reclaim_cached(self, n_pages, protect=frozenset()):
        """Drain up to ``n_pages`` refcount-free cached pages (LRU) back
        into the free list.  Returns pages actually freed (0 when the
        cache is off, empty, or fully pinned by live sharers)."""
        if self.prefix_cache is None or n_pages <= 0:
            return 0
        freed = self.prefix_cache.evict(n_pages, protect)
        if freed:
            self.metrics.record_cache_eviction(self.step_idx, freed)
        return freed

    def _grow_or_evict(self, slot, target_len):
        """ensure_capacity with the reclaim/eviction policy behind it:
        under pool pressure, refcount-free CACHED pages drain first
        (they are reclaimable capacity, not live state), then the
        legacy preempt-the-youngest eviction runs. Returns False when
        ``slot`` itself was preempted. Raises
        :class:`PagePoolExhausted` on a genuine dead-end (cache drained
        AND no evictable victim) — callers shed the slot's request
        rather than letting the loop die.  Every pressure resolution
        records a causal chain on the memory telemetry (trigger ->
        drained cache pages -> evicted victim rid -> outcome); the
        no-pressure fast path records nothing."""
        req = self.slot_req[slot]
        chain = None
        try:
            faults.fire("serve.page_alloc", step=self.step_idx, slot=slot,
                        rid=None if req is None else req.rid)
        except PagePoolExhausted:
            # an injected exhaustion episode models pool pressure: the
            # cache must drain before any victim is shed — only a
            # drained cache makes the episode terminal
            if self.mem.enabled:
                chain = self._open_pressure_chain(
                    "grow", slot, req, target_len,
                    injected_exhaustion=True)
            drained = self._reclaim_cached(self.kv.pool.num_pages)
            if chain is not None and drained:
                chain.add("cache_drain", pages=drained)
            if not drained:
                if chain is not None:
                    chain.close("dead_end")
                raise
        while not self.kv.ensure_capacity(slot, target_len):
            if chain is None and self.mem.enabled:
                chain = self._open_pressure_chain("grow", slot, req,
                                                  target_len)
            # reclaim the whole known shortfall in ONE batched drain
            # (evict() amortizes its tree scans per layer, not per page)
            short = self.kv.pages_needed(slot, target_len) - \
                self.kv.pool.free_pages
            drained = self._reclaim_cached(max(1, short))
            if drained:
                if chain is not None:
                    chain.add("cache_drain", pages=drained)
                continue
            victim = self._preempt_youngest(protect=slot, chain=chain)
            if victim is None:
                if chain is not None:
                    chain.close("dead_end")
                raise PagePoolExhausted(
                    f"cannot grow slot {slot} to {target_len} tokens: "
                    "pool exhausted with no evictable request")
            if victim == slot:
                if chain is not None:
                    chain.close("self_preempted")
                return False
        if chain is not None:
            chain.close("grown")
        return True

    def _open_pressure_chain(self, trigger, slot, req, target_len,
                             **extra):
        return self.mem.chain(
            trigger, step=self.step_idx, slot=slot,
            rid=None if req is None else req.trace_rid,
            target_len=int(target_len),
            pages_needed=self.kv.pages_needed(slot, target_len),
            free_pages=self.kv.pool.free_pages, **extra)

    # ----------------------------------------------------- failure policy
    def _estimated_service_steps(self, req):
        """Scheduler iterations this request still needs if admitted
        now: remaining prefill chunks + one decode horizon per
        ``decode_horizon_steps`` remaining tokens (ignores queueing
        ahead of it — a deliberately optimistic bound, so shedding only
        fires on certainly-hopeless requests).  With the prefix cache
        on, tokens a hit would skip are subtracted — a request the
        cache makes feasible must not be shed for the prefill it will
        never run (match() is a pure host trie walk, cheap enough to
        price in here)."""
        pending = max(0, len(req.prompt) - req.prefill_pos)
        if self.prefix_cache is not None and req.prefill_pos == 0 \
                and pending > 1:
            full, _, plen = self.prefix_cache.match(
                req.prompt, limit=len(req.prompt) - 1,
                ns=self._req_ns(req))
            pending = max(1, pending - len(full) * self.kv.page_size
                          - plen)
        chunk = self.prefill_chunk
        if self.seq_plan is not None and self.seq_parallel_threshold > 0 \
                and pending >= self.seq_parallel_threshold:
            # priced at the widest sp bucket: routed prompts retire
            # axis_size x prefill_chunk tokens per step
            chunk = self.sp_chunk_buckets[-1]
        prefill = -(-pending // chunk)
        horizons = -(-max(1, req.remaining_new) // self.decode_horizon_steps)
        return prefill + horizons

    def _step_s_estimate(self):
        """Robust per-step wall-time estimate for admission decisions:
        median over a recent window (compile spikes must not starve
        admissions), None until there are at least two samples."""
        if len(self._step_window) < 2:
            return None
        return float(np.median(self._step_window))

    def _infeasible(self, req, now):
        est = self._step_s_estimate()
        if req.deadline is None or est is None:
            return False
        eta = now + self._estimated_service_steps(req) * est
        return eta > req.deadline

    def _sweep(self, now):
        """Step-boundary honoring of cancellations and deadlines, for
        both queued and running requests.  ``now`` is the phase's single
        timestamp: every decision in one sweep prices time identically."""
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None:
                continue
            if req.cancelled:
                self._close_slot(slot, CANCELLED, "cancelled")
            elif req.past_deadline(now):
                self._close_slot(slot, SHED, "deadline expired mid-flight")
        if any(r.cancelled or r.past_deadline(now) for r in self.waiting):
            keep = deque()
            for req in self.waiting:
                if req.cancelled:
                    self._drop_waiting(req, CANCELLED, "cancelled")
                elif req.past_deadline(now):
                    self._drop_waiting(req, SHED,
                                       "deadline expired in queue")
                else:
                    keep.append(req)
            self.waiting = keep

    # -------------------------------------------------------------- step
    def step(self):
        """One scheduler iteration; returns True if any work remains.

        One iteration dispatches (and harvests) one fused decode
        *horizon* — up to ``decode_horizon_steps`` tokens per running
        slot — rather than a single token.  Boundary work (sweep, admit,
        prefill) runs on every step whose host state is authoritative,
        i.e. every step that is not a purely chained continuation of an
        in-flight horizon."""
        self.step_idx += 1
        t_step = time.monotonic()
        # fault point: slow-step / loop-level fault injection. Fires per
        # HORIZON since the fused-decode change — with
        # decode_horizon_steps > 1 a "step" covers up to that many
        # tokens (docs/resilience.md documents the timing change).
        faults.fire("serve.step", step=self.step_idx)

        t_wait, pulled = 0.0, 0
        chained = False
        if self._inflight:
            if self.overlap:
                # overlap: put the NEXT horizon on the device before
                # doing this one's host bookkeeping
                chained = self._try_chain()
            w, n = self._harvest()
            t_wait += w
            pulled += n
        if not chained:
            # conservative barrier: membership may change below, so no
            # horizon may remain in flight (its page-table snapshot
            # would go stale and eviction could corrupt live pages)
            while self._inflight:
                w, n = self._harvest()
                t_wait += w
                pulled += n
            now = time.monotonic()
            # 1. cancellations + deadlines leave at the boundary
            self._sweep(now)
            # 2. admit waiting requests into free slots (retirement
            # happens at harvest, so slots are already recycled);
            # handoff chains go first — their pages are already held
            self._admit_attached(now)
            self._admit(now)
            # 3. one prompt chunk per prefilling slot (chunked prefill)
            self._prefill()
            # 4. dispatch ONE fused decode horizon over running slots
            self._dispatch()
            if not self.overlap and self._inflight:
                w, n = self._harvest()
                t_wait += w
                pulled += n

        # 5. observability
        dt = time.monotonic() - t_step
        self._step_window.append(dt)
        if pulled:
            self._tok_window.append(dt / pulled)
        self._ema_step_s = dt if self._ema_step_s is None \
            else 0.8 * self._ema_step_s + 0.2 * dt
        n_running = sum(r is not None for r in self.slot_req)
        self.metrics.record_step(
            self.step_idx, queue_depth=len(self.waiting),
            running=n_running, waiting=len(self.waiting),
            page_utilization=self.kv.utilization(),
            device_wait_s=t_wait, host_s=max(0.0, dt - t_wait),
            cached_pages=None if self.prefix_cache is None
            else self.prefix_cache.cached_pages)
        if self.mem.enabled:
            # rolling page-state attribution + per-request page-seconds
            # + sustained-pressure detection (one host sweep per step)
            self.mem.on_step(self)
        if self.tenancy is not None and not chained:
            # scalar tenancy gauges per barrier step; the per-tenant
            # split rides health()["tenants"] (scalar-only sinks)
            pages = {t: self._tenant_pages(t)
                     for t in self.tenancy.tenants}
            self.metrics.record_tenants(
                self.step_idx,
                active=sum(1 for p in pages.values() if p),
                page_seconds=sum(u.page_seconds for u in
                                 self.tenancy.usage.values()),
                max_share=max(pages.values()) / self.kv.pool.num_pages)
        if self.audit_every and not chained and \
                self.step_idx % self.audit_every == 0:
            # barrier steps only: a chained step's host view is not
            # authoritative, but page refcounts are — we still skip it
            # to keep audit cadence aligned with host-authoritative
            # bookkeeping (and off the overlap hot path)
            self.audit()
        if self.compile_watchdog is not None:
            # auto-steady ticker: after steady_after_steps quiet steps
            # the watchdog arms and further signature churn is a
            # detection, not warmup (owner-gated: on a shared engine
            # only the current owner's steps advance the counter)
            self.compile_watchdog.step(owner=self.metrics)
        if self.online is not None and not chained:
            # online tuner nudges ride BARRIER steps only: knob changes
            # must land on host-authoritative state, never while a
            # chained horizon's stale snapshot is in flight.  Every
            # nudge stays inside the construction-time bucket sets, so
            # the compiled-signature story is untouched.
            self.online.on_step(self)
        return bool(self.waiting) or n_running > 0 or \
            bool(self._inflight) or bool(self._pending_attach)

    # ------------------------------------------------- boundary phases
    def _admit(self, now):
        at_quota = set()   # tenants parked this round: at quota, with
                           # their own live/parked pages still draining
        for slot in range(self.num_slots):
            if self.slot_req[slot] is not None or slot in self._zombies:
                continue
            req = hit = None
            need, protect = 0, frozenset()
            while self.waiting:
                req = self._pick_waiting(at_quota)
                if req is None:
                    break
                # deadline-aware admission: shed what cannot finish in
                # time instead of admitting it and wasting pool pages
                if self._infeasible(req, now):
                    self.waiting.remove(req)
                    self._drop_waiting(
                        req, SHED,
                        f"deadline infeasible at admission "
                        f"(needs ~{self._estimated_service_steps(req)} "
                        f"steps at "
                        f"{self._step_s_estimate() * 1e3:.1f} ms/step)")
                    req = None
                    continue
                hit = None
                if self.prefix_cache is not None:
                    # longest-prefix match, capped at len(prompt)-1 so
                    # at least one prompt token remains to prefill (the
                    # boundary logits the first sampled token comes
                    # from); namespaced per (tenant, adapter) — a
                    # cross-tenant identical prompt can never hit
                    hit = self.prefix_cache.match(
                        req.prompt, limit=len(req.prompt) - 1,
                        ns=self._req_ns(req))
                # admission control: the UNIQUE part of the prompt must
                # fit now — matched full pages are shared, not
                # allocated, and refcount-free cached pages count as
                # reclaimable capacity (drained on demand, with the
                # matched chain protected)
                need = self.kv.pool.pages_for_tokens(len(req.prompt))
                protect = frozenset()
                if hit is not None:
                    need -= len(hit[0])
                    protect = frozenset(
                        id(n) for n in hit[0] +
                        ([hit[1]] if hit[1] is not None else []))
                verdict = self._check_quota(req, need, protect)
                if verdict == "admit":
                    break
                if verdict == "wait":
                    # backlogged at quota: its own retirements will
                    # free pages — park the tenant, try the next one
                    at_quota.add(req.tenant)
                else:
                    self.waiting.remove(req)
                    self._drop_waiting(req, SHED, verdict)
                    self.metrics.record_quota_shed(self.step_idx)
                req = None
            if req is None:
                break
            short = need - self.kv.pool.free_pages
            if short > 0:
                chain = self.mem.chain(
                    "admission", step=self.step_idx, rid=req.trace_rid,
                    pages_needed=need,
                    free_pages=self.kv.pool.free_pages) \
                    if self.mem.enabled else None
                # pre-check with the EXACT drainable count (under the
                # same protect set the drain will honor) before touching
                # the cache: a shortfall the drain provably cannot cover
                # must not destroy the cache every step while the head
                # request stays blocked anyway
                if self.prefix_cache is None or short > \
                        self.prefix_cache.reclaimable_pages(protect):
                    if chain is not None:
                        chain.close("blocked")
                    break
                drained = self._reclaim_cached(short, protect)
                if chain is not None:
                    chain.add("cache_drain", pages=drained)
                    chain.close("admitted" if drained >= short
                                else "blocked")
                if drained < short:
                    break
            self.waiting.remove(req)
            self.slot_req[slot] = req
            req.state = PREFILL
            # one timestamp per phase: admission decisions within a step
            # price time identically (no per-slot clock reads)
            req.t_admit = now
            if self.tracer.enabled:
                # the queue-wait phase closes at admission: submit->admit
                self.tracer.complete("queued", req.t_submit, now,
                                     cat="lifecycle", rid=req.trace_rid,
                                     args={"slot": slot})
            self._eos_ids[slot] = -1 if req.eos_token_id is None \
                else int(req.eos_token_id)
            self._seed_slot_policy(slot, req)
            if self.tenancy is not None:
                self._adapter_ids[slot] = req.adapter_id
                self.tenancy.note(req.tenant, "admitted")
            self.lengths[slot] = 0
            req.cached_prefix_tokens = 0
            if hit is not None:
                try:
                    self._attach_prefix(slot, req, hit)
                except Exception as e:   # containment: the attach (incl.
                    # the COW device copy) is per-request work — fail
                    # ONE request, never the admission loop
                    self._close_slot(slot, FAILED,
                                     f"{type(e).__name__}: {e}")
            if self.slot_req[slot] is req:
                self._route_seq_parallel(slot, req)

    def _attach_prefix(self, slot, req, hit):
        """Map a matched cached chain into the admitted slot: full pages
        are shared read-only (refcount++), a partially matched page is
        duplicated on-device into a fresh PRIVATE page (copy-on-write —
        decode will append into it, and the cached original must stay
        immutable for its other readers).  Prefill then resumes from the
        cached boundary: ``lengths[slot]`` seeds the position/rotary
        offset, so the jit signature is untouched."""
        full_nodes, pnode, plen = hit
        cached = 0
        if full_nodes:
            self.kv.attach_prefix(slot,
                                  self.prefix_cache.acquire(full_nodes))
            cached = len(full_nodes) * self.kv.page_size
        if pnode is not None and self.kv.pool.can_allocate(1):
            page = self.kv.pool.allocate(1)[0]
            # adopt BEFORE the device copy: if the copy throws, the
            # containment close releases the page with the slot instead
            # of leaking it
            self.kv.adopt_page(slot, page)
            with self.tracer.span("cow_copy", track=slot,
                                  rid=req.trace_rid,
                                  args={"src_page": pnode.page,
                                        "dst_page": page}
                                  if self.tracer.enabled else None):
                self.pools = self.engine.copy_page(self.pools, pnode.page,
                                                   page)
            self.prefix_cache.touch(pnode)
            self.prefix_cache.cow_copies += 1
            cached += plen
        if cached:
            self.prefix_cache.tokens_reused += cached
            self.lengths[slot] = cached
            req.prefill_pos = cached
            req.cached_prefix_tokens = cached
        # one lookup per ADMISSION, counted when the outcome is known —
        # a hit iff tokens were actually reused (match() itself is
        # pure, so a capacity-blocked request re-matched every step
        # cannot inflate the rate, and health()'s hit rate counts the
        # same event as metrics.summary()'s)
        self.prefix_cache.lookups += 1
        if cached:
            self.prefix_cache.hits += 1
            if self.tracer.enabled:
                self.tracer.instant("prefix_hit", track=slot,
                                    rid=req.trace_rid,
                                    args={"cached_tokens": cached,
                                          "prompt_tokens":
                                          len(req.prompt)})
        self.metrics.record_prefix(self.step_idx, cached, len(req.prompt))

    def _route_seq_parallel(self, slot, req):
        """Admission-time routing onto the sequence-parallel prefill
        path.  A routed prompt pre-reserves its FULL page chain up
        front: the wide sharded chunks retire ``axis_size`` pages of KV
        per dispatch, and an allocation stall mid-chunk would waste the
        whole collective.  Reservation is fairness-capped
        (``prefill_reserve_frac``): a prompt whose chain exceeds the
        cap is shed with an explicit reason, because holding most of
        the pool through a long prefill starves every short request
        behind it.  Degrades (no usable axis, reservation
        self-preempted) fall back to the chunked loop with a
        breadcrumb — routing is an optimization, never a correctness
        gate."""
        req.seq_parallel = False
        pending = len(req.prompt) - req.prefill_pos
        if self.seq_parallel_threshold <= 0 \
                or pending < self.seq_parallel_threshold:
            return
        if req.adapter_id >= 0:
            # the sp closure carries no adapter side input: an adapter
            # request degrades to the chunked loop (which does) with a
            # breadcrumb — routing is an optimization, never a
            # correctness gate
            self.metrics.record_seq_prefill_degrade(self.step_idx)
            if self.tracer.enabled:
                self.tracer.instant(
                    "seq_prefill_degrade", track=slot, rid=req.trace_rid,
                    args={"reason": "lora adapter slot"})
            return
        if self.seq_plan is None:
            self.metrics.record_seq_prefill_degrade(self.step_idx)
            if self.tracer.enabled:
                self.tracer.instant(
                    "seq_prefill_degrade", track=slot, rid=req.trace_rid,
                    args={"reason": self._sp_degrade_reason})
            return
        need = self.kv.pages_needed(slot, len(req.prompt))
        if need > self.prefill_reserve_cap:
            self.metrics.record_seq_prefill_shed(self.step_idx, need)
            self._close_slot(
                slot, SHED,
                f"seq-parallel reserve cap: prompt needs {need} pages, "
                f"cap is {self.prefill_reserve_cap} of "
                f"{self.kv.pool.num_pages}")
            return
        try:
            if not self._grow_or_evict(slot, len(req.prompt)):
                # reservation pressure evicted THIS request; it is back
                # in the waiting queue and will re-route on re-admission
                return
        except (PagePoolExhausted, ValueError) as e:
            self._close_slot(slot, SHED, f"page capacity: {e}")
            return
        req.seq_parallel = True
        self.metrics.record_seq_prefill_route(self.step_idx, pending, need)
        if self.tracer.enabled:
            self.tracer.instant(
                "seq_prefill_route", track=slot, rid=req.trace_rid,
                args={"tokens": pending, "reserved_pages": need,
                      "impl": self.seq_plan.impl})

    def _sp_chunk(self, pending):
        """Smallest sp chunk bucket covering ``pending`` tokens (the
        largest bucket when none does) — same quantization idea as the
        decode-horizon buckets, pinning one jit signature per bucket."""
        for b in self.sp_chunk_buckets:
            if b >= pending:
                return b
        return self.sp_chunk_buckets[-1]

    def _prefill(self):
        """One prompt chunk per prefilling slot.  The per-slot body is
        attributable to ONE request, so containment wraps it: a
        per-request failure frees the slot and moves on.  Slots
        finishing their prompt this step sample their first token in
        ONE batched device call instead of one tiny dispatch each."""
        finishing = []
        for slot in range(self.num_slots):
            req = self.slot_req[slot]
            if req is None or req.state != PREFILL:
                continue
            try:
                sp = getattr(req, "seq_parallel", False) \
                    and self.seq_plan is not None
                width = self._sp_chunk(len(req.prompt) - req.prefill_pos) \
                    if sp else self.prefill_chunk
                chunk = req.prompt[req.prefill_pos:
                                   req.prefill_pos + width]
                n_valid = len(chunk)
                if not self._grow_or_evict(slot, req.prefill_pos + n_valid):
                    continue      # self-preempted: back in the queue
                ids = np.zeros((1, width), np.int32)
                ids[0, :n_valid] = chunk
                with self.tracer.span(
                        "prefill_chunk", track=slot, rid=req.trace_rid,
                        args={"tokens": n_valid, "pos": req.prefill_pos,
                              "seq_parallel": sp}
                        if self.tracer.enabled else None):
                    if sp:
                        logits, self.pools = \
                            self.engine.prefill_sequence_parallel(
                                ids, slot, n_valid, self.kv.table,
                                self.lengths, self.pools)
                    else:
                        a_ids, a_pack = self._adapter_args()
                        logits, self.pools = \
                            self.engine.prefill_into_slots(
                                ids, slot, n_valid, self.kv.table,
                                self.lengths, self.pools,
                                adapter_ids=a_ids, adapters=a_pack)
                if sp:
                    self.metrics.record_seq_prefill_chunk(self.step_idx,
                                                          n_valid)
                self.lengths[slot] += n_valid
                req.prefill_pos += n_valid
                if req.prefill_pos == len(req.prompt):
                    finishing.append((slot, req, logits))
            except PagePoolExhausted as e:
                self._close_slot(slot, SHED, f"page capacity: {e}")
            except Exception as e:   # containment: fail one, not all
                self._close_slot(slot, FAILED,
                                 f"{type(e).__name__}: {e}")
        # a later slot's growth may have evicted an earlier finishing
        # slot — drop stale entries BEFORE the batched sample (the
        # policy-table gathers index by slot, so a vacated slot must
        # not reach them)
        finishing = [(s, r, lg) for s, r, lg in finishing
                     if self.slot_req[s] is r and r.state == PREFILL]
        if not finishing:
            return
        # the batched sample is shared work (like the decode dispatch);
        # emit/callback stays contained per request below
        rows = [lg for _, _, lg in finishing]
        if self._batch_needs_policy([s for s, _, _ in finishing]):
            # boundary token under the decoding policy: same pipeline,
            # same position-keyed stream as the fused decode (token 0
            # of the request draws from fold_in(key, sample_offset))
            self._ensure_policy_tables()
            sl = [s for s, _, _ in finishing]
            idx = np.array([r.sample_offset + len(r.out_tokens)
                            for _, r, _ in finishing], np.int32)
            toks = self.engine.sample_from_logits_policy(
                rows, self._samp_keys[sl], idx, self._samp_temps[sl],
                self._samp_topk[sl], self._samp_topp[sl],
                self._samp_rep[sl], self._samp_pres[sl],
                self._samp_freq[sl], self._tok_counts[sl],
                self._grammar_masks[sl])
        else:
            toks = self.engine.sample_from_logits(rows, **self.sampling)
        for (slot, req, _), tok in zip(finishing, toks):
            if self.slot_req[slot] is not req or req.state != PREFILL:
                continue   # a later slot's growth evicted this one
            try:
                self._emit(req, tok)
                self._note_emitted(slot, req, tok)
            except Exception as e:
                self._close_slot(slot, FAILED, f"{type(e).__name__}: {e}")
                continue
            if req._finished_by(tok) or self._grammar_finished(req):
                self._retire(slot)
            elif req.handoff and self.on_handoff is not None:
                self._do_handoff(slot, req, tok)
            else:
                self.last_tok[slot] = tok
                req.state = RUNNING
                if req.grammar is not None:
                    self._grammar_masks[slot] = req.grammar.token_mask()

    # ------------------------------------------------ disaggregated KV
    def _do_handoff(self, slot, req, tok):
        """Prefill-worker epilogue: the prompt's KV is complete and the
        boundary token is emitted — detach the slot's page chain (pool
        references travel with it) and hand (pages, prefilled length,
        boundary token) to ``on_handoff`` for a decode worker to adopt.
        The callback is cluster code and therefore contained: if it
        raises, the pages go back to the pool and THIS request fails —
        never the prefill loop."""
        pages = self.kv.take_slot_pages(slot)
        plen = int(self.lengths[slot])
        self.slot_req[slot] = None
        self.lengths[slot] = 0
        self._release_adapter(slot)
        try:
            self.on_handoff(req, pages, plen, tok)
        except Exception as e:
            self.kv.pool.free(pages)
            self._finalize(req, FAILED, f"handoff: {type(e).__name__}: {e}")
            self.metrics.record_terminal(self.step_idx, FAILED, req.rid,
                                         req.error)
            return
        self._finalize(req, HANDOFF)
        self.metrics.record_handoff(self.step_idx, plen)
        if self.tracer.enabled:
            self.tracer.instant("handoff_out", cat="handoff", track=slot,
                                rid=req.trace_rid,
                                args={"tokens": plen,
                                      "pages": len(pages)})

    def attach_handoff(self, prompt, pages, length, first_tok, *,
                       max_new_tokens, eos_token_id=None, on_token=None,
                       deadline_s=None, trace_ctx=None, sampling=None,
                       seed=None, grammar=None, sample_offset=0,
                       tenant=None, adapter=None):
        """Decode-worker intake for a prefill worker's donated chain:
        the request joins with its prompt KV already written (``pages``
        cover ``length`` prefilled positions in the SHARED pool) and its
        first token already emitted by the prefill worker.  It slots in
        as a RUNNING decoder — no prefill dispatch ever runs here — at
        the next admission boundary.  Until a slot frees up the chain
        waits in ``_pending_attach`` still holding its pages (bounded:
        the cluster router only hands off what the decode side's queue
        can absorb)."""
        if self.draining:
            raise QueueFull("scheduler is draining; handoff refused")
        t_cfg, adapter_id = self._resolve_tenant(tenant, adapter)
        req = Request(prompt, max_new_tokens, eos_token_id, on_token,
                      deadline_s=deadline_s)
        if t_cfg is not None:
            # failover/disaggregation preserves attribution: the decode
            # side keeps billing the SAME tenant the prefill side did
            req.tenant = t_cfg.name
            req.adapter = adapter
            req.adapter_id = adapter_id
        if trace_ctx is not None and trace_ctx.get("trace_id") is not None:
            req.trace_rid = trace_ctx["trace_id"]
        now = time.monotonic()
        # the boundary token was emitted (and TTFT recorded) by the
        # prefill worker; seeding t_first keeps _emit on the inter-token
        # branch so this scheduler never double-counts a first token
        req.out_tokens = [int(first_tok)]
        req.t_first = req.t_last = now
        req.prefill_pos = len(req.prompt)
        # policy continuity across the handoff: the prefill worker drew
        # the boundary token at position sample_offset + 0; out_tokens
        # already holds it, so this side's next draw lands at +1 with
        # the SAME offset, and _apply_policy replays the grammar cursor
        # through it
        self._apply_policy(req, sampling, seed, grammar, sample_offset)
        self._check_adapter_policy(req)
        req._attach = (list(pages), int(length), int(first_tok))
        if req.remaining_new <= 0:
            self.kv.pool.free(req._attach[0])
            req.state = FINISHED
            self.completed.append(req)
            self.metrics.record_completion(self.step_idx)
            return req
        self.requests[req.rid] = req
        self._pending_attach.append(req)
        return req

    def _admit_attached(self, now):
        """Seed pending handoff chains into free slots ahead of the
        waiting queue (their pages are already allocated — parking them
        longer than necessary only starves the pool)."""
        for slot in range(self.num_slots):
            if not self._pending_attach:
                return
            if self.slot_req[slot] is not None or slot in self._zombies:
                continue
            req = self._pending_attach.popleft()
            pages, length, tok = req._attach
            if req.cancelled or req.past_deadline(now):
                self.kv.pool.free(pages)
                state = CANCELLED if req.cancelled else SHED
                reason = "cancelled" if req.cancelled \
                    else "deadline expired before attach"
                self._finalize(req, state, reason)
                self.metrics.record_terminal(self.step_idx, state,
                                             req.rid, reason)
                continue
            try:
                self.kv.adopt_chain(slot, pages)
            except Exception as e:   # containment: a chain this slot
                # table cannot hold fails ONE request, not the loop
                self.kv.pool.free(pages)
                self._finalize(req, FAILED, f"{type(e).__name__}: {e}")
                self.metrics.record_terminal(self.step_idx, FAILED,
                                             req.rid, req.error)
                continue
            self.slot_req[slot] = req
            self.lengths[slot] = length
            self.last_tok[slot] = tok
            self._eos_ids[slot] = -1 if req.eos_token_id is None \
                else int(req.eos_token_id)
            self._seed_slot_policy(slot, req)
            if self.tenancy is not None:
                self._adapter_ids[slot] = req.adapter_id
                self.tenancy.note(req.tenant, "admitted")
            req.t_admit = now
            req.state = RUNNING
            if self.tracer.enabled:
                self.tracer.instant("handoff_in", cat="handoff",
                                    track=slot, rid=req.trace_rid,
                                    args={"prefilled": length})

    # ----------------------------------------------------------- drain
    def begin_drain(self, shed_waiting=False):
        """Enter drain mode: ``submit``/``attach_handoff`` refuse new
        work (QueueFull — the router's signal to route elsewhere) while
        everything already accepted keeps being served.  With
        ``shed_waiting`` the not-yet-admitted queue is shed NOW with a
        distinct reason instead of silently vanishing at process exit —
        the ds_serve SIGTERM contract."""
        self.draining = True
        if shed_waiting:
            while self.waiting:
                self._drop_waiting(self.waiting.popleft(), SHED,
                                   "shutdown drain: still queued")
            while self._pending_attach:
                req = self._pending_attach.popleft()
                self.kv.pool.free(req._attach[0])
                self._finalize(req, SHED, "shutdown drain: still queued")
                self.metrics.record_terminal(self.step_idx, SHED, req.rid,
                                             req.error)

    def drain(self, grace_s=None, shed_waiting=True):
        """Drain for shutdown/restart: stop admitting new work, finish
        what is in flight within ``grace_s`` (None = no deadline), then
        shed — distinctly, with reasons — whatever the grace budget
        could not cover.  Returns ``{"finished": n, "shed": n}`` for the
        requests that were live when the drain began."""
        before = self.metrics.completed
        shed_before = self.metrics.shed
        t_drain = time.monotonic()
        self.begin_drain(shed_waiting=shed_waiting)
        deadline = None if grace_s is None \
            else time.monotonic() + float(grace_s)
        while deadline is None or time.monotonic() < deadline:
            if not self.step():
                break
        # grace exhausted with work still live: harvest every in-flight
        # horizon first (the device may still be writing those pages),
        # then shed the survivors instead of losing them silently
        while self._inflight:
            self._harvest()
        for slot in range(self.num_slots):
            if self.slot_req[slot] is not None:
                self._close_slot(slot, SHED, "shutdown drain: grace "
                                 "budget exhausted mid-flight")
        while self.waiting:
            self._drop_waiting(self.waiting.popleft(), SHED,
                               "shutdown drain: grace budget exhausted")
        while self._pending_attach:
            req = self._pending_attach.popleft()
            self.kv.pool.free(req._attach[0])
            self._finalize(req, SHED, "shutdown drain: grace budget "
                           "exhausted")
            self.metrics.record_terminal(self.step_idx, SHED, req.rid,
                                         req.error)
        counts = {"finished": self.metrics.completed - before,
                  "shed": self.metrics.shed - shed_before}
        if self.tracer.enabled:
            self.tracer.complete("drain", t_drain, time.monotonic(),
                                 cat="lifecycle", args=dict(counts))
        return counts

    # -------------------------------------------------- horizon decode
    def _bucket_floor(self, h):
        out = 1
        for b in self.horizon_buckets:
            if b <= h:
                out = b
        return out

    def _pick_horizon(self, running, now):
        """Largest useful horizon, quantized to the bucket set: capped
        by the largest remaining token budget among running slots (scan
        steps past every budget are pure waste) and by the tightest live
        deadline (a horizon overshooting a deadline generates tokens the
        sweep will throw away).  A grammar-constrained slot pins the
        batch to horizon 1: its allowed-token mask is a host-compiled
        function of the tokens emitted so far, so the device may take
        at most one constrained step per staged mask."""
        if any(self.slot_req[s].grammar is not None for s in running):
            return 1
        h = min(self.decode_horizon_steps,
                max(self.slot_req[s].remaining_new for s in running))
        deadlines = [self.slot_req[s].deadline for s in running
                     if self.slot_req[s].deadline is not None]
        if deadlines and self._tok_window:
            per_tok = float(np.median(self._tok_window))
            if per_tok > 0:
                slack = min(deadlines) - now
                h = max(1, min(h, int(slack / per_tok)))
        return self._bucket_floor(h)

    def _reserve(self, running, horizon):
        """Pre-reserve every running slot's pages for the whole horizon
        so growth never interrupts the fused scan.  Under pool pressure
        the horizon shrinks bucket-by-bucket before any eviction runs;
        at horizon 1 the legacy evict/shed policy applies unchanged.
        Returns (horizon, surviving slots)."""
        reclaimable = None   # lazy: the cache can't change mid-loop
        h0 = horizon
        chain = None
        while horizon > 1:
            need = sum(self.kv.pages_needed(
                s, int(self.lengths[s]) +
                min(horizon, self.slot_req[s].remaining_new))
                for s in running)
            avail = self.kv.pool.free_pages
            if need > avail and self.prefix_cache is not None:
                # refcount-free cached pages are reclaimable capacity:
                # don't shrink the horizon while a drain would cover it
                # (the exact tree walk only runs when free pages alone
                # don't already answer the question, and once per
                # dispatch)
                if reclaimable is None:
                    reclaimable = self.prefix_cache.reclaimable_pages()
                avail += reclaimable
            if need <= avail:
                break
            if chain is None and self.mem.enabled:
                chain = self.mem.chain(
                    "reserve", step=self.step_idx, slots=len(running),
                    horizon=h0, pages_needed=need,
                    free_pages=self.kv.pool.free_pages,
                    reclaimable=reclaimable or 0)
            horizon = self._bucket_floor(horizon - 1)
        if chain is not None:
            chain.add("horizon_shrink", from_h=h0, to_h=horizon)
            chain.close("shrunk")
        kept = []
        for slot in running:
            req = self.slot_req[slot]
            if req is None or req.state != RUNNING:
                continue   # evicted by an earlier slot's growth
            budget = min(horizon, req.remaining_new)
            try:
                if self._grow_or_evict(slot,
                                       int(self.lengths[slot]) + budget):
                    kept.append(slot)
            except PagePoolExhausted as e:
                self._close_slot(slot, SHED, f"page capacity: {e}")
            except Exception as e:   # same containment as prefill: the
                self._close_slot(slot, FAILED,  # growth is per-slot work
                                 f"{type(e).__name__}: {e}")
        # a later slot's growth can evict an earlier kept slot too
        return horizon, [s for s in kept if self.slot_req[s] is not None
                         and self.slot_req[s].state == RUNNING]

    # --------------------------------------------- speculative decoding
    def _spec_bucket(self, k):
        """Smallest spec-K bucket >= k (compile count stays bounded by
        the bucket set, like horizons)."""
        for b in self.spec_k_buckets:
            if b >= k:
                return b
        return self.spec_k_buckets[-1]

    def _spec_bucket_floor(self, k):
        """Largest spec-K bucket <= k (the pressure-shrink ladder)."""
        out = 1
        for b in self.spec_k_buckets:
            if b <= k:
                out = b
        return out

    def _update_spec_k(self, req, proposed, accepted):
        """Per-request adaptive K: EWMA of the per-round acceptance
        fraction; shrink a bucket when drafts mostly miss (each
        rejected draft column is wasted verify compute + a rolled-back
        KV write), grow back toward ``spec_k`` when they mostly hit."""
        if proposed <= 0:
            return
        rate = accepted / proposed
        prev = getattr(req, "_spec_accept", None)
        req._spec_accept = rate if prev is None else 0.5 * prev + 0.5 * rate
        k = getattr(req, "_spec_k", self.spec_k)
        if req._spec_accept < 0.35:
            k = max(1, k // 2)
        elif req._spec_accept > 0.75:
            k = min(self.spec_k, max(1, k) * 2)
        req._spec_k = self._spec_bucket(k)

    def _collect_drafts(self, running):
        """Ask the drafter for proposals, per-request containment
        included: the ``serve.spec_verify`` fault point fires per
        request here, and an exception from it (or from the drafter)
        degrades THAT request to normal decode — sticky via
        ``_spec_off`` — without touching the loop or its peers."""
        items = []
        for slot in running:
            req = self.slot_req[slot]
            if getattr(req, "_spec_off", False):
                continue
            if req.grammar is not None:
                # a draft column's validity depends on the mask AFTER
                # the previous column — one staged mask per dispatch
                # cannot cover K speculative steps.  The slot rides the
                # verify round as a width-0 one-token decode (the bonus
                # token is drawn under its fresh mask).
                continue
            if not req.sampling.is_greedy and \
                    not getattr(self._spec, "supports_sampling", False):
                # per-request capability gate: a drafter that has not
                # opted into lossless sampled verification only loses
                # THIS slot's proposals, never the round
                continue
            # never draft past the request's budget (the verify bonus
            # token supplies the last one) or the slot's page table
            k = min(getattr(req, "_spec_k", self.spec_k),
                    req.remaining_new - 1,
                    self.kv.max_tokens_per_slot() - int(self.lengths[slot])
                    - 1)
            if k <= 0:
                continue
            try:
                faults.fire("serve.spec_verify", step=self.step_idx,
                            slot=slot, rid=req.rid)
                items.append((slot, req, k))
            except Exception as e:
                req._spec_off = True
                self.metrics.record_spec_degrade(
                    self.step_idx, req.rid, f"{type(e).__name__}: {e}")
        if not items:
            return {}
        try:
            drafts = self._spec.propose(items)
        except Exception:
            # the batch call hides WHICH request blew up — re-propose
            # item by item so the offender(s) degrade sticky while
            # innocent peers keep their drafts (containment: fail one
            # request's speculation, never the round, never the loop)
            drafts = {}
            for item in items:
                slot, req = item[0], item[1]
                try:
                    drafts.update(self._spec.propose([item]))
                except Exception as e:
                    req._spec_off = True
                    self.metrics.record_spec_degrade(
                        self.step_idx, req.rid,
                        f"{type(e).__name__}: {e}")
        out = {}
        for s, _, _ in items:
            # no truthiness on the proposal — a drafter handing back a
            # numpy array would raise on `or`/bool() here, OUTSIDE the
            # containment try/excepts above, and kill the whole loop
            d = drafts.get(s)
            out[s] = [int(t) for t in d] if d is not None and len(d) else []
        return out

    def _dispatch_spec(self, running):
        """One draft/verify round over the running slots.  Returns True
        when a verify dispatch was launched (or the round consumed the
        step by closing slots); False falls back to the normal fused
        horizon — the cold-start/no-proposal path, where the plain
        loop (including overlap) is strictly better."""
        t_prop = time.monotonic()
        drafts = self._collect_drafts(running)
        if self.tracer.enabled:
            self.tracer.complete("spec_propose", t_prop, time.monotonic(),
                                 cat="spec",
                                 args={"proposing": sum(
                                     1 for d in drafts.values() if d)})
        proposing = [s for s in running if drafts.get(s)]
        if not proposing:
            return False
        # mixed-batch gate: a verify round runs every NON-proposing
        # slot as a 1-token decode, so when proposers are a minority
        # of the batch the plain fused horizon (decode_horizon_steps
        # tokens for EVERY slot) out-produces the round server-wide —
        # fall back and let the minority ride it this step.  Abandoned
        # proposals are safe to discard: the ngram drafter is
        # stateless and DraftModelDrafter._sync truncates
        # never-harvested draft KV (same contract as the round-level
        # fault degrade below).
        if 2 * len(proposing) < len(running):
            return False
        k = self._spec_bucket(max(len(d) for d in drafts.values()))
        # page pre-reservation, spec flavor: a verify writes
        # widths[s]+1 positions (rollback releases the surplus), so
        # shrink the K bucket before any eviction would run — same
        # policy ladder as the horizon pre-reservation
        reclaimable = None
        k0 = k
        chain = None
        while k > 1:
            need = sum(self.kv.pages_needed(
                s, int(self.lengths[s]) + min(len(drafts.get(s, ())), k)
                + 1) for s in running)
            avail = self.kv.pool.free_pages
            if need > avail and self.prefix_cache is not None:
                if reclaimable is None:
                    reclaimable = self.prefix_cache.reclaimable_pages()
                avail += reclaimable
            if need <= avail:
                break
            if chain is None and self.mem.enabled:
                chain = self.mem.chain(
                    "spec_reserve", step=self.step_idx,
                    slots=len(running), spec_k=k0, pages_needed=need,
                    free_pages=self.kv.pool.free_pages,
                    reclaimable=reclaimable or 0)
            k = self._spec_bucket_floor(k - 1)
        if chain is not None:
            chain.add("spec_k_shrink", from_k=k0, to_k=k)
            chain.close("shrunk")
        kept = []
        for slot in running:
            req = self.slot_req[slot]
            if req is None or req.state != RUNNING:
                continue
            w = min(len(drafts.get(slot, ())), k)
            try:
                if self._grow_or_evict(slot, int(self.lengths[slot]) + w
                                       + 1):
                    kept.append(slot)
            except PagePoolExhausted as e:
                self._close_slot(slot, SHED, f"page capacity: {e}")
            except Exception as e:
                self._close_slot(slot, FAILED, f"{type(e).__name__}: {e}")
        running = [s for s in kept if self.slot_req[s] is not None and
                   self.slot_req[s].state == RUNNING]
        if not running:
            return True
        try:
            # dispatch-level fault point: a raised verify failure
            # degrades the whole round to normal decode (the loop and
            # every request survive; tokens stay exact either way)
            faults.fire("serve.spec_verify", step=self.step_idx)
        except Exception as e:
            self.metrics.record_spec_degrade(
                self.step_idx, None, f"{type(e).__name__}: {e}")
            return False
        draft_arr = np.zeros((self.num_slots, k), np.int32)
        widths = np.zeros(self.num_slots, np.int32)
        active = np.zeros(self.num_slots, bool)
        budgets = np.zeros(self.num_slots, np.int32)
        for s in running:
            d = drafts.get(s, [])[:k]
            draft_arr[s, :len(d)] = d
            widths[s] = len(d)
            active[s] = True
            budgets[s] = self.slot_req[s].remaining_new
        self._chain_budgets = budgets
        t_disp = time.monotonic()
        if self._batch_needs_policy(running):
            pol = self._policy_args(running)
            out = self.engine.verify_multi_policy(
                self.last_tok, draft_arr, active, self.kv.table,
                self.lengths, self.pools, widths=widths, budgets=budgets,
                eos_ids=self._eos_ids, **pol)
            (toks, valid, tok_end, active_end, lengths_end, emitted_end,
             accepted, _counts_end, pools) = out
            self.metrics.record_policy_dispatch(self.step_idx,
                                                len(running))
        else:
            a_ids, a_pack = self._adapter_args()
            out = self.engine.verify_multi(
                self.last_tok, draft_arr, active, self.kv.table,
                self.lengths, self.pools, widths=widths, budgets=budgets,
                eos_ids=self._eos_ids, adapter_ids=a_ids,
                adapters=a_pack)
            (toks, valid, tok_end, active_end, lengths_end, emitted_end,
             accepted, pools) = out
        self.pools = pools
        for arr in (toks, valid):
            if hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        self._inflight.append({
            "spec": True,
            "slots": list(running),
            "reqs": {s: self.slot_req[s] for s in running},
            "horizon": k + 1,
            "widths": {s: int(widths[s]) for s in running},
            "accepted": accepted,
            "toks": toks, "valid": valid, "tok_end": tok_end,
            "active_end": active_end, "lengths_end": lengths_end,
            "emitted_end": emitted_end, "release_after": set(),
            "t_dispatch": time.monotonic(),
        })
        if self.tracer.enabled:
            self.tracer.complete("spec_verify_dispatch", t_disp,
                                 time.monotonic(), cat="spec",
                                 args={"k": k, "slots": len(running)})
        return True

    def _dispatch(self):
        """Reserve pages and launch one fused horizon over every running
        slot.  The batched dispatch is shared — an error here is NOT
        attributable to one request and must surface loudly."""
        running = [s for s in range(self.num_slots)
                   if self.slot_req[s] is not None and
                   self.slot_req[s].state == RUNNING]
        if not running:
            return
        if self._spec is not None and self._dispatch_spec(running):
            return
        running = [s for s in running if self.slot_req[s] is not None and
                   self.slot_req[s].state == RUNNING]
        if not running:
            return
        t_disp = time.monotonic()
        horizon, running = self._reserve(
            running, self._pick_horizon(running, t_disp))
        if not running:
            return
        active = np.zeros(self.num_slots, bool)
        active[running] = True
        budgets = np.zeros(self.num_slots, np.int32)
        for s in running:
            budgets[s] = self.slot_req[s].remaining_new
        # budgets baseline for any chained continuation: the device's
        # `emitted` carry counts from THIS dispatch
        self._chain_budgets = budgets
        if self._batch_needs_policy(running):
            pol = self._policy_args(running)
            out = self.engine.decode_multi_policy(
                self.last_tok, active, self.kv.table, self.lengths,
                self.pools, horizon=horizon, budgets=budgets,
                eos_ids=self._eos_ids, **pol)
            self.metrics.record_policy_dispatch(self.step_idx,
                                                len(running))
        else:
            pol = None
            a_ids, a_pack = self._adapter_args()
            out = self.engine.decode_multi(
                self.last_tok, active, self.kv.table, self.lengths,
                self.pools, horizon=horizon, budgets=budgets,
                eos_ids=self._eos_ids, adapter_ids=a_ids,
                adapters=a_pack, **self.sampling)
        self._commit_dispatch(out, running, horizon,
                              {s: self.slot_req[s] for s in running},
                              policy=pol)
        if self.tracer.enabled:
            # host side of the dispatch: page reservation + argument
            # staging + launching the fused scan (the device's share of
            # the horizon shows up as device_wait at harvest)
            self.tracer.complete("horizon_dispatch", t_disp,
                                 time.monotonic(), cat="dispatch",
                                 args={"horizon": horizon,
                                       "slots": len(running)})

    def _commit_dispatch(self, out, running, horizon, reqs, policy=None):
        if policy is not None:
            # the policy twin returns a counts carry before the pools:
            # a chained continuation stages IT (device truth mid-chain)
            # instead of the host mirror
            (toks, valid, tok_end, active_end, lengths_end, emitted_end,
             counts_end, pools) = out
            policy = dict(policy, counts_end=counts_end)
        else:
            (toks, valid, tok_end, active_end, lengths_end, emitted_end,
             pools) = out
        self.pools = pools
        for arr in (toks, valid):
            # overlap: the host copy starts NOW, so the harvest one
            # horizon later rarely stalls on the device
            if hasattr(arr, "copy_to_host_async"):
                arr.copy_to_host_async()
        self._inflight.append({
            "slots": list(running), "reqs": reqs, "horizon": horizon,
            # per-slot upper bound on length advance during this horizon
            # (drives the NEXT chained reservation; actual advance is
            # only known at harvest)
            "max_advance": {s: int(min(horizon, reqs[s].remaining_new))
                            for s in running},
            "toks": toks, "valid": valid, "tok_end": tok_end,
            "active_end": active_end, "lengths_end": lengths_end,
            "emitted_end": emitted_end, "release_after": set(),
            "policy": policy, "t_dispatch": time.monotonic(),
        })

    def _try_chain(self):
        """Dispatch the next horizon straight off the in-flight
        horizon's device carries — no host round-trip — when membership
        is provably frozen: nothing waiting or prefilling, no
        cancel/deadline pressure, and the next horizon's worst-case page
        growth fits in FREE pages.  A chained dispatch never evicts:
        eviction while the device is still writing a victim's pages
        would corrupt the new owner's cache.  Returns True when the
        chained horizon was dispatched."""
        prev = self._inflight[-1]
        if self._spec is not None or prev.get("spec"):
            # spec rounds need host-authoritative token history (the
            # drafter reads out_tokens) and a host-side rollback per
            # verify — every spec step is a barrier step by design
            return False
        if self.waiting:
            return False
        live = [r for r in self.slot_req if r is not None]
        if any(r.state == PREFILL for r in live):
            return False
        if any(r.cancelled or r.deadline is not None for r in live):
            return False
        cont = [s for s in prev["slots"]
                if self.slot_req[s] is prev["reqs"][s] and
                prev["reqs"][s].state == RUNNING and
                s not in self._zombies]
        if not cont:
            return False
        if any(prev["reqs"][s].grammar is not None for s in cont):
            # a constrained slot's next allowed-token mask depends on
            # the in-flight horizon's tokens (host-compiled DFA): every
            # constrained step is a barrier step
            return False
        if all(prev["reqs"][s].remaining_new - prev["max_advance"][s] <= 0
               for s in cont):
            # the in-flight horizon exhausts every continuing slot's
            # budget: the chained dispatch would scan H steps over
            # all-frozen slots and emit nothing — take the barrier path
            return False
        # remaining_new is an upper bound here (the in-flight horizon's
        # tokens are not appended yet): safe for horizon sizing and page
        # reservation, both of which only over-provision
        horizon = self._bucket_floor(
            min(self.decode_horizon_steps,
                max(prev["reqs"][s].remaining_new for s in cont)))
        targets, need = {}, 0
        for s in cont:
            req = prev["reqs"][s]
            cap = len(req.orig_prompt) + req.max_new_tokens
            targets[s] = min(int(self.lengths[s]) + prev["max_advance"][s]
                             + horizon, cap)
            need += self.kv.pages_needed(s, targets[s])
        short = need - self.kv.pool.free_pages
        if short > 0:
            # a chained dispatch never evicts a live slot (the device
            # may still be writing the victim's pages) — but cache-only
            # pages are not referenced by any LIVE row of an in-flight
            # dispatch (frozen rows read them at worst, and frozen
            # output is discarded), so draining them here is safe and
            # keeps the overlap alive under a warm cache.  Pre-check
            # the exact drainable count so a hopeless chain attempt
            # does not flush the cache on its way to the barrier.
            chain = self.mem.chain(
                "chain", step=self.step_idx, slots=len(cont),
                pages_needed=need,
                free_pages=self.kv.pool.free_pages) \
                if self.mem.enabled else None
            if self.prefix_cache is None or \
                    short > self.prefix_cache.reclaimable_pages():
                # provably-uncoverable shortfall: the most common
                # reason overlap degrades to a barrier step — it must
                # leave a forensics chain like every other capacity
                # decision, not vanish silently
                if chain is not None:
                    chain.close("barrier_fallback")
                return False
            drained = self._reclaim_cached(short)
            if chain is not None:
                chain.add("cache_drain", pages=drained)
                chain.close("drained" if drained >= short
                            else "barrier_fallback")
            if drained < short:
                return False
        try:
            for s in cont:
                faults.fire("serve.page_alloc", step=self.step_idx,
                            slot=s, rid=prev["reqs"][s].rid)
                if not self.kv.ensure_capacity(s, targets[s]):
                    return False
        except PagePoolExhausted:
            return False   # injected exhaustion: take the barrier path
        active = prev["active_end"]
        if self._zombies:
            # freeze slots whose requests were terminated host-side
            # while the previous horizon still had them active
            import jax.numpy as jnp
            keep = np.ones(self.num_slots, bool)
            keep[list(self._zombies)] = False
            active = jnp.logical_and(active, jnp.asarray(keep))
        pol = prev.get("policy")
        if pol is not None:
            # same path as the in-flight horizon, same staged params
            # (membership is frozen, so the slot mirrors are unchanged);
            # tok_base stays the chain-start base — the device's
            # `emitted` carry keeps the position stream continuous —
            # and counts continue from the device carry
            out = self.engine.decode_multi_policy(
                prev["tok_end"], active, self.kv.table,
                prev["lengths_end"], self.pools, horizon=horizon,
                budgets=self._chain_budgets, eos_ids=self._eos_ids,
                emitted=prev["emitted_end"], keys=pol["keys"],
                tok_base=pol["tok_base"], temps=pol["temps"],
                top_ks=pol["top_ks"], top_ps=pol["top_ps"],
                rep_pens=pol["rep_pens"], pres_pens=pol["pres_pens"],
                freq_pens=pol["freq_pens"], counts=pol["counts_end"],
                mask=pol["mask"])
            self.metrics.record_policy_dispatch(self.step_idx, len(cont))
            chain_pol = {k: pol[k] for k in
                         ("keys", "tok_base", "temps", "top_ks", "top_ps",
                          "rep_pens", "pres_pens", "freq_pens", "counts",
                          "mask")}
        else:
            chain_pol = None
            # membership is frozen across a chain, so the slot->adapter
            # map (and therefore the staged ids) is unchanged
            a_ids, a_pack = self._adapter_args()
            out = self.engine.decode_multi(
                prev["tok_end"], active, self.kv.table,
                prev["lengths_end"], self.pools, horizon=horizon,
                budgets=self._chain_budgets, eos_ids=self._eos_ids,
                emitted=prev["emitted_end"], adapter_ids=a_ids,
                adapters=a_pack, **self.sampling)
        self._commit_dispatch(out, cont, horizon,
                              {s: prev["reqs"][s] for s in cont},
                              policy=chain_pol)
        if self.tracer.enabled:
            self.tracer.instant("horizon_chained", cat="dispatch",
                                args={"horizon": horizon,
                                      "slots": len(cont)})
        return True

    def _harvest(self):
        """Pull the oldest in-flight horizon's token block and run the
        host bookkeeping: emit (streaming callbacks + metrics), retire,
        honor cancellations/deadlines/emit-failures discovered mid-
        horizon, and release any deferred pages parked on this horizon.
        Returns (device_wait_s, tokens_delivered)."""
        rec = self._inflight.popleft()
        t0 = time.monotonic()
        toks = np.asarray(rec["toks"])    # blocks until the device (and
        valid = np.asarray(rec["valid"])  # async host copy) catch up
        wait = time.monotonic() - t0
        now = time.monotonic()
        if self.tracer.enabled:
            # the host/device split the device_wait instrumentation
            # already measures: time blocked pulling the token block is
            # the device's (+ copy's) share of this horizon; 0 means the
            # overlapped copy had already landed
            self.tracer.complete("device_wait", t0, t0 + wait,
                                 cat="device", track="device",
                                 args={"horizon": rec["horizon"],
                                       "spec": bool(rec.get("spec"))})
        pulled = 0
        for slot in rec["slots"]:
            req = rec["reqs"][slot]
            if req.state in TERMINAL or self.slot_req[slot] is not req:
                continue       # closed at an earlier boundary (zombie)
            if req.cancelled:
                # tokens generated past the cancel are dropped: honored
                # at the horizon boundary, like the legacy step boundary
                self._close_slot_or_defer(slot, CANCELLED, "cancelled")
                continue
            if req.past_deadline(now):
                self._close_slot_or_defer(slot, SHED,
                                          "deadline expired mid-flight")
                continue
            n = int(valid[slot].sum())
            if n and req.t_last is not None:
                # horizon-granularity time-between-tokens: the client-
                # visible burst cadence (per-token gaps within a burst
                # are ~0 and still land in tpot)
                self.metrics.record_tbt(self.step_idx, now - req.t_last)
            for i in range(rec["horizon"]):
                if not valid[slot, i]:
                    continue
                tok = int(toks[slot, i])
                try:
                    self._emit(req, tok)
                    pulled += 1   # only tokens actually DELIVERED count
                    # policy bookkeeping rides the same containment: a
                    # grammar rejection of a delivered token fails THIS
                    # request (the device mask should make it
                    # impossible — reaching it means corrupted state)
                    self._note_emitted(slot, req, tok)
                except Exception as e:  # per-request emit/callback fault
                    self._close_slot_or_defer(
                        slot, FAILED, f"{type(e).__name__}: {e}")
                    break
                if req._finished_by(tok) or self._grammar_finished(req):
                    # the device froze the slot at this same token, so
                    # its pages are read-only in any chained horizon:
                    # immediate release is safe.  A grammar cursor with
                    # no continuation (done) finishes the request even
                    # without eos — the constrained output is complete.
                    self._retire(slot)
                    break
            if self.slot_req[slot] is req and req.state == RUNNING and \
                    req.grammar is not None:
                # refresh the staged mask for the next (barrier)
                # dispatch — constrained slots run horizon-1 unchained,
                # so the mask is always exactly one token fresh
                self._grammar_masks[slot] = req.grammar.token_mask()
            if n and self.tracer.enabled:
                # one span per (slot, horizon) burst on the slot's own
                # track: dispatch -> harvest, n tokens delivered.  This
                # is the per-request timeline row (rid-keyed), emitted
                # even when the request just retired/closed above.
                self.tracer.complete(
                    "decode_burst" if not rec.get("spec")
                    else "spec_round", rec["t_dispatch"], now,
                    cat="decode", track=slot, rid=req.trace_rid,
                    args={"tokens": n, "horizon": rec["horizon"]})
            if self.slot_req[slot] is req and req.state == RUNNING:
                self.lengths[slot] += n
                if n:
                    self.last_tok[slot] = int(toks[slot][valid[slot]][-1])
        if rec.get("spec"):
            self._harvest_spec(rec, valid)
        for slot in rec["release_after"]:
            self.kv.release_slot(slot)
            self.lengths[slot] = 0
            self._zombies.discard(slot)
        if rec.get("spec"):
            self.metrics.record_spec_wait(self.step_idx, wait)
        else:
            self.metrics.record_horizon(self.step_idx, rec["horizon"],
                                        pulled, wait)
        if self.tracer.enabled:
            # host bookkeeping share of the harvest (emit callbacks,
            # retire, rollback) — the counterpart of device_wait above
            self.tracer.complete("harvest", now, time.monotonic(),
                                 cat="dispatch",
                                 args={"tokens": pulled,
                                       "horizon": rec["horizon"],
                                       "spec": bool(rec.get("spec"))})
        return wait, pulled

    def _harvest_spec(self, rec, valid):
        """Spec-round epilogue: roll the KV back to the emitted
        boundary (``truncate_slot`` — pages written for rejected drafts
        recycle), feed the drafter its acceptance outcome, adapt each
        request's K, and record the round's telemetry.  Runs after the
        shared emit/retire loop, so ``lengths`` already counts only
        emitted tokens and ``out_tokens`` is current."""
        accepted = np.asarray(rec["accepted"])
        proposed = acc_total = rollbacks = rollback_tokens = 0
        for slot in rec["slots"]:
            req = rec["reqs"][slot]
            w = rec["widths"][slot]
            n = int(valid[slot].sum())
            acc = int(accepted[slot])
            proposed += w
            acc_total += acc
            discard = max(0, (w + 1) - n)
            if discard:
                rollbacks += 1
                rollback_tokens += discard
            req._spec_proposed = getattr(req, "_spec_proposed", 0) + w
            req._spec_hits = getattr(req, "_spec_hits", 0) + acc
            self._update_spec_k(req, w, acc)
            if self.slot_req[slot] is req and req.state == RUNNING:
                # live slot: release pages past the accepted boundary
                # (a retiring slot's surplus pages were already freed —
                # or donated minus the invalid tail — at retire)
                self.kv.truncate_slot(slot, int(self.lengths[slot]))
                if self._spec is not None:
                    try:
                        self._spec.on_verified(slot, req, n, acc)
                    except Exception as e:   # containment, as ever
                        req._spec_off = True
                        self.metrics.record_spec_degrade(
                            self.step_idx, req.rid,
                            f"{type(e).__name__}: {e}")
        self.metrics.record_spec(
            self.step_idx, proposed=proposed, accepted=acc_total,
            emitted=int(valid.sum()), rollbacks=rollbacks,
            rollback_tokens=rollback_tokens, k=rec["horizon"] - 1,
            slot_rounds=sum(1 for s in rec["slots"]
                            if rec["widths"][s] > 0))

    def _close_slot_or_defer(self, slot, state, reason):
        """Terminal removal discovered at a horizon boundary.  If a
        chained horizon is still in flight with this slot unfrozen, the
        device may be writing the slot's pages: close the request's
        bookkeeping NOW (state, metrics, history) but hold the pages
        until that horizon is harvested."""
        if not self._inflight:
            self._close_slot(slot, state, reason)
            return
        req = self.slot_req[slot]
        self._spec_release(slot, req)
        self.slot_req[slot] = None
        self._finalize(req, state, reason)
        self.metrics.record_terminal(self.step_idx, state, req.rid, reason)
        if state == FAILED:
            self._last_error = f"rid={req.rid}: {reason}"
        self._zombies.add(slot)
        self._inflight[-1]["release_after"].add(slot)

    def run(self, max_steps=100000):
        """Drive step() until idle; returns {rid: generated tokens} for
        requests that FINISHED (failed/shed/cancelled requests are
        reported distinctly — see ``health()`` and each request's
        ``.state``/``.error``). The result set is exact for everything
        that finished during (or before) this call even when the bounded
        ``completed`` history has rotated old entries out."""
        self._collect = {r.rid: list(r.out_tokens) for r in self.completed
                         if r.state == FINISHED}
        t0 = time.monotonic()
        try:
            for _ in range(max_steps):
                if not self.step():
                    break
        finally:
            results, self._collect = self._collect, None
        self._wall_s = time.monotonic() - t0
        # max_steps exhausted with live work is a legitimate outcome (a
        # bounded drain): finished requests are returned, the rest stay
        # queued/running for further step() calls
        return results

    # -------------------------------------------------------------- audit
    def audit(self, raise_on_error=True):
        """Refcount invariant audit (serving/mem_telemetry.audit_pool):
        cross-check the pool's refcounts against THIS scheduler's
        holders — slot page tables, the prefix-cache trie, parked
        handoff chains — and the draft pool against the drafter's
        tables.  Raises :class:`~deepspeed_tpu.serving.mem_telemetry.
        AuditError` on a leak, double-free hazard, or orphan table
        entry.  Over a SHARED (disaggregated) pool only the structural
        + double-free directions run (``exact=False``): peer schedulers
        and router-held packets hold references this scheduler cannot
        see — the exact fleet-wide census is ``ClusterRouter.audit()``.
        Also asserts the page-state attribution is conservation-exact
        (the states sum to ``num_pages``)."""
        chains = [r._attach[0] for r in self._pending_attach]
        report = memtel.audit_pool(
            self.kv.pool, managers=[self.kv],
            caches=[self.prefix_cache] if self.prefix_cache is not None
            else [], chains=chains, exact=not self._pool_shared,
            label="kv_pool", raise_on_error=raise_on_error)
        reports = [report]
        # getattr like classify(): a duck-typed custom drafter without
        # the mem_stats hook must not turn a telemetry opt-in into an
        # AttributeError that kills a working serving loop
        stats = None if self._spec is None else \
            getattr(self._spec, "mem_stats", lambda: None)()
        if stats is not None and getattr(self._spec, "kv", None) \
                is not None:
            reports.append(memtel.audit_pool(
                self._spec.kv.pool, managers=[self._spec.kv],
                exact=True, label="draft_pool",
                raise_on_error=raise_on_error))
        counts = memtel.classify(self)
        total = sum(counts.get(k, 0) for k in
                    ("slot", "prefix_shared", "prefix_sole", "handoff",
                     "unattributed", "free"))
        if total != self.kv.pool.num_pages:
            msg = (f"page-state attribution not conservation-exact: "
                   f"{counts} sums to {total} != "
                   f"{self.kv.pool.num_pages}")
            if raise_on_error:
                raise memtel.AuditError(msg)
            reports.append({"label": "attribution", "errors": [msg],
                            "ok": False})
        if not self._pool_shared and counts["unattributed"]:
            msg = (f"{counts['unattributed']} allocated page(s) with no "
                   "known holder on a private pool (leak)")
            if raise_on_error:
                raise memtel.AuditError(msg)
            reports.append({"label": "attribution", "errors": [msg],
                            "ok": False})
        out = {"ok": all(r.get("ok", True) for r in reports),
               "reports": reports, "counts": counts}
        if self.tenancy is not None:
            # per-tenant split of the same census: every attributable
            # page charged to exactly one tenant (a page under two
            # tenants is a cross-tenant leak and fails the audit)
            treport = memtel.classify_tenants(
                self, raise_on_error=raise_on_error)
            reports.append(treport)
            out["ok"] = out["ok"] and treport["ok"]
            out["tenants"] = treport["tenants"]
        return out

    # ------------------------------------------------- comm ledger
    def comm_ledger(self, refresh=False):
        """Compute (and cache) the static HLO comm ledger of every
        serving signature this scheduler's engine has dispatched
        (``profiling/comm_ledger.py``), emit the ``serving/comm/*``
        gauges, and populate the ``comm_*`` health fields.

        The steady-state unit the gauges describe is the *largest
        captured decode_multi horizon* — the dispatch shape a warm
        server settles into; per-signature detail is the return value
        (``{label: ledger}``) and the CI artifact.  First call pays one
        analysis re-compile per signature (lower -> compile -> parse),
        so callers run it off the hot path: at drain/summary time, or
        the first health heartbeat (``ds_serve`` does the latter).
        Empty dict when ``comm_telemetry`` is off."""
        if not self.comm_telemetry or \
                not hasattr(self.engine, "comm_ledger"):
            return {}
        ledgers = self.engine.comm_ledger(refresh=refresh)
        best_h, decode_led = 0, None
        for label, led in ledgers.items():
            m = re.match(r"decode_multi\[h=(\d+)\]", label)
            if m:
                h = int(m.group(1))
                if h > best_h:
                    best_h, decode_led = h, led
        if decode_led is None and "decode" in ledgers:
            best_h, decode_led = 1, ledgers["decode"]
        if decode_led is not None:
            # a decode_multi dispatch serves ALL slots for `horizon`
            # steps, so the per-token unit divides by both — wire
            # bytes per emitted token at full slot occupancy (the
            # like-for-like scorecard unit; partial occupancy moves
            # the realized cost up, never down)
            self._comm_summary = {
                "horizon": best_h,
                "bytes_per_step": int(decode_led["wire_bytes"]),
                "bytes_per_token":
                    round(decode_led["wire_bytes"]
                          / max(best_h * self.num_slots, 1), 1),
                "collectives_per_step": int(decode_led["collectives"]),
                "per_axis": dict(decode_led["per_axis"]),
                "ici_bytes": int(decode_led["per_tier"]["ici"]),
                "dcn_bytes": int(decode_led["per_tier"]["dcn"]),
            }
            self.metrics.record_comm(self.step_idx, self._comm_summary)
        return ledgers

    def comm_health_fields(self):
        """The ``comm_*`` slice of :meth:`health` (the router's fleet
        aggregation reads this directly).  Byte figures are None until
        :meth:`comm_ledger` has analyzed a decode signature — health
        itself never compiles."""
        s = self._comm_summary
        wd = self.compile_watchdog
        return {
            "comm_telemetry": self.comm_telemetry,
            "comm_bytes_per_step":
                None if s is None else s["bytes_per_step"],
            "comm_bytes_per_token":
                None if s is None else s["bytes_per_token"],
            "comm_collectives_per_step":
                None if s is None else s["collectives_per_step"],
            "comm_axis_bytes": None if s is None else s["per_axis"],
            "comm_ici_bytes_per_step":
                None if s is None else s["ici_bytes"],
            "comm_dcn_bytes_per_step":
                None if s is None else s["dcn_bytes"],
            "compile_watchdog": wd is not None,
            "compiles": 0 if wd is None
            else int(sum(wd.counts.values())),
            "steady_recompiles": 0 if wd is None
            else wd.steady_recompiles,
        }

    # ------------------------------------------------------------- health
    def health(self):
        """Liveness/saturation snapshot for operators (exposed by
        ``bin/ds_serve``): current load, pool pressure, step latency,
        and terminal counts by kind."""
        m = self.metrics
        pc = self.prefix_cache
        uptime = max(1e-9, time.monotonic() - self._t_start)
        # page-state attribution: a fresh host sweep per snapshot (the
        # heartbeat cadence, not the hot loop), so health() reports the
        # split whether or not per-step telemetry is on.  Per-device
        # bytes derive from the existing pool_bytes_per_device figure.
        mem_counts = memtel.classify(self)
        bpp = None
        per_dev = self.mesh_info.get("kv_pool_bytes_per_device")
        if per_dev:
            bpp = per_dev // self.kv.pool.num_pages

        def _bytes(pages):
            return None if bpp is None else int(pages) * bpp
        return {
            "step": self.step_idx,
            "uptime_s": round(uptime, 3),
            "steps_per_s": round(self.step_idx / uptime, 3),
            "tracing": self.tracer.enabled,
            "mesh": self.mesh_info.get("mesh_shape"),
            "mesh_devices": self.mesh_info.get("mesh_devices"),
            "serving_axes": self.mesh_info.get("serving_axes"),
            # the paged-attention path actually dispatched (kernel vs
            # reference, shard_map vs direct, and why): an accidental
            # reference fallback must show up on the operator surface,
            # not hide behind a silent slowdown
            "paged_attention": self.mesh_info.get("paged_attention"),
            # quantized serving memory: the pool dtype actually
            # allocated (int8/fp8 pools report their TRUE byte
            # footprint below — payload + scale leaves summed, never a
            # hand-computed figure) and the weight storage dtype
            "kv_dtype": self.kv_dtype_name,
            "weight_dtype": getattr(self.engine, "weight_dtype_name",
                                    None),
            "kv_pool_bytes_per_device":
                self.mesh_info.get("kv_pool_bytes_per_device"),
            "kv_pool_bytes_total":
                self.mesh_info.get("kv_pool_bytes_total"),
            "prefix_cache": pc is not None,
            "prefix_hit_rate": None if pc is None
            else round(pc.hit_rate(), 4),
            "tokens_reused": 0 if pc is None else pc.tokens_reused,
            "pages_shared": 0 if pc is None else pc.pages_shared,
            "cached_pages": 0 if pc is None else pc.cached_pages,
            "cow_copies": 0 if pc is None else pc.cow_copies,
            "running": sum(r is not None for r in self.slot_req),
            "waiting": len(self.waiting),
            "live_requests": len(self.requests),
            "queue_capacity": self.max_queue,
            "free_pages": self.kv.pool.free_pages,
            "page_utilization": round(self.kv.utilization(), 4),
            "ema_step_ms": None if self._ema_step_s is None
            else round(self._ema_step_s * 1e3, 3),
            "decode_horizon_steps": self.decode_horizon_steps,
            "horizon_buckets": list(self.horizon_buckets),
            "overlap": self.overlap,
            # sequence-parallel prefill: the resolved transport (or why
            # it degraded), the routing threshold, and the fairness cap
            # on up-front page reservations
            "seq_parallel_threshold": self.seq_parallel_threshold,
            "seq_parallel_axis": None if self.seq_plan is None
            else self.seq_plan.axis,
            "seq_parallel_impl": None if self.seq_plan is None
            else self.seq_plan.impl,
            "seq_parallel_degrade_reason": self._sp_degrade_reason,
            "sp_chunk_buckets": list(self.sp_chunk_buckets),
            "prefill_reserve_cap": self.prefill_reserve_cap,
            "seq_prefill_routed": m.seq_prefill_routed,
            "seq_prefill_chunks": m.seq_prefill_chunks,
            "seq_prefill_degraded": m.seq_prefill_degraded,
            "seq_prefill_shed": m.seq_prefill_shed,
            # decoding-policy subsystem: the scheduler-wide default
            # policy label, and how much of the traffic actually used
            # per-request sampling / grammar constraints
            "decoding_policy": self.default_sampling.label(),
            "sampled_requests": m.sampled_requests,
            "grammar_requests": m.grammar_requests,
            "policy_dispatches": m.policy_dispatches,
            "grammar_violations": m.grammar_violations,
            "spec_decode": self.spec_mode,
            "spec_k": self.spec_k if self._spec is not None else None,
            "spec_acceptance_rate": round(m.spec_acceptance_rate(), 4),
            "spec_mean_accepted": round(m.spec_mean_accepted(), 3),
            "spec_draft_tokens": m.spec_proposed,
            "spec_accepted_tokens": m.spec_accepted,
            "spec_rollbacks": m.spec_rollbacks,
            "spec_degraded": m.spec_degraded,
            "mem_telemetry": self.mem.enabled,
            "mem_slot_pages": mem_counts["slot"],
            "mem_prefix_shared_pages": mem_counts["prefix_shared"],
            "mem_prefix_sole_pages": mem_counts["prefix_sole"],
            "mem_handoff_pages": mem_counts["handoff"],
            "mem_draft_pages": mem_counts.get("draft", 0),
            "mem_unattributed_pages": mem_counts["unattributed"],
            "mem_free_pages": mem_counts["free"],
            "mem_free_frac": round(
                self.kv.pool.free_pages / self.kv.pool.num_pages, 4),
            "mem_page_seconds": round(self.mem.page_seconds, 3)
            if self.mem.enabled else 0.0,
            "mem_pressure_events": m.mem_pressure_events,
            "mem_pressure_episodes": m.mem_pressure_episodes,
            "mem_slot_bytes_per_device": _bytes(mem_counts["slot"]),
            "mem_prefix_bytes_per_device": _bytes(
                mem_counts["prefix_shared"] + mem_counts["prefix_sole"]),
            "mem_handoff_bytes_per_device": _bytes(
                mem_counts["handoff"]),
            "mem_free_bytes_per_device": _bytes(mem_counts["free"]),
            # communication & compile observability (PR 12): the HLO
            # comm-ledger summary (None until comm_ledger() ran — a
            # health probe must never pay an analysis compile) and the
            # recompile-watchdog counters
            **self.comm_health_fields(),
            # serving autotuner (ROADMAP item 3): online-controller
            # presence + nudge count, and the searched-config
            # provenance (--tuned-config PATH; None = hand-set)
            "online_tuner": self.online is not None,
            "tune_nudges": m.tune_nudges,
            "tuned_from": self.tuned_from,
            "inflight_horizons": len(self._inflight),
            "draining": self.draining,
            "handoffs": m.handoffs,
            "pending_handoffs": len(self._pending_attach),
            # handoff transport (cross-pool chain transfers; all zero
            # on the shared-pool path, which moves page ids only)
            "handoff_bytes_out": m.handoff_bytes_out,
            "handoff_bytes_in": m.handoff_bytes_in,
            "handoff_chunks": m.handoff_chunks,
            "handoff_transport_ms": round(m.handoff_transport_ms, 3),
            "handoff_aborted": m.handoff_aborted,
            "completed": m.completed,
            "failed": m.failed,
            "shed": m.shed,
            "cancelled": m.cancelled,
            "preemptions": m.preemptions,
            "tokens_emitted": m.tokens_emitted,
            "last_error": self._last_error,
            "ha_epoch": self.ha_epoch,
            "ha_fenced": self.ha_fenced,
            # multi-tenant serving tier: per-tenant usage ledgers
            # (page-seconds billed, admissions, sheds) + live page
            # footprints, and the loaded adapter-store shape (the
            # rank bucket is a jit-signature input — operators watch
            # it to understand warmup recompiles)
            "tenancy": self.tenancy is not None,
            "tenants": None if self.tenancy is None
            else self.tenancy.usage_fields(),
            "tenant_pages": None if self.tenancy is None
            else {t: self._tenant_pages(t)
                  for t in sorted(self.tenancy.tenants)},
            "adapters": 0 if self.tenancy is None or
            self.tenancy.store is None else len(self.tenancy.store),
            "adapter_rank_bucket": 0 if self.tenancy is None or
            self.tenancy.store is None
            else self.tenancy.store.rank_bucket(),
            "quota_shed": m.quota_shed,
        }

    def summary(self):
        out = self.metrics.summary(getattr(self, "_wall_s", None))
        if self.mem.enabled:
            # per-request memory attribution aggregates: page-seconds
            # is the unit the autotuner's cost model bills capacity in
            out.update(self.mem.summary_fields())
        return out
