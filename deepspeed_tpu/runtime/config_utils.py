"""Pydantic base model + helpers for the config tree.

Reimplements the contract of the reference's ``runtime/config_utils.py:16``
(``DeepSpeedConfigModel``) on pydantic v2: unknown keys are tolerated (with a
log line), and a field may be declared deprecated with a ``new_param`` that it
auto-populates, so old configs keep working.
"""

import json
from functools import reduce
from typing import ClassVar, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigModel(BaseModel):
    """Base for all config submodels.

    Deprecated fields are declared via ``Field(json_schema_extra={
    "deprecated": True, "new_param": "other_field", "new_param_fn": fn})``.

    Fields that are accepted for reference-config compatibility but have
    no effect in the TPU runtime are declared in ``_inert_fields``
    (name -> reason). Explicitly setting one logs a loud warning — a
    silently-ignored knob misleads users porting reference configs
    (e.g. expecting ZeRO++ quantized comm that never engages).
    """

    _inert_fields: ClassVar[Dict[str, str]] = {}

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        arbitrary_types_allowed=True,
        protected_namespaces=(),
    )

    def __init__(self, strict=False, **data):
        if not strict:
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)

    @model_validator(mode="after")
    def _warn_inert_fields(self):
        for name, reason in type(self)._inert_fields.items():
            if name in self.model_fields_set:
                logger.warning(
                    f"Config key '{name}' is accepted for compatibility "
                    f"but has NO EFFECT on TPU: {reason}")
        return self

    @model_validator(mode="after")
    def _process_deprecated_fields(self):
        fields_set = self.model_fields_set
        for name, field in type(self).model_fields.items():
            extra = field.json_schema_extra or {}
            if not isinstance(extra, dict) or not extra.get("deprecated", False):
                continue
            if name not in fields_set:
                continue
            new_param = extra.get("new_param", "")
            dep_msg = f"Config parameter {name} is deprecated"
            if new_param:
                dep_msg += f", use {new_param} instead"
            logger.warning(dep_msg)
            if not new_param:
                continue
            # Only forward if the new param wasn't explicitly set by the user.
            new_param_root = new_param.split(".")[0]
            if new_param_root in fields_set:
                continue
            value = extra.get("new_param_fn", lambda x: x)(getattr(self, name))
            try:
                if "." in new_param:
                    nodes = new_param.split(".")
                    target = reduce(getattr, nodes[:-1], self)
                    setattr(target, nodes[-1], value)
                else:
                    object.__setattr__(self, new_param, value)
            except Exception as e:
                logger.error(f"Tried to set value {value} for deprecated->new field "
                             f"{name}->{new_param} but failed: {e}")
                raise
        return self

    def get(self, key, default=None):
        return getattr(self, key, default)

    def __getitem__(self, key):
        return getattr(self, key)


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json.load object_pairs_hook that rejects duplicate keys."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder(json.JSONEncoder):
    """JSON encoder printing large numbers in scientific notation
    (reference ``runtime/config_utils.py`` namesake; used by config dump)."""

    def iterencode(self, o, _one_shot=False, level=0):
        indent = self.indent if self.indent is not None else 4
        prefix_close = " " * level * indent
        level += 1
        prefix = " " * level * indent
        if isinstance(o, bool):
            return "true" if o else "false"
        elif isinstance(o, float) and o >= 1e3:
            return f"{o:e}"
        elif isinstance(o, int) and o >= 1e3:
            return f"{o:e}"
        elif isinstance(o, dict):
            x = [f"\n{prefix}\"{k}\": {self.iterencode(v, level=level)}"
                 for k, v in o.items()]
            return "{" + ", ".join(x) + f"\n{prefix_close}" + "}"
        elif isinstance(o, list):
            return "[" + ", ".join(self.iterencode(v, level=level) for v in o) + "]"
        else:
            return json.dumps(o)
