"""Progressive layer drop (reference
``runtime/progressive_layer_drop.py:40``): per-step keep probability
theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar, consumed by
stochastic-depth transformer blocks."""

import math


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
