"""Data loading (reference: ``deepspeed/runtime/dataloader.py``, 162 LoC).

Single-controller JAX feeds **global** batches (micro_batch x dp_world) that
the engine shards over the `data` mesh axis, so there is no per-rank
DistributedSampler; the loader's job is batching + collation + epoch cycling.
Accepts indexable datasets (torch-style), iterables of ready batches, or
dicts of arrays.
"""

import numpy as np


def default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(it[k]) for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return tuple(np.stack([np.asarray(it[i]) for it in items])
                     for i in range(len(first)))
    return np.stack([np.asarray(it) for it in items])


class DeepSpeedDataLoader:
    def __init__(self, dataset, batch_size, collate_fn=None, drop_last=False,
                 shuffle=False, seed=0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0

    def __len__(self):
        if isinstance(self.dataset, dict):
            n = len(next(iter(self.dataset.values())))
        elif hasattr(self.dataset, "__len__"):
            n = len(self.dataset)
        else:
            raise TypeError("underlying dataset has no __len__")
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        ds = self.dataset
        if isinstance(ds, dict):
            n = len(next(iter(ds.values())))
            idx = np.arange(n)
            if self.shuffle:
                idx = np.random.default_rng(self.seed + self.epoch).permutation(n)
            self.epoch += 1
            for s in range(0, n - (self.batch_size - 1 if self.drop_last else 0),
                           self.batch_size):
                sel = idx[s:s + self.batch_size]
                if len(sel) == 0:
                    return
                yield {k: np.asarray(v)[sel] for k, v in ds.items()}
        elif hasattr(ds, "__getitem__") and hasattr(ds, "__len__"):
            n = len(ds)
            idx = np.arange(n)
            if self.shuffle:
                idx = np.random.default_rng(self.seed + self.epoch).permutation(n)
            self.epoch += 1
            stop = n - self.batch_size + 1 if self.drop_last else n
            for s in range(0, max(stop, 0), self.batch_size):
                sel = idx[s:s + self.batch_size]
                yield self.collate_fn([ds[int(i)] for i in sel])
        else:  # already an iterable of batches
            yield from iter(ds)


class DistributedSampler:
    """Per-process index shard (reference torch DistributedSampler used by
    ``deepspeed_io``, engine.py:1561): on multi-host JAX each process
    feeds only its addressable slice of the global batch, so the sampler
    partitions the dataset by (num_replicas, rank) with per-epoch
    shuffling and padding to equal length."""

    def __init__(self, dataset_len, num_replicas=None, rank=None,
                 shuffle=True, seed=0, drop_last=False):
        import jax
        self.n = int(dataset_len)
        self.num_replicas = num_replicas if num_replicas is not None \
            else jax.process_count()
        self.rank = rank if rank is not None else jax.process_index()
        assert 0 <= self.rank < self.num_replicas
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.n // self.num_replicas
        return (self.n + self.num_replicas - 1) // self.num_replicas

    def __iter__(self):
        idx = np.arange(self.n)
        if self.shuffle:
            idx = np.random.default_rng(
                self.seed + self.epoch).permutation(self.n)
        if self.drop_last:
            idx = idx[:len(self) * self.num_replicas]
        else:  # pad by wrapping (possibly several times: tiny datasets
            # with many replicas) so every replica sees equal length
            target = len(self) * self.num_replicas
            if target > self.n:
                reps = -(-target // self.n)
                idx = np.tile(idx, reps)[:target]
        return iter(idx[self.rank::self.num_replicas].tolist())


class CurriculumDataLoader:
    """Wraps a loader, truncating token batches to the curriculum
    scheduler's current difficulty (reference DeepSpeedDataSampler /
    legacy ``curriculum_seqlen`` engine hook, engine.py:1692-1696)."""

    def __init__(self, loader, scheduler, step_fn=None,
                 keys=("input_ids", "labels", "attention_mask")):
        self.loader = loader
        self.scheduler = scheduler
        self.step_fn = step_fn or (lambda: self._step)
        self.keys = keys
        self._step = 0

    def __iter__(self):
        for batch in self.loader:
            seqlen = self.scheduler.update_difficulty(int(self.step_fn()))
            if isinstance(batch, dict):
                batch = {k: (v[:, :seqlen]
                             if k in self.keys and np.ndim(v) >= 2 else v)
                         for k, v in batch.items()}
            self._step += 1
            yield batch

    def __len__(self):
        return len(self.loader)


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference
    ``runtime/dataloader.py`` namesake, used by pipeline tests)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
