"""Top-level config: JSON file/dict -> typed config tree.

TPU-native rework of the reference's ``deepspeed/runtime/config.py`` (978 LoC):
the user-facing JSON keys are preserved (fp16/bf16/zero_optimization/optimizer/
scheduler/batch keys, reference `runtime/constants.py`), the batch-size
invariant ``train_batch_size = micro_batch * grad_accum * dp_world_size``
(reference ``config.py:853-915``) is enforced identically, and a TPU-only
``mesh`` section selects the device-mesh axis sizes (data/model/pipe/expert/
sequence) that replace the reference's process-group plumbing.
"""

import json
import os
from typing import ClassVar, Dict, Optional

from pydantic import Field

from deepspeed_tpu.comm.config import CommsLoggerConfig
from deepspeed_tpu.monitor.config import get_monitor_config
from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.runtime.config_utils import (DeepSpeedConfigModel,
                                                dict_raise_error_on_duplicate_keys)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.utils.logging import logger


class Fp16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0

    @property
    def dynamic_loss_scale(self):
        return self.loss_scale == 0

    @property
    def initial_dynamic_scale(self):
        return 2 ** self.initial_scale_power if self.dynamic_loss_scale else self.loss_scale


class Bf16Config(DeepSpeedConfigModel):
    enabled: bool = False


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class MeshConfig(DeepSpeedConfigModel):
    """TPU-only: sizes of the named mesh axes. -1 on at most one axis means
    "all remaining devices"; unspecified axes default to 1."""
    data: int = -1
    model: int = 1
    pipe: int = 1
    expert: int = 1
    sequence: int = 1


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-only: jax.checkpoint policy name ("nothing_saveable",
    # "dots_saveable", "dots_with_no_batch_dims_saveable", ...)
    remat_policy: Optional[str] = None

    _inert_fields: ClassVar[Dict[str, str]] = {
        "partition_activations": "saved residuals carry the program's "
                                 "SPMD shardings; there is no replicated "
                                 "per-TP-rank activation copy to slice",
        "contiguous_memory_optimization": "XLA lays out residuals",
        "number_checkpoints": "checkpoint granularity is the model's "
                              "per-block remat",
        "synchronize_checkpoint_boundary": "no streams to synchronize",
        "profile": "use the flops profiler / jax profiler traces",
    }


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write_pipeline: bool = False
    async_save: bool = False  # TPU-only: orbax-style async save


class AioConfig(DeepSpeedConfigModel):
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class PldConfig(DeepSpeedConfigModel):
    enabled: bool = False
    theta: float = 1.0
    gamma: float = 0.001


class EigenvalueConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


class OptimizerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: dict = {}
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: dict = {}


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig:
    """Parse + validate a config dict/file, resolve the batch invariant.

    ``dp_world_size`` is the *data-parallel* degree = mesh data axis size
    (reference resolved it from torch.distributed world size / mp / pp).
    """

    def __init__(self, config, dp_world_size=1):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"Config file {config} not found")
            with open(config) as f:
                self._param_dict = json.load(
                    f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif config is None:
            self._param_dict = {}
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path or dict, got {type(config)}")

        pd = self._param_dict
        self.dp_world_size = dp_world_size

        # --- batch sizes (resolved below) ---
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS)

        # --- subsections ---
        self.optimizer = OptimizerConfig(**(pd.get(C.OPTIMIZER) or {}))
        self.scheduler = SchedulerConfig(**(pd.get(C.SCHEDULER) or {}))
        self.fp16 = Fp16Config(**(pd.get(C.FP16) or {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD)) or {}
        self.bf16 = Bf16Config(**bf16_dict)
        self.data_types = DataTypesConfig(**(pd.get(C.DATA_TYPES) or {}))
        self.zero_config = DeepSpeedZeroConfig(**(pd.get("zero_optimization") or {}))
        self.mesh_config = MeshConfig(**(pd.get(C.MESH) or {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **(pd.get("activation_checkpointing") or {}))
        self.checkpoint_config = CheckpointConfig(**(pd.get(C.CHECKPOINT) or {}))
        self.aio_config = AioConfig(**(pd.get("aio") or {}))
        self.monitor_config = get_monitor_config(pd)
        self.comms_logger = CommsLoggerConfig(**(pd.get("comms_logger") or {}))
        self.flops_profiler = DeepSpeedFlopsProfilerConfig(
            **(pd.get("flops_profiler") or {}))
        self.pld = PldConfig(**(pd.get(C.PLD) or {}))
        self.eigenvalue = EigenvalueConfig(**(pd.get(C.EIGENVALUE) or {}))
        elastic_dict = pd.get("elasticity") or {}
        self.elasticity_enabled = bool(elastic_dict.get("enabled", False))
        if self.elasticity_enabled:
            from deepspeed_tpu.elasticity import ElasticityConfig
            self.elasticity = ElasticityConfig(elastic_dict)
        else:
            self.elasticity = None
        self.curriculum_learning = pd.get("curriculum_learning") or {}
        self.curriculum_enabled = bool(
            self.curriculum_learning.get("enabled", False))
        self.data_efficiency = pd.get("data_efficiency") or {}
        self.compression_training = pd.get("compression_training") or {}
        self.checkpoint_engine = pd.get("checkpoint_engine") or {}
        self.autotuning_config = pd.get("autotuning") or {}

        # --- scalars ---
        self.gradient_clipping = pd.get(C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = pd.get(
            C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN, C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.dataloader_drop_last = pd.get(C.DATALOADER_DROP_LAST, C.DATALOADER_DROP_LAST_DEFAULT)
        self.communication_data_type = pd.get(
            C.COMMUNICATION_DATA_TYPE, C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.disable_allgather = pd.get(C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.matmul_precision = pd.get(C.MATMUL_PRECISION, "default")

        self._warn_unknown_sections(pd)
        self._apply_elasticity()
        self._resolve_batch_parameters()
        self._do_sanity_check()

    def _apply_elasticity(self):
        """Elasticity OVERRIDES the batch parameters (reference
        deepspeed/__init__.py + elasticity integration: the computed
        elastic batch replaces any non-elastic batch config)."""
        if not self.elasticity_enabled:
            return
        from deepspeed_tpu.elasticity import compute_elastic_config
        from deepspeed_tpu.utils.logging import logger
        has_batch_info = any(x is not None for x in (
            self.train_batch_size, self.train_micro_batch_size_per_gpu,
            self.gradient_accumulation_steps))
        if has_batch_info and not \
                self.elasticity.ignore_non_elastic_batch_info:
            raise DeepSpeedConfigError(
                "elasticity is enabled but batch parameters are also set; "
                "remove them or set "
                "elasticity.ignore_non_elastic_batch_info=true")
        # compute_elastic_config divides world by the config's
        # model_parallel_size to get replicas; dp_world_size already IS
        # the replica count, so reconstruct the world it expects
        world = self.dp_world_size * self.elasticity.model_parallel_size
        final_batch, _, micro = compute_elastic_config(
            self.elasticity, world_size=world, return_microbatch=True)
        self.train_batch_size = final_batch
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = final_batch // (
            micro * self.dp_world_size)
        logger.info(f"elasticity: batch={final_batch} micro={micro} "
                    f"gas={self.gradient_accumulation_steps} "
                    f"(dp={self.dp_world_size})")

    _KNOWN_KEYS = frozenset({
        C.TRAIN_BATCH_SIZE, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
        C.GRADIENT_ACCUMULATION_STEPS, C.OPTIMIZER, C.SCHEDULER, C.FP16,
        C.BFLOAT16, C.BFLOAT16_OLD, C.DATA_TYPES, "zero_optimization",
        C.MESH, "activation_checkpointing", C.CHECKPOINT, "aio",
        "comms_logger", "flops_profiler", C.PLD, C.EIGENVALUE, "elasticity",
        "curriculum_learning", "data_efficiency", "compression_training",
        "checkpoint_engine",
        "autotuning", C.GRADIENT_CLIPPING, C.PRESCALE_GRADIENTS,
        C.GRADIENT_PREDIVIDE_FACTOR, C.SPARSE_GRADIENTS, C.STEPS_PER_PRINT,
        C.WALL_CLOCK_BREAKDOWN, C.MEMORY_BREAKDOWN, C.DUMP_STATE,
        C.DATALOADER_DROP_LAST, C.COMMUNICATION_DATA_TYPE,
        C.DISABLE_ALLGATHER, C.MATMUL_PRECISION, "monitor", "tensorboard",
        "wandb", "csv_monitor", "zero_allow_untested_optimizer",
    })

    def _warn_unknown_sections(self, pd):
        """A real-world DeepSpeed config with a section this build doesn't
        implement must say so instead of silently 'working' (VERDICT weak
        #9: unvalidated sections misread as supported)."""
        from deepspeed_tpu.utils.logging import logger
        for key in pd:
            if key not in self._KNOWN_KEYS:
                logger.warning(
                    f"config section '{key}' is not recognized by "
                    "deepspeed_tpu and will be IGNORED")

    # --- batch invariant (reference runtime/config.py:853-915) ---
    def _resolve_batch_parameters(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = self.dp_world_size

        if all(x is not None for x in (train, micro, gas)):
            pass  # checked in sanity check
        elif train is not None and micro is not None:
            gas = train // (micro * dp)
        elif train is not None and gas is not None:
            micro = train // (dp * gas)
        elif micro is not None and gas is not None:
            train = micro * gas * dp
        elif train is not None:
            gas = 1
            micro = train // dp
        elif micro is not None:
            train = micro * dp
            gas = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "needs to be provided")

        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    def _batch_assertion(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        assert train > 0, f"Train batch size: {train} has to be greater than 0"
        assert micro > 0, f"Micro batch size per device: {micro} has to be greater than 0"
        assert gas > 0, f"Gradient accumulation steps: {gas} has to be greater than 0"
        assert train == micro * gas * self.dp_world_size, (
            f"Check batch related parameters. train_batch_size is not equal to "
            f"micro_batch_per_device * gradient_acc_step * world_size "
            f"{train} != {micro} * {gas} * {self.dp_world_size}")

    def _do_sanity_check(self):
        self._batch_assertion()
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.zero_config.stage > 0 and not (self.fp16.enabled or self.bf16.enabled):
            logger.info("ZeRO with fp32 params: state sharding still applies")

    # convenience accessors used across the runtime
    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    @property
    def precision_dtype(self):
        if self.bf16.enabled:
            return "bfloat16"
        if self.fp16.enabled:
            return "float16"
        return "float32"

    def print_config(self, name="DeepSpeedConfig"):
        logger.info(f"{name}:")
        for key in sorted(self.__dict__):
            if key.startswith("_"):
                continue
            logger.info(f"  {key} = {self.__dict__[key]}")
