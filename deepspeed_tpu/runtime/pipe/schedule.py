"""Pipeline instruction schedules (reference: ``runtime/pipe/schedule.py``
— ``PipeSchedule`` base, ``InferenceSchedule`` :135, ``TrainSchedule`` :189
(1F1B), instruction classes :237+).

On TPU the *jitted* pipeline (pipe/module.py) executes a fused SPMD
program, so these schedules serve two roles: (1) parity surface + host-side
driver for eager/debug stage execution, (2) the specification the fused
program is tested against (each microbatch's forward must precede its
backward, buffer counts bounded by stages, etc.).
"""


class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    """Operates on a numbered activation buffer (reference :291)."""

    def __init__(self, buffer_id, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


class PipeSchedule:
    """Yields lists of instructions per step for one (stage, #stages,
    #microbatches) coordinate (reference PipeSchedule)."""

    def __init__(self, micro_batches, stages, stage_id):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self):
        raise NotImplementedError

    def steps(self):
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference :135)."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        total = self.micro_batches + self.stages - 1
        out = []
        for step_id in range(total):
            cmds = []
            mb = step_id - self.stage_id
            buf = mb % 2
            if 0 <= mb < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buf))
                else:
                    cmds.append(RecvActivation(buf))
                cmds.append(ForwardPass(buf))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buf))
            out.append(cmds)
        return out


class TrainSchedule(PipeSchedule):
    """1F1B (reference TrainSchedule :189): each stage runs
    `stages - stage_id - 1` warmup forwards, then alternates 1 forward /
    1 backward, then drains backwards. Peak live activations per stage =
    stages - stage_id, which is what num_pipe_buffers reports."""

    def num_pipe_buffers(self):
        return max(min(self.stages - self.stage_id,
                       self.micro_batches), 2)

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(S - s - 1, M)
        nbuf = self.num_pipe_buffers()
        out = []
        fwd_mb = 0   # next microbatch to forward
        bwd_mb = 0   # next microbatch to backward

        def fwd_cmds(mb):
            buf = mb % nbuf
            cmds = []
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(buf))
            else:
                cmds.append(RecvActivation(buf))
            cmds.append(ForwardPass(buf))
            if not self.is_last_stage:
                cmds.append(SendActivation(buf))
            return cmds

        def bwd_cmds(mb):
            buf = mb % nbuf
            cmds = []
            if not self.is_last_stage:
                cmds.append(RecvGrad(buf))
            cmds.append(BackwardPass(buf))
            if not self.is_first_stage:
                cmds.append(SendGrad(buf))
            return cmds

        # warmup forwards
        for _ in range(warmup):
            out.append(fwd_cmds(fwd_mb))
            fwd_mb += 1
        # steady state: 1F1B
        while fwd_mb < M:
            out.append(fwd_cmds(fwd_mb))
            fwd_mb += 1
            out.append(bwd_cmds(bwd_mb))
            bwd_mb += 1
        # drain backwards
        while bwd_mb < M:
            out.append(bwd_cmds(bwd_mb))
            bwd_mb += 1
        # epilogue (reference :232-246)
        out.append([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
        return out
