"""Pipeline-parallel module (reference: ``runtime/pipe/module.py`` —
``LayerSpec`` :36, ``PipelineModule`` :85, partitioning :353 via
``partition_balanced`` ``runtime/utils.py:599``).

TPU redesign: instead of per-rank layer ownership + p2p send/recv
(reference ``runtime/pipe/p2p.py``, engine instruction loop), the pipeline
is ONE SPMD program over the `pipe` mesh axis:

  * per-stage block params are **stacked** on a leading axis sharded over
    `pipe` (logical name "pipe");
  * a ``shard_map`` + ``lax.scan`` runs the GPipe fill-drain: every step
    each stage applies its blocks to its current activation, then
    ``ppermute`` shifts activations to the next stage while stage 0
    ingests the next microbatch;
  * backward is jax autodiff through the scan — the reverse pipeline
    (grad ppermute in the opposite direction) is generated, not hand
    written; remat inside the block bounds live activations like 1F1B.

Embedding and head run outside the pipelined region (they are
data-parallel work; at scale their cost is dominated by the blocks).

``LayerSpec``/``partition_balanced`` are kept for API parity and for the
host-driven schedule tests (pipe/schedule.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

import flax.linen as nn


# --------------------------------------------------------- reference parity
class LayerSpec:
    """Deferred layer construction (reference LayerSpec, pipe/module.py:36)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """Layer whose params are shared across stages (reference :63). In the
    TPU design tied weights live outside the pipelined region (embed/head),
    so tying is structural rather than an allreduce."""

    def __init__(self, key, typename, *args, forward_fn=None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights, num_parts):
    """Balanced contiguous partition of weighted items: returns part
    boundaries of length num_parts+1 (reference ``partition_balanced``,
    runtime/utils.py:599 — binary search over prefix sums)."""
    weights = list(weights)
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)
    prefix = np.concatenate([[0], np.cumsum(weights)])

    def parts_needed(max_weight):
        parts, cur = 1, 0.0
        for w in weights:
            if w > max_weight:
                return num_parts + 1
            if cur + w > max_weight:
                parts += 1
                cur = w
            else:
                cur += w
        return parts

    lo, hi = max(weights), float(prefix[-1])
    for _ in range(60):
        mid = (lo + hi) / 2
        if parts_needed(mid) <= num_parts:
            hi = mid
        else:
            lo = mid
    # build boundaries greedily at weight hi; a stage must also break when
    # the remaining items are only enough to give each remaining stage one
    # (otherwise trailing stages end up empty, e.g. 4 blocks / 3 stages)
    bounds, cur = [0], 0.0
    for i, w in enumerate(weights):
        parts_left = num_parts - (len(bounds) - 1)
        must_break = (n - i) <= (parts_left - 1) and i > bounds[-1]
        if (cur + w > hi or must_break) and len(bounds) < num_parts:
            bounds.append(i)
            cur = w
        else:
            cur += w
    while len(bounds) < num_parts:
        bounds.append(n)
    bounds.append(n)
    return bounds


# ------------------------------------------------------------ SPMD pipeline
def _rebox(tree, prefix_names, like):
    """Box `tree`'s leaves with `prefix_names` + the logical names carried
    by the corresponding (Partitioned-boxed) leaves of `like`."""
    from deepspeed_tpu.parallel import sharding as shd
    names = shd.get_logical_specs(like)   # same structure as unboxed `tree`

    def f(x, nm):
        inner = tuple(nm) if nm is not None \
            else (None,) * (np.ndim(x) - len(prefix_names))
        return nn.Partitioned(x, tuple(prefix_names) + inner)

    return jax.tree.map(f, tree, names)


def pipeline_spmd_forward(stage_params, x, *, block_apply, mesh,
                          num_microbatches, rng=None):
    """Run stacked-stage blocks as a GPipe pipeline over the `pipe` axis.

    stage_params: pytree, leaves [S, k, ...] ('pipe'-sharded on dim 0).
    x: activations [batch, ...] (batch divisible by num_microbatches).
    Returns activations [batch, ...] after all S*k blocks.
    """
    S = mesh.shape["pipe"]
    M = num_microbatches
    b = x.shape[0]
    assert b % M == 0, f"batch {b} not divisible by microbatches {M}"
    xs = x.reshape(M, b // M, *x.shape[1:])

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    def use(ax, dim):
        return ax if ax in mesh.shape and mesh.shape[ax] > 1 and \
            dim % mesh.shape[ax] == 0 else None

    # microbatch tensors: batch may stay data-sharded through the pipeline
    x_spec = P(None, use("data", xs.shape[1]), *([None] * (xs.ndim - 2)))
    p_spec = jax.tree.map(lambda a: P("pipe", *([None] * (a.ndim - 1))),
                          stage_params)

    def per_stage(params_loc, xs_loc):
        params_loc = jax.tree.map(lambda a: a[0], params_loc)  # [k, ...]
        stage = lax.axis_index("pipe")
        T = M + S - 1
        # derive a stage-varying zero so scan carries have consistent
        # device-varying axes (see ops/attention/ring.py)
        svar = jax.tree.leaves(params_loc)[0].ravel()[0] * 0.0
        cur0 = jnp.zeros_like(xs_loc[0]) + svar.astype(xs_loc.dtype)
        outs0 = jnp.zeros_like(xs_loc) + svar.astype(xs_loc.dtype)

        def body(carry, t):
            cur, outs = carry
            inp = jnp.where(stage == 0, xs_loc[jnp.clip(t, 0, M - 1)], cur)
            # decorrelate dropout across stages and pipeline steps
            step_rng = None if rng is None else \
                jax.random.fold_in(jax.random.fold_in(rng, t), stage)
            y = block_apply(params_loc, inp, step_rng)
            # record the finished microbatch on the last stage
            out_t = t - (S - 1)
            is_last = stage == S - 1
            valid = jnp.logical_and(out_t >= 0, is_last)
            idx = jnp.clip(out_t, 0, M - 1)
            outs = outs.at[idx].set(jnp.where(valid, y, outs[idx]))
            # shift activations downstream (stage i -> i+1)
            shifted = lax.ppermute(y, "pipe",
                                   [(i, i + 1) for i in range(S - 1)])
            return (shifted, outs), None

        (_, outs), _ = lax.scan(body, (cur0, outs0), jnp.arange(M + S - 1))
        # only the last stage holds real outputs; broadcast them
        mask = (stage == S - 1).astype(outs.dtype)
        return lax.psum(outs * mask, "pipe")

    out_spec = x_spec
    fn = shard_map(per_stage, mesh=mesh, in_specs=(p_spec, x_spec),
                   out_specs=out_spec)
    outs = fn(stage_params, xs)
    return outs.reshape(b, *x.shape[1:])


class PipelineModule:
    """Uniform-block pipeline model with engine-compatible init/apply.

    Construction (TPU-native path):
        PipelineModule(block=BlockModule, num_blocks=L, num_stages=S,
                       embed=EmbedModule, head=HeadModule,
                       num_microbatches=M)

    Reference-parity path: ``PipelineModule(layers=[LayerSpec, ...])`` is
    accepted for host-side partitioning math (``stage_ranges``); fused SPMD
    execution requires the uniform-block form.
    """

    def __init__(self, layers=None, *, block=None, num_blocks=None,
                 num_stages=None, embed=None, head=None,
                 num_microbatches=None, partition_method="parameters",
                 loss_fn=None, tied_head=False, schedule="1f1b",
                 layer_weights=None):
        self.layers = layers
        self.block = block
        self.num_blocks = num_blocks
        self.num_stages = num_stages
        self.embed = embed
        self.head = head
        self.num_microbatches = num_microbatches or (num_stages or 1)
        self.partition_method = partition_method
        self.loss_fn = loss_fn
        # tied_head: head receives the embed params (reference
        # TiedLayerSpec — embeddings shared between first and last stage;
        # here both live outside the pipelined region, so tying is direct)
        self.tied_head = tied_head
        # "1f1b": training runs the bounded-memory interleaved schedule
        # (one_f_one_b.py); "gpipe": autodiff through the fill-drain scan
        assert schedule in ("1f1b", "gpipe"), schedule
        self.schedule = schedule
        if block is not None:
            assert num_blocks is not None and num_stages is not None
            # non-uniform stages (reference LayerSpec weights +
            # partition_balanced, runtime/utils.py:599): each stage's
            # stack is padded to the max and padded slots are skipped
            w = list(layer_weights) if layer_weights is not None \
                else [1] * num_blocks
            assert len(w) == num_blocks, (len(w), num_blocks)
            bounds = partition_balanced(w, num_stages)
            self.k_per_stage = tuple(bounds[i + 1] - bounds[i]
                                     for i in range(num_stages))
            assert min(self.k_per_stage) >= 1, \
                f"empty pipeline stage: {self.k_per_stage}"
            self.layers_per_stage = max(self.k_per_stage)
            self.uniform = len(set(self.k_per_stage)) == 1

    # --------------------------------------------------------- 1F1B loss
    def make_loss_fn(self, per_token_loss=None):
        """Engine-compatible ``loss_fn(params, batch, rng)`` running the
        1F1B schedule (runtime/pipe/one_f_one_b.py). The default
        per-token loss is next-token CE with -100 ignore (the reference
        PipelineEngine's loss_fn contract, pipe/engine.py:285)."""
        from deepspeed_tpu.runtime.pipe.one_f_one_b import (
            make_pipeline_loss_fn)

        if per_token_loss is None:
            from deepspeed_tpu.models.gpt2 import gpt2_loss_fn

            def per_token_loss(logits, labels):
                return gpt2_loss_fn(logits, {"labels": labels})

        cache = {}

        def resolve(batch):
            from deepspeed_tpu import comm as dist
            mesh = dist.get_mesh()
            assert mesh is not None and \
                mesh.shape.get("pipe") == self.num_stages, \
                "active mesh must carry the pipe axis sized num_stages"
            key = id(mesh)
            if key not in cache:
                cache[key] = make_pipeline_loss_fn(
                    self, per_token_loss, mesh=mesh,
                    num_microbatches=self.num_microbatches)
            ids = batch["input_ids"]
            labels = batch.get("labels")
            if labels is None:
                labels = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)),
                                 constant_values=-100)
            return cache[key], ids, labels

        def split(params):
            return {"stages": params["stages"],
                    "embed": params.get("embed", {}),
                    "head": params.get("head", {})}

        def loss_fn(params, batch, rng):
            fn, ids, labels = resolve(batch)
            return fn(split(params), ids, labels)

        def loss_and_grads(params, batch):
            """One interleaved scan for (loss, grads) — the engine's
            training fast path. Going through value_and_grad would run
            the forward-only pipeline AND the interleaved scan (3x
            forward FLOPs); this is the reference's 2x (forward +
            activation-checkpoint recompute)."""
            fn, ids, labels = resolve(batch)
            return fn.pipeline_bwd_grads(split(params), ids, labels)

        loss_fn.loss_and_grads = loss_and_grads
        return loss_fn

    # ---------------------------------------------------- reference parity
    def stage_ranges(self, weights=None):
        """Layer index ranges per stage for a LayerSpec pipeline."""
        assert self.layers is not None
        n = len(self.layers)
        w = weights or [1] * n
        bounds = partition_balanced(w, self.num_stages)
        return [(bounds[i], bounds[i + 1]) for i in range(self.num_stages)]

    # ------------------------------------------------------- flax protocol
    def init(self, rng, x, *args, **kwargs):
        assert self.block is not None, \
            "fused pipeline needs the uniform-block construction"
        S, k = self.num_stages, self.layers_per_stage
        r_embed, r_blocks, r_head = jax.random.split(rng, 3)
        params = {}
        a = x
        if self.embed is not None:
            ev = self.embed.init(r_embed, x)
            params["embed"] = ev.get("params", ev)
            a = self.embed.apply({"params": nn.meta.unbox(params["embed"])}, x)

        keys = jax.random.split(r_blocks, S * k)
        inner = self.block.init(keys[0], a).get("params", None)  # for names
        stacked = jax.vmap(
            lambda r: nn.meta.unbox(self.block.init(r, a)
                                    .get("params", None)))(keys)
        stacked = jax.tree.map(
            lambda leaf: leaf.reshape(S, k, *leaf.shape[1:]), stacked)
        params["stages"] = _rebox(stacked, ("pipe", "layers"), like=inner)

        if self.head is not None:
            kw = {"embed_params": nn.meta.unbox(params["embed"])} \
                if self.tied_head else {}
            hv = self.head.init(r_head, a, **kw)
            params["head"] = hv.get("params", hv)
        return {"params": params}

    def apply(self, variables, x, *args, deterministic=True, rngs=None,
              mutable=None, **kwargs):
        from deepspeed_tpu import comm as dist
        params = variables["params"]
        params = nn.meta.unbox(params) if _has_boxes(params) else params
        mesh = dist.get_mesh()
        assert mesh is not None and mesh.shape["pipe"] == self.num_stages, \
            "active mesh must carry the pipe axis sized num_stages"

        a = x
        if self.embed is not None:
            a = self.embed.apply({"params": params["embed"]}, x)

        block = self.block
        drop_rng = (rngs or {}).get("dropout")

        uniform = self.uniform
        k_per_stage = self.k_per_stage

        def block_apply(kparams, h, step_rng):
            k_local = None if uniform else \
                jnp.asarray(k_per_stage)[lax.axis_index("pipe")]

            def one(carry, xs):
                h, i = carry
                layer_params = xs
                kw = {}
                if step_rng is not None:
                    kw["rngs"] = {"dropout": jax.random.fold_in(step_rng, i)}
                y = block.apply({"params": layer_params}, h,
                                deterministic, **kw)
                if isinstance(y, tuple):  # blocks with a (x, cache) contract
                    y = y[0]
                if k_local is not None:   # padded slot on a short stage
                    y = jnp.where(i < k_local, y, h)
                return (y, i + 1), None
            (h, _), _ = lax.scan(one, (h, jnp.int32(0)), kparams)
            return h

        a = pipeline_spmd_forward(params["stages"], a,
                                  block_apply=block_apply, mesh=mesh,
                                  num_microbatches=self.num_microbatches,
                                  rng=drop_rng)
        if self.head is not None:
            kw = {"embed_params": params["embed"]} if self.tied_head else {}
            a = self.head.apply({"params": params["head"]}, a, **kw)
        if mutable is not None:
            return a, {}
        return a


def _has_boxes(tree):
    return any(isinstance(l, nn.Partitioned)
               for l in jax.tree.leaves(
                   tree, is_leaf=lambda x: isinstance(x, nn.Partitioned)))
