"""1F1B pipeline training as one SPMD program.

Reference: ``runtime/pipe/schedule.py:189`` (``TrainSchedule`` — the 1F1B
instruction stream) and ``runtime/pipe/engine.py:599-1099`` (its
executor: per-rank p2p send/recv, PartitionedTensor activations, tied
grads). The defining property of 1F1B over GPipe is *bounded in-flight
activations*: a stage holds at most O(S) microbatch activations, not
O(M + S).

TPU redesign: the schedule is a single ``lax.scan`` under ``shard_map``
over the ``pipe`` mesh axis, with every stage running the same program
and stage-dependent predicates. One scan tick = one forward AND one
backward slot (the 1F1B steady state):

  * forward of microbatch m runs on stage s at tick ``t = m + s``;
    activations hop downstream via ``ppermute``;
  * backward of m runs on stage s at tick ``t = 2(S-1) - s + m``; grads
    hop upstream via the reverse ``ppermute``;
  * each stage keeps a **ring buffer** of its block-stack inputs, size
    ``R = 2S-1`` — the 1F1B in-flight bound. The backward tick re-runs
    the stage forward under ``jax.vjp`` from the saved input (DeepSpeed's
    PP + activation-checkpointing configuration) and accumulates param
    grads in the scan carry;
  * the embedding runs inside stage 0 and the head + loss inside stage
    S-1, so the only cross-stage reduction at the end is the scalar loss
    and the (small) embed/head grads — the GPipe path's x S broadcast of
    full activations (VERDICT weak #3) does not exist here. Tied
    embeddings get grad contributions from both ends of the pipe, summed
    by the same psum (reference ``pipe/module.py:406`` tied allreduce).

Autodiff never sees the pipeline: the public entry is a
``jax.custom_vjp`` whose forward is a residual-free forward-only scan
and whose backward IS the interleaved 1F1B scan returning hand-built
grads — so ``jax.value_and_grad`` (what the engine calls) works
unchanged on top.

Total ticks: forward-only ``M + S - 1``; interleaved ``M + 2(S-1)``.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


def _get_shard_map():
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _unwrap(y):
    return y[0] if isinstance(y, tuple) else y


def make_pipeline_loss_fn(pipe, per_token_loss, *, mesh, num_microbatches):
    """Build ``loss_fn(variables, ids, labels) -> scalar`` running the
    1F1B pipeline over `mesh`'s pipe axis.

    pipe: a PipelineModule (uniform stacked stages, embed + head).
    per_token_loss: ``(logits, labels) -> scalar mean loss`` (e.g.
    models.gpt2.gpt2_loss_fn's core).
    """
    S = mesh.shape.get("pipe")
    assert S, "mesh must carry a pipe axis"
    M = num_microbatches
    block = pipe.block
    embed = pipe.embed
    head = pipe.head
    tied = pipe.tied_head
    shard_map = _get_shard_map()

    def use(ax, dim):
        return ax if ax in mesh.shape and mesh.shape[ax] > 1 and \
            dim % mesh.shape[ax] == 0 else None

    uniform = getattr(pipe, "uniform", True)
    k_per_stage = getattr(pipe, "k_per_stage", None)

    def stack_fwd(params_k, h):
        k_local = None if uniform else \
            jnp.asarray(k_per_stage)[lax.axis_index("pipe")]

        def one(carry, p):
            h, j = carry
            y = _unwrap(block.apply({"params": p}, h))
            if k_local is not None:      # padded slot on a short stage
                y = jnp.where(j < k_local, y, h)
            return (y, j + 1), None
        (h, _), _ = lax.scan(one, (h, jnp.int32(0)), params_k)
        return h

    def head_loss(head_params, embed_params, h, labels_m):
        kw = {"embed_params": embed_params} if tied else {}
        logits = head.apply({"params": head_params}, h, **kw)
        return per_token_loss(logits, labels_m)

    # ---------------------------------------------------- forward only
    def fwd_loss(params, ids, labels):
        stages, embed_p, head_p = params["stages"], params["embed"], \
            params["head"]
        b = ids.shape[0]
        assert b % M == 0, f"batch {b} % microbatches {M} != 0"
        mb = b // M
        ids_m = ids.reshape(M, mb, *ids.shape[1:])
        lab_m = labels.reshape(M, mb, *labels.shape[1:])

        x_spec = P(None, use("data", mb), *([None] * (ids_m.ndim - 2)))
        p_spec = jax.tree.map(lambda a: P("pipe", *([None] * (a.ndim - 1))),
                              stages)
        r_spec = jax.tree.map(lambda a: P(*([None] * np.ndim(a))), embed_p)
        h_spec = jax.tree.map(lambda a: P(*([None] * np.ndim(a))), head_p)

        def per_stage(stages_loc, embed_loc, head_loc, ids_loc, lab_loc):
            params_k = jax.tree.map(lambda a: a[0], stages_loc)
            s = lax.axis_index("pipe")
            # a zero that is device-varying over EVERY manual axis in
            # play (pipe from params, data from the batch), so scan
            # carries pass the shard_map vma type discipline
            svar = (jax.tree.leaves(params_k)[0].ravel()[0]
                    .astype(jnp.float32) * 0.0 +
                    ids_loc.ravel()[0].astype(jnp.float32) * 0.0)

            embed0 = embed.apply({"params": embed_loc}, ids_loc[0])
            cur0 = jnp.zeros_like(embed0) + svar.astype(embed0.dtype)

            def tick(carry, t):
                cur, loss_acc = carry
                m_f = t - s
                emb = embed.apply({"params": embed_loc},
                                  ids_loc[jnp.clip(m_f, 0, M - 1)])
                inp = jnp.where(s == 0, emb, cur)
                y = stack_fwd(params_k, inp)
                is_last = s == S - 1
                fwd_on = jnp.logical_and(m_f >= 0, m_f < M)
                lm = head_loss(head_loc, embed_loc, y,
                               lab_loc[jnp.clip(m_f, 0, M - 1)])
                loss_acc = loss_acc + jnp.where(
                    jnp.logical_and(is_last, fwd_on), lm, 0.0)
                nxt = lax.ppermute(y, "pipe",
                                   [(i, i + 1) for i in range(S - 1)])
                return (nxt, loss_acc), None

            (_, loss_acc), _ = lax.scan(
                tick, (cur0, jnp.float32(0.0) + svar), jnp.arange(M + S - 1))
            loss = lax.psum(loss_acc, "pipe") / M
            if use("data", mb):
                loss = lax.pmean(loss, "data")
            return loss

        fn = shard_map(per_stage, mesh=mesh,
                       in_specs=(p_spec, r_spec, h_spec, x_spec, x_spec),
                       out_specs=P())
        return fn(stages, embed_p, head_p, ids_m, lab_m)

    # ------------------------------------------------- interleaved 1F1B
    # grads computed at unit cotangent; the caller scales by the real
    # cotangent afterwards (shard_map must not close over tracers)
    def bwd_grads(params, ids, labels):
        stages, embed_p, head_p = params["stages"], params["embed"], \
            params["head"]
        b = ids.shape[0]
        mb = b // M
        ids_m = ids.reshape(M, mb, *ids.shape[1:])
        lab_m = labels.reshape(M, mb, *labels.shape[1:])
        R = 2 * S - 1
        T = M + 2 * (S - 1)

        x_spec = P(None, use("data", mb), *([None] * (ids_m.ndim - 2)))
        p_spec = jax.tree.map(lambda a: P("pipe", *([None] * (a.ndim - 1))),
                              stages)
        r_spec = jax.tree.map(lambda a: P(*([None] * np.ndim(a))), embed_p)
        h_spec = jax.tree.map(lambda a: P(*([None] * np.ndim(a))), head_p)

        def per_stage(stages_loc, embed_loc, head_loc, ids_loc, lab_loc):
            params_k = jax.tree.map(lambda a: a[0], stages_loc)
            s = lax.axis_index("pipe")
            # a zero that is device-varying over EVERY manual axis in
            # play (pipe from params, data from the batch), so scan
            # carries pass the shard_map vma type discipline
            svar = (jax.tree.leaves(params_k)[0].ravel()[0]
                    .astype(jnp.float32) * 0.0 +
                    ids_loc.ravel()[0].astype(jnp.float32) * 0.0)

            embed0 = embed.apply({"params": embed_loc}, ids_loc[0])
            act_shape = embed0.shape
            zeros_act = jnp.zeros(act_shape, embed0.dtype)
            cur0 = zeros_act + svar.astype(embed0.dtype)
            gcur0 = jnp.zeros(act_shape, jnp.float32) + svar
            ring0 = jnp.zeros((R,) + act_shape, embed0.dtype) + \
                svar.astype(embed0.dtype)
            # Gradient/vma discipline: under shard_map's vma type system,
            # jax.vjp w.r.t. values that are REPLICATED over a manual axis
            # auto-inserts a psum over that axis (the transpose of the
            # implicit broadcast). So: (a) every cotangent is pre-gated —
            # masking after the vjp would be too late, the invalid
            # devices' contributions are already summed in; (b) no manual
            # psum/pmean on grads of replicated params — the vjp already
            # produced the global sum; (c) the data-parallel 1/dp
            # normalization rides in the seed cotangent.
            dpn = float(mesh.shape["data"]) if use("data", mb) else 1.0
            pg0 = jax.tree.map(lambda a: a.astype(jnp.float32) * 0.0,
                               params_k)
            eg0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                               embed_loc)
            hg0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                               head_loc)

            def tick(carry, t):
                cur, gcur, ring, pg, eg, hg, loss_acc = carry
                # ---------------- forward slot: microbatch m_f = t - s
                m_f = t - s
                fwd_on = jnp.logical_and(m_f >= 0, m_f < M)
                emb = embed.apply({"params": embed_loc},
                                  ids_loc[jnp.clip(m_f, 0, M - 1)])
                inp = jnp.where(s == 0, emb, cur)
                inp = jnp.where(fwd_on, inp, zeros_act)
                ring = lax.dynamic_update_index_in_dim(
                    ring, inp.astype(ring.dtype), jnp.mod(t, R), 0)
                y = stack_fwd(params_k, inp)

                # last stage: head loss + dy for the SAME microbatch
                # (its backward tick coincides with its forward tick)
                is_last = s == S - 1
                lab_f = lab_loc[jnp.clip(m_f, 0, M - 1)]
                lm, head_vjp = jax.vjp(
                    lambda hp, ep, h: head_loss(hp, ep, h, lab_f),
                    head_loc, embed_loc, y)
                hgate = jnp.where(jnp.logical_and(is_last, fwd_on), 1.0, 0.0)
                ct = (hgate / (M * dpn)).astype(lm.dtype) + \
                    svar.astype(lm.dtype)
                dhp, dep_h, dy = head_vjp(ct)
                hg = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                                  hg, dhp)
                eg = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                                  eg, dep_h)
                loss_acc = loss_acc + jnp.where(
                    jnp.logical_and(is_last, fwd_on), lm, 0.0)

                # --------------- backward slot: microbatch m_b
                m_b = t - (2 * (S - 1) - s)
                bwd_on = jnp.logical_and(m_b >= 0, m_b < M)
                t_saved = m_b + s                       # its forward tick here
                inp_b = lax.dynamic_index_in_dim(
                    ring, jnp.mod(jnp.clip(t_saved, 0, T - 1), R), 0,
                    keepdims=False)
                inp_b = jnp.where(bwd_on, inp_b, zeros_act)
                g_in = jnp.where(is_last, dy.astype(jnp.float32), gcur)
                g_in = jnp.where(bwd_on, g_in, jnp.zeros_like(gcur))

                # recompute stage forward under vjp (activation ckpt);
                # g_in is gated, so dp/dx vanish on idle slots
                _, stack_vjp = jax.vjp(stack_fwd, params_k, inp_b)
                dp, dx = stack_vjp(g_in.astype(inp_b.dtype))
                pg = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                                  pg, dp)

                # stage 0 consumes dx into embedding grads: the stage gate
                # multiplies the COTANGENT (the vjp auto-psums over pipe)
                dx_emb = jnp.where(s == 0, dx, jnp.zeros_like(dx))
                _, emb_vjp = jax.vjp(
                    lambda ep: embed.apply(
                        {"params": ep}, ids_loc[jnp.clip(m_b, 0, M - 1)]),
                    embed_loc)
                (dep,) = emb_vjp(dx_emb.astype(embed0.dtype))
                eg = jax.tree.map(lambda a, d: a + d.astype(jnp.float32),
                                  eg, dep)

                # hops: activations downstream, grads upstream
                nxt = lax.ppermute(y, "pipe",
                                   [(i, i + 1) for i in range(S - 1)])
                gnxt = lax.ppermute(dx.astype(jnp.float32), "pipe",
                                    [(i, i - 1) for i in range(1, S)])
                return (nxt, gnxt, ring, pg, eg, hg, loss_acc), None

            carry0 = (cur0, gcur0, ring0, pg0, eg0, hg0,
                      jnp.float32(0.0) + svar)
            (_, _, _, pg, eg, hg, loss_acc), _ = lax.scan(
                tick, carry0, jnp.arange(T))

            loss = lax.psum(loss_acc, "pipe") / M
            if use("data", mb):
                loss = lax.pmean(loss, "data")
            pg = jax.tree.map(lambda a: a[None], pg)   # [1, k, ...] shard
            return loss, pg, eg, hg

        fn = shard_map(per_stage, mesh=mesh,
                       in_specs=(p_spec, r_spec, h_spec, x_spec, x_spec),
                       out_specs=(P(), p_spec, r_spec, h_spec))
        loss, pg, eg, hg = fn(stages, embed_p, head_p, ids_m, lab_m)
        grads = {"stages": jax.tree.map(
                     lambda g, p: g.astype(jnp.asarray(p).dtype), pg, stages),
                 "embed": jax.tree.map(
                     lambda g, p: g.astype(jnp.asarray(p).dtype), eg, embed_p),
                 "head": jax.tree.map(
                     lambda g, p: g.astype(jnp.asarray(p).dtype), hg, head_p)}
        return loss, grads

    # ------------------------------------------------------ custom_vjp
    @jax.custom_vjp
    def loss_fn(params, ids, labels):
        return fwd_loss(params, ids, labels)

    def fwd(params, ids, labels):
        return fwd_loss(params, ids, labels), (params, ids, labels)

    def bwd(res, gbar):
        params, ids, labels = res
        _, grads = bwd_grads(params, ids, labels)
        grads = jax.tree.map(lambda g: g * gbar.astype(g.dtype), grads)
        zero_i = np.zeros(np.shape(ids), jax.dtypes.float0)
        zero_l = np.zeros(np.shape(labels), jax.dtypes.float0)
        return grads, zero_i, zero_l

    loss_fn.defvjp(fwd, bwd)
    loss_fn.pipeline_bwd_grads = bwd_grads   # exposed for direct tests
    return loss_fn
