"""Pipeline parallelism (reference: deepspeed/runtime/pipe/)."""

from deepspeed_tpu.runtime.pipe.module import (LayerSpec,  # noqa: F401
                                               PipelineModule,
                                               TiedLayerSpec,
                                               partition_balanced,
                                               pipeline_spmd_forward)
from deepspeed_tpu.runtime.pipe import schedule  # noqa: F401
