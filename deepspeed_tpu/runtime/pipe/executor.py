"""Host-driven executor for the pipeline instruction schedules.

Reference: ``PipelineEngine._exec_schedule`` (``runtime/pipe/engine.py:1286``)
dispatching each :class:`~deepspeed_tpu.runtime.pipe.schedule.PipeInstruction`
through ``_INSTRUCTION_MAP`` (:1273).

On TPU the production path is the fused SPMD 1F1B
(``runtime/pipe/one_f_one_b.py`` — one shard_map scan, XLA-scheduled).
This executor is the *eager* counterpart: it walks the same
``TrainSchedule``/``InferenceSchedule`` streams with per-stage callables
and an explicit mailbox for the p2p edges. Its roles:

  1. debug/irregular topologies — stages can be arbitrary Python
     callables (different devices, host stages, uneven shapes) that the
     fused jit cannot express;
  2. specification — the oracle tests assert its loss/grads equal plain
     autodiff, and the fused pipeline is tested against the same oracle,
     so schedule and fused program are pinned to the same semantics.

All stages run in one process. Like the reference's blocking p2p
(``pipe/p2p.py``), a Recv waits for its Send: stages advance
cooperatively, each yielding when the next instruction's mailbox entry
has not arrived yet.
"""

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                 InferenceSchedule,
                                                 LoadMicroBatch,
                                                 OptimizerStep, RecvActivation,
                                                 RecvGrad, ReduceGrads,
                                                 ReduceTiedGrads,
                                                 SendActivation, SendGrad,
                                                 TrainSchedule)


class _StageRun:
    """One stage's flattened instruction stream + its local buffers."""

    def __init__(self, stage_id, sched):
        self.stage_id = stage_id
        self.cmds = [c for step in sched.steps() for c in step]
        self.pos = 0
        self.bufs = {}      # buffer_id -> current activation / cotangent
        self.saved = {}     # buffer_id -> (vjp, microbatch index)
        self.fwd_mb = 0
        self.bwd_mb = 0
        self.cur_bwd_mb = 0
        self.mb_of = {}     # buffer_id -> microbatch currently in it

    def done(self):
        return self.pos >= len(self.cmds)

    def peek(self):
        return self.cmds[self.pos]


class ScheduleExecutor:
    """Execute instruction schedules over per-stage callables.

    Args:
      stage_fns: list of ``fn(stage_params, x) -> y``, one per stage.
      loss_fn: ``fn(last_stage_output, label_microbatch) -> scalar``
        (mean over the microbatch), used by :meth:`train`.
    """

    def __init__(self, stage_fns, loss_fn=None):
        self.stage_fns = list(stage_fns)
        self.loss_fn = loss_fn
        self.stages = len(self.stage_fns)

    def _drive(self, runs, ready, exec_one):
        """Cooperative round-robin: run each stage until it blocks on a
        Recv whose mailbox entry is missing; error on deadlock."""
        while any(not r.done() for r in runs):
            progressed = False
            for r in runs:
                while not r.done() and ready(r):
                    exec_one(r)
                    r.pos += 1
                    progressed = True
            if not progressed:
                stuck = {r.stage_id: repr(r.peek())
                         for r in runs if not r.done()}
                raise RuntimeError(
                    f"pipeline schedule deadlock; waiting on {stuck}")

    # ------------------------------------------------------------- train
    def train(self, stage_params, micro_inputs, micro_labels):
        """Run ``TrainSchedule`` for every stage; returns (mean_loss,
        per-stage grads) with grads averaged over microbatches — the
        same convention as the fused 1F1B (mean of microbatch means)."""
        M = len(micro_inputs)
        S = self.stages
        runs = [_StageRun(s, TrainSchedule(M, S, s)) for s in range(S)]
        act_mail, grad_mail = {}, {}
        losses = [None] * M
        grads = [None] * S

        # Mailboxes are keyed by (stage, MICROBATCH): adjacent stages
        # number buffers mod different nbuf, so buffer ids don't line up
        # across the p2p edge; microbatches are processed in order on
        # both sides (the reference's p2p pairs by send/recv order).
        def ready(r):
            cmd = r.peek()
            if isinstance(cmd, RecvActivation):
                return (r.stage_id, r.fwd_mb) in act_mail
            if isinstance(cmd, RecvGrad):
                return (r.stage_id, r.bwd_mb) in grad_mail
            if isinstance(cmd, ForwardPass):
                # 1F1B pacing: a buffer's vjp must be consumed by its
                # backward before the buffer is reused
                return cmd.buffer_id not in r.saved
            return True

        def exec_one(r):
            s, cmd = r.stage_id, r.peek()
            last = s == S - 1
            if isinstance(cmd, LoadMicroBatch):
                r.bufs[cmd.buffer_id] = micro_inputs[r.fwd_mb]
            elif isinstance(cmd, RecvActivation):
                r.bufs[cmd.buffer_id] = act_mail.pop((s, r.fwd_mb))
            elif isinstance(cmd, ForwardPass):
                mb, r.fwd_mb = r.fwd_mb, r.fwd_mb + 1
                r.mb_of[cmd.buffer_id] = mb
                x = r.bufs[cmd.buffer_id]
                if last and self.loss_fn is not None:
                    def run_fn(p, x_):
                        return self.loss_fn(self.stage_fns[s](p, x_),
                                            micro_labels[mb])
                    loss, vjp = jax.vjp(run_fn, stage_params[s], x)
                    losses[mb] = loss
                else:
                    y, vjp = jax.vjp(
                        lambda p, x_: self.stage_fns[s](p, x_),
                        stage_params[s], x)
                    r.bufs[cmd.buffer_id] = y
                r.saved[cmd.buffer_id] = vjp
            elif isinstance(cmd, SendActivation):
                act_mail[(s + 1, r.mb_of[cmd.buffer_id])] = \
                    r.bufs[cmd.buffer_id]
            elif isinstance(cmd, RecvGrad):
                r.bufs[cmd.buffer_id] = grad_mail.pop((s, r.bwd_mb))
            elif isinstance(cmd, BackwardPass):
                r.cur_bwd_mb, r.bwd_mb = r.bwd_mb, r.bwd_mb + 1
                vjp = r.saved.pop(cmd.buffer_id)
                if s == S - 1 and self.loss_fn is not None:
                    dp, dx = vjp(jnp.ones((), jnp.float32))
                else:
                    dp, dx = vjp(r.bufs[cmd.buffer_id])
                grads[s] = dp if grads[s] is None else \
                    jax.tree.map(jnp.add, grads[s], dp)
                r.bufs[cmd.buffer_id] = dx
            elif isinstance(cmd, SendGrad):
                grad_mail[(s - 1, r.cur_bwd_mb)] = r.bufs[cmd.buffer_id]
            elif isinstance(cmd, (ReduceTiedGrads, ReduceGrads,
                                  OptimizerStep)):
                pass  # single-process: reduction/step are the caller's
            else:
                raise TypeError(f"unknown instruction {cmd!r}")

        self._drive(runs, ready, exec_one)
        mean_loss = jnp.mean(jnp.stack(losses))
        grads = [jax.tree.map(lambda g: g / M, g) for g in grads]
        return mean_loss, grads

    # --------------------------------------------------------- inference
    def infer(self, stage_params, micro_inputs):
        """Run ``InferenceSchedule``; returns the last stage's outputs in
        microbatch order."""
        M = len(micro_inputs)
        S = self.stages
        runs = [_StageRun(s, InferenceSchedule(M, S, s)) for s in range(S)]
        act_mail = {}
        outs = [None] * M

        def ready(r):
            cmd = r.peek()
            if isinstance(cmd, RecvActivation):
                return (r.stage_id, r.fwd_mb) in act_mail
            return True

        def exec_one(r):
            s, cmd = r.stage_id, r.peek()
            if isinstance(cmd, LoadMicroBatch):
                r.bufs[cmd.buffer_id] = micro_inputs[r.fwd_mb]
            elif isinstance(cmd, RecvActivation):
                r.bufs[cmd.buffer_id] = act_mail.pop((s, r.fwd_mb))
            elif isinstance(cmd, ForwardPass):
                mb, r.fwd_mb = r.fwd_mb, r.fwd_mb + 1
                r.mb_of[cmd.buffer_id] = mb
                y = self.stage_fns[s](stage_params[s],
                                      r.bufs[cmd.buffer_id])
                r.bufs[cmd.buffer_id] = y
                if s == S - 1:
                    outs[mb] = y
            elif isinstance(cmd, SendActivation):
                act_mail[(s + 1, r.mb_of[cmd.buffer_id])] = \
                    r.bufs[cmd.buffer_id]
            else:
                raise TypeError(f"unknown instruction {cmd!r}")

        self._drive(runs, ready, exec_one)
        return outs
