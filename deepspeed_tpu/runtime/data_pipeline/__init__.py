"""Data-efficiency pipeline (reference ``deepspeed/runtime/data_pipeline/``:
curriculum learning on sequence length, difficulty-indexed data sampling
(v2), and random layerwise token dropping).
"""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (  # noqa: F401
    CurriculumScheduler)
from deepspeed_tpu.runtime.data_pipeline.data_sampling import (  # noqa: F401
    CurriculumIndexLoader, DataAnalyzer, DeepSpeedDataSampler, MetricIndex,
    find_fit_int_dtype)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (  # noqa: F401
    RandomLTDScheduler, random_ltd_gather, random_ltd_scatter)
