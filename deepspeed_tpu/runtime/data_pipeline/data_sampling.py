"""Data-efficiency v2 sampling suite: difficulty-metric analysis +
curriculum data sampler with deterministic mid-epoch resume.

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/`` —
``data_analyzer.py:20`` (map/reduce metric pass over the dataset),
``data_sampler.py:36`` (``DeepSpeedDataSampler``: per-step difficulty
thresholds -> sample clusters -> weighted cluster sampling),
``indexed_dataset.py:1`` (Megatron mmap bin/idx container).

TPU/numpy redesign (same capability, different data model):

* Index files are plain ``.npy`` arrays opened with ``mmap_mode="r"`` —
  no Megatron bin/idx container needed. Per metric the analyzer emits
  three aligned files under one prefix:
    ``{prefix}_sample_to_metric.npy``  value per sample, dataset order
    ``{prefix}_sorted_samples.npy``    sample ids ascending by value
    ``{prefix}_sorted_values.npy``     the values, same order
  The sorted pair replaces the reference's value-bucketed
  ``metric_to_sample`` rows: value-range selection is two
  ``np.searchsorted`` calls on the memmap instead of a scan over every
  bucket, and percentile selection is a slice.

* The sampler needs no collective: JAX training here is
  single-controller (the engine feeds GLOBAL batches and shards them
  over the mesh), and the batch stream is a pure function of the config
  seed, so any process that needs the stream recomputes it — the
  reference's rank-0 + ``dist.broadcast`` protocol
  (data_sampler.py:278-290) becomes deterministic replay.

* ``state_dict``/``load_state_dict`` carry the np Generator state, the
  in-flight batch remainder, cluster descriptors and read positions —
  resuming mid-epoch reproduces the exact uninterrupted sample stream
  (tested in tests/unit/test_data_sampling.py).
"""

import os

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler)


def find_fit_int_dtype(min_value, max_value):
    """Smallest numpy integer dtype covering [min_value, max_value]
    (reference data_sampling/utils.py:21)."""
    if min_value >= 0:
        for dt in (np.uint8, np.uint16, np.uint32, np.uint64):
            if max_value <= np.iinfo(dt).max:
                return dt
    else:
        for dt in (np.int8, np.int16, np.int32, np.int64):
            if np.iinfo(dt).min <= min_value and \
                    max_value <= np.iinfo(dt).max:
                return dt
    raise ValueError((min_value, max_value))


# --------------------------------------------------------------------------
# analyzer
# --------------------------------------------------------------------------
class DataAnalyzer:
    """Map/reduce difficulty-metric pass over an indexable dataset
    (reference data_analyzer.py:20).

    ``metric_functions`` get a LIST of raw samples (this worker's batch)
    and return one value per sample (``single_value_per_sample``) or one
    aggregate (``accumulate_value_over_samples``). Values must be
    integers — ties and exact threshold comparisons stay exact (the
    reference enforces the same, data_analyzer.py:64).

    Workers split the dataset into contiguous shards; each writes
    ``{prefix}_worker{W}.npy``. ``run_reduce`` concatenates the shards
    in worker order (so the final file is dataset-ordered) and builds
    the sorted index.
    """

    def __init__(self, dataset, num_workers=1, worker_id=0, batch_size=64,
                 metric_names=(), metric_functions=(), metric_types=(),
                 save_path="./", collate_fn=None):
        self.dataset = dataset
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = list(metric_types) or \
            ["single_value_per_sample"] * len(self.metric_names)
        self.save_path = save_path
        self.collate_fn = collate_fn

    def _prefix(self, metric):
        return os.path.join(self.save_path, metric)

    def _worker_range(self, worker_id):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        return worker_id * per, min((worker_id + 1) * per, n)

    def run_map(self):
        os.makedirs(self.save_path, exist_ok=True)
        start, end = self._worker_range(self.worker_id)
        results = [[] for _ in self.metric_names]
        for s in range(start, end, self.batch_size):
            batch = [self.dataset[i] for i in range(
                s, min(s + self.batch_size, end))]
            if self.collate_fn is not None:
                batch = self.collate_fn(batch)
            for m, fn in enumerate(self.metric_functions):
                vals = np.asarray(fn(batch))
                if self.metric_types[m] == "single_value_per_sample":
                    assert np.issubdtype(vals.dtype, np.integer), \
                        f"metric {self.metric_names[m]} must be integer-" \
                        "valued (reference data_analyzer.py:64)"
                    results[m].append(vals.reshape(-1))
                else:  # accumulate_value_over_samples
                    results[m].append(vals)
        for m, name in enumerate(self.metric_names):
            if self.metric_types[m] == "single_value_per_sample":
                out = np.concatenate(results[m]) if results[m] else \
                    np.zeros(0, np.int64)
            else:
                out = np.sum(np.stack(results[m]), axis=0) if results[m] \
                    else np.zeros(0, np.int64)
            np.save(f"{self._prefix(name)}_worker{self.worker_id}.npy", out)

    def run_reduce(self):
        for m, name in enumerate(self.metric_names):
            parts = []
            for w in range(self.num_workers):
                f = f"{self._prefix(name)}_worker{w}.npy"
                assert os.path.exists(f), \
                    f"missing worker shard {f}: run_map all workers first"
                parts.append(np.load(f))
            if self.metric_types[m] == "single_value_per_sample":
                s2m = np.concatenate(parts)
                assert len(s2m) == len(self.dataset)
                dt = find_fit_int_dtype(int(s2m.min(initial=0)),
                                        int(s2m.max(initial=0)))
                np.save(f"{self._prefix(name)}_sample_to_metric.npy",
                        s2m.astype(dt))
                order = np.argsort(s2m, kind="stable")
                idt = find_fit_int_dtype(0, len(s2m))
                np.save(f"{self._prefix(name)}_sorted_samples.npy",
                        order.astype(idt))
                np.save(f"{self._prefix(name)}_sorted_values.npy",
                        s2m[order].astype(dt))
            else:
                np.save(f"{self._prefix(name)}_metric_value.npy",
                        np.sum(np.stack(parts), axis=0))
            for w in range(self.num_workers):
                os.remove(f"{self._prefix(name)}_worker{w}.npy")

    def run_map_reduce(self):
        assert self.num_workers == 1 or self.worker_id == 0, \
            "run_map_reduce is the single-process convenience path"
        for w in range(self.num_workers):
            DataAnalyzer(self.dataset, self.num_workers, w, self.batch_size,
                         self.metric_names, self.metric_functions,
                         self.metric_types, self.save_path,
                         self.collate_fn).run_map()
        self.run_reduce()


class MetricIndex:
    """Memmapped view over one metric's analyzer output."""

    def __init__(self, prefix):
        self.sample_to_metric = np.load(
            prefix + "_sample_to_metric.npy", mmap_mode="r")
        self.sorted_samples = np.load(
            prefix + "_sorted_samples.npy", mmap_mode="r")
        self.sorted_values = np.load(
            prefix + "_sorted_values.npy", mmap_mode="r")

    def __len__(self):
        return len(self.sample_to_metric)

    def samples_in_value_range(self, lo, hi):
        """Sample ids with metric value in (lo, hi] — the reference's
        get_sample_based_on_metric_value (data_sampler.py:127) as two
        binary searches on the sorted index."""
        a = np.searchsorted(self.sorted_values, lo, side="right")
        b = np.searchsorted(self.sorted_values, hi, side="right")
        return np.asarray(self.sorted_samples[a:b])

    def samples_in_percentile_range(self, p_start, p_end, max_percentile):
        """Reference get_sample_based_on_metric_percentile
        (data_sampler.py:137): count-based slices of the sorted order.
        Bounds scale as n*p//max rather than (n//max)*p so datasets
        smaller than max_percentile still admit samples (n//max == 0
        would make every intermediate difficulty empty) and the tail
        n % max_percentile isn't excluded until the very last step."""
        n = len(self)
        a = n * p_start // max_percentile
        b = n if p_end == max_percentile else n * p_end // max_percentile
        return np.asarray(self.sorted_samples[a:b])


# --------------------------------------------------------------------------
# sampler
# --------------------------------------------------------------------------
class DeepSpeedDataSampler:
    """Curriculum data sampler (reference data_sampler.py:36).

    ``data_efficiency_config`` mirrors the reference json keys::

        {"seed": 1234,
         "data_sampling": {"num_epochs": N,
           "curriculum_learning": {"enabled": true,
             "data_cluster_path": dir,
             "curriculum_metrics": {
               "<metric>": {"index_prefix": path-prefix,
                            "difficulty_type": "value"|"percentile",
                            "clustering_type": "cluster"|"single_cluster",
                            "min_difficulty": ..., "max_difficulty": ...,
                            "schedule_type": ..., "schedule_config": {...}}}}}}

    (``index_prefix`` replaces the reference's ``index_to_sample_path``/
    ``index_to_metric_path`` pair — one prefix names all three npy files
    the analyzer wrote.)

    Yields per-data-parallel-rank lists of sample indices, one micro
    batch per ``__iter__`` step; a new GLOBAL batch is drawn (and the
    curriculum stepped) every ``micro_batch x dp_size x gas`` samples.
    """

    def __init__(self, data_efficiency_config, one_epoch_total_samples,
                 micro_batch_size, data_parallel_rank=0,
                 data_parallel_size=1, data_parallel_group=None,
                 gradient_accumulation_steps=1, global_rank=0,
                 drop_last=True):
        self.config = data_efficiency_config
        self.one_epoch_total_samples = int(one_epoch_total_samples)
        ds_cfg = data_efficiency_config.get("data_sampling", {})
        self.total_samples = self.one_epoch_total_samples * \
            int(ds_cfg.get("num_epochs", 1000))
        self.micro_batch_size = micro_batch_size
        self.data_parallel_rank = data_parallel_rank
        self.micro_batch_times_data_parallel_size = \
            micro_batch_size * data_parallel_size
        self.global_batch_size = (self.micro_batch_times_data_parallel_size
                                  * gradient_accumulation_steps)
        self.global_rank = global_rank
        self.drop_last = drop_last
        self.index_dtype = find_fit_int_dtype(0, one_epoch_total_samples)
        self.np_rng = np.random.default_rng(
            int(data_efficiency_config.get("seed", 1234)))
        self.batch = []
        self.consumed_samples = 0

        cl = ds_cfg.get("curriculum_learning", {})
        self.curriculum_enabled = bool(cl.get("enabled", False))
        if self.curriculum_enabled:
            self.cluster_path = cl["data_cluster_path"]
            os.makedirs(self.cluster_path, exist_ok=True)
            self.curriculum_step = 0
            self.current_difficulties = {}
            self.data_cluster_paths = []
            self.data_cluster_current_position = []
            self.data_cluster_wraps = []  # reshuffle count per cluster
            self.data_clusters = []       # in-memory index arrays
            self.data_cluster_sizes = []
            self.curriculum_schedulers = {}
            self.difficulty_type = {}
            self.clustering_type = {}
            self.metric_index = {}
            for metric, mcfg in cl["curriculum_metrics"].items():
                self.curriculum_schedulers[metric] = \
                    CurriculumScheduler(mcfg)
                self.difficulty_type[metric] = mcfg["difficulty_type"]
                self.clustering_type[metric] = \
                    mcfg.get("clustering_type", "cluster")
                if self.clustering_type[metric] != "single_cluster":
                    self.metric_index[metric] = MetricIndex(
                        mcfg["index_prefix"])

        assert self.total_samples > 0
        assert self.micro_batch_size > 0
        assert data_parallel_size > 0
        assert self.data_parallel_rank < data_parallel_size

    def __len__(self):
        return self.total_samples

    def set_custom_curriculum_learning_schedule(self, fns):
        for metric, sched in self.curriculum_schedulers.items():
            if metric in fns:
                sched.set_custom_get_difficulty(fns[metric])

    # ------------------------------------------------------------- clusters
    def _admitted(self, metric, lo, hi):
        if self.difficulty_type[metric] == "value":
            return self.metric_index[metric].samples_in_value_range(lo, hi)
        maxd = self.curriculum_schedulers[metric].max_difficulty
        return self.metric_index[metric].samples_in_percentile_range(
            lo, hi, maxd)

    def _new_cluster(self, previous_difficulties):
        fname = "cluster"
        for metric in self.curriculum_schedulers:
            fname += f"_{metric}{self.current_difficulties[metric]}"
        path = os.path.join(self.cluster_path, fname + ".npy")

        multi = sum(1 for m, t in self.clustering_type.items()
                    if t != "single_cluster") > 1
        if multi:
            # intersect each metric's full admitted set, minus what
            # earlier clusters already hold (reference
            # data_sampler.py:178-195)
            new = None
            for metric in self.curriculum_schedulers:
                if self.clustering_type[metric] == "single_cluster":
                    part = np.arange(self.one_epoch_total_samples,
                                     dtype=self.index_dtype)
                else:
                    lo = float("-inf") \
                        if self.difficulty_type[metric] == "value" else 0
                    part = self._admitted(
                        metric, lo, self.current_difficulties[metric])
                new = part if new is None else np.intersect1d(
                    new, part, assume_unique=True)
            for cluster in self.data_clusters:
                new = np.setdiff1d(new, cluster, assume_unique=True)
        else:
            new = None
            if not self.data_clusters:
                new = np.arange(self.one_epoch_total_samples,
                                dtype=self.index_dtype)
            for metric in self.curriculum_schedulers:
                if self.clustering_type[metric] != "single_cluster":
                    new = self._admitted(metric,
                                         previous_difficulties[metric],
                                         self.current_difficulties[metric])
        if new is not None and len(new):
            new = np.array(new, dtype=self.index_dtype)
            self.np_rng.shuffle(new)
            if self.global_rank == 0:
                np.save(path, new)
            self.data_clusters.append(new)
            self.data_cluster_sizes.append(len(new))
            self.data_cluster_paths.append(fname)
            self.data_cluster_current_position.append(0)
            self.data_cluster_wraps.append(0)

    def _cluster_file(self, cidx):
        """On-disk name of cluster cidx's CURRENT order. Each wrap
        reshuffle writes a NEW versioned file (never overwrites): a
        resume that restores pre-wrap rng state must find the pre-wrap
        array, or the replayed stream silently diverges from the
        uninterrupted one."""
        fname = self.data_cluster_paths[cidx]
        w = self.data_cluster_wraps[cidx]
        return os.path.join(self.cluster_path,
                            fname + (f"_w{w}" if w else "") + ".npy")

    def _sample_from_clusters(self):
        sizes = np.asarray(self.data_cluster_sizes, np.float64)
        if sizes.sum() == 0:
            raise ValueError(
                "curriculum admitted zero samples at step "
                f"{self.curriculum_step} (difficulties "
                f"{self.current_difficulties}): no metric value falls at "
                "or below the current threshold — raise min_difficulty "
                "or speed up the schedule")
        weights = sizes / sizes.sum()
        picks = self.np_rng.choice(len(self.data_clusters),
                                   self.global_batch_size, replace=True,
                                   p=weights)
        return np.bincount(picks, minlength=len(self.data_clusters))

    def _take_from_cluster(self, cidx, num):
        pos = self.data_cluster_current_position[cidx]
        cluster = self.data_clusters[cidx]
        out = list(cluster[pos:pos + num])
        self.data_cluster_current_position[cidx] = pos + num
        if len(out) < num:   # exhausted: reshuffle and wrap (reference
            remain = num - len(out)      # get_sample_from_cluster :246)
            reshuffled = np.array(cluster)
            self.np_rng.shuffle(reshuffled)
            self.data_clusters[cidx] = reshuffled
            self.data_cluster_wraps[cidx] += 1
            if self.global_rank == 0:
                np.save(self._cluster_file(cidx), reshuffled)
                # prune old generations (keep the last 3: enough for any
                # checkpoint taken within the last two wraps to resume;
                # load_state_dict raises a clear error for older ones)
                w_old = self.data_cluster_wraps[cidx] - 3
                if w_old >= 0:
                    fname = self.data_cluster_paths[cidx]
                    stale = os.path.join(
                        self.cluster_path,
                        fname + (f"_w{w_old}" if w_old else "") + ".npy")
                    if os.path.exists(stale):
                        os.remove(stale)
            out += list(reshuffled[:remain])
            self.data_cluster_current_position[cidx] = remain
        return out

    def _next_global_batch(self):
        if self.curriculum_enabled:
            self.curriculum_step += 1
            changed = False
            previous = {}
            for metric, sched in self.curriculum_schedulers.items():
                nxt = sched.update_difficulty(self.curriculum_step)
                if metric not in self.current_difficulties or \
                        nxt != self.current_difficulties[metric]:
                    changed = True
                previous[metric] = self.current_difficulties.get(
                    metric,
                    float("-inf")
                    if self.difficulty_type[metric] == "value" else 0)
                self.current_difficulties[metric] = nxt
            if changed:
                self._new_cluster(previous)
            per_cluster = self._sample_from_clusters()
            batch = []
            for cidx, num in enumerate(per_cluster):
                batch += self._take_from_cluster(cidx, int(num))
            self.np_rng.shuffle(batch)
            self.batch = [int(i) for i in batch]
        else:
            self.batch = [
                int(i) for i in self.np_rng.integers(
                    0, self.one_epoch_total_samples, self.global_batch_size)]

    def __iter__(self):
        while self.consumed_samples <= self.total_samples:
            if len(self.batch) == 0:
                self._next_global_batch()
            cur = self.batch[:self.micro_batch_times_data_parallel_size]
            self.batch = self.batch[
                self.micro_batch_times_data_parallel_size:]
            if len(cur) == self.micro_batch_times_data_parallel_size or \
                    (len(cur) > 0 and not self.drop_last):
                a = self.data_parallel_rank * self.micro_batch_size
                yield cur[a:a + self.micro_batch_size]
                self.consumed_samples += len(cur)

    # ---------------------------------------------------------------- state
    def state_dict(self):
        return {
            "batch": list(self.batch),
            "consumed_samples": self.consumed_samples,
            "curriculum_step": getattr(self, "curriculum_step", 0),
            "current_difficulties": dict(
                getattr(self, "current_difficulties", {})),
            "data_cluster_paths": list(
                getattr(self, "data_cluster_paths", [])),
            "data_cluster_current_position": list(
                getattr(self, "data_cluster_current_position", [])),
            "data_cluster_wraps": list(
                getattr(self, "data_cluster_wraps", [])),
            "np_rng_state": self.np_rng.bit_generator.state,
        }

    def load_state_dict(self, sd):
        self.batch = list(sd["batch"])
        self.consumed_samples = sd["consumed_samples"]
        self.np_rng.bit_generator.state = sd["np_rng_state"]
        if self.curriculum_enabled:
            self.curriculum_step = sd["curriculum_step"]
            self.current_difficulties = dict(sd["current_difficulties"])
            self.data_cluster_paths = list(sd["data_cluster_paths"])
            self.data_cluster_current_position = list(
                sd["data_cluster_current_position"])
            # older checkpoints predate cluster-file versioning
            self.data_cluster_wraps = list(sd.get(
                "data_cluster_wraps", [0] * len(self.data_cluster_paths)))
            self.data_clusters = []
            self.data_cluster_sizes = []
            for cidx in range(len(self.data_cluster_paths)):
                path = self._cluster_file(cidx)
                if not os.path.exists(path):
                    raise FileNotFoundError(
                        f"cluster file {path} was pruned: this "
                        "checkpoint predates the last 3 cluster-wrap "
                        "reshuffles. Resume from a newer checkpoint, or "
                        "re-run the analyzer to rebuild clusters")
                arr = np.load(path)
                self.data_clusters.append(arr)
                self.data_cluster_sizes.append(len(arr))


class CurriculumIndexLoader:
    """Loader over (dataset, DeepSpeedDataSampler): each sampler yield is
    a list of sample ids collated into one batch (the deepspeed_io
    integration point, reference engine.py:1561)."""

    def __init__(self, dataset, sampler, collate_fn=None):
        from deepspeed_tpu.runtime.dataloader import default_collate
        self.dataset = dataset
        self.data_sampler = sampler
        self.collate_fn = collate_fn or default_collate

    def __len__(self):
        return len(self.data_sampler) // max(
            self.data_sampler.micro_batch_times_data_parallel_size, 1)

    def __iter__(self):
        for idxs in self.data_sampler:
            yield self.collate_fn([self.dataset[int(i)] for i in idxs])
