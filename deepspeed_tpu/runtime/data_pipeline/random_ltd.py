"""Random layerwise token dropping (random-LTD).

Reference: ``runtime/data_pipeline/data_routing/basic_layer.py:14``
(RandomLayerTokenDrop wraps each transformer layer: a random subset of
tokens passes through the layer, the rest skip it) with CUDA
token_sort/gather kernels (``csrc/random_ltd/``). On TPU the
gather/scatter is ``jnp.take``/``.at[].set`` — XLA emits efficient
dynamic-gather; no custom kernel needed (SURVEY §2.4 random-LTD row).
"""

import jax
import jax.numpy as jnp


def random_ltd_indices(rng, seq_len, keep, batch):
    """[batch, keep] sorted indices of the tokens that pass through the
    layer (reference token_sort_: random selection, order-preserving)."""
    scores = jax.random.uniform(rng, (batch, seq_len))
    _, idx = jax.lax.top_k(scores, keep)
    return jnp.sort(idx, axis=1)


def random_ltd_gather(x, indices):
    """[b, l, d] -> [b, keep, d] (reference gather_tokens)."""
    return jnp.take_along_axis(x, indices[..., None], axis=1)


def random_ltd_scatter(sub, indices, full):
    """Scatter layer outputs back into the full sequence: dropped tokens
    keep their pre-layer values (reference scatter_tokens)."""
    b = jnp.arange(full.shape[0])[:, None]
    return full.at[b, indices].set(sub)


class RandomLTDScheduler:
    """Linear schedule of the kept-token count (reference
    data_routing/scheduler.py): from ``start_ratio*seq`` up to the full
    sequence over ``schedule_steps``."""

    def __init__(self, seq_len, start_tokens=None, schedule_steps=1000,
                 step_size=16):
        self.seq_len = seq_len
        self.start = start_tokens or max(seq_len // 4, step_size)
        self.steps = schedule_steps
        self.step_size = step_size
        self.current = self.start

    def keep_tokens(self, global_step):
        frac = min(1.0, global_step / self.steps)
        if frac >= 1.0:
            # exact completion regardless of step_size divisibility:
            # flooring 1000 to a 16-grid would leave 8 tokens dropped
            # forever after the schedule ends
            self.current = self.seq_len
            return self.current
        raw = self.start + frac * (self.seq_len - self.start)
        kept = int(raw // self.step_size * self.step_size)
        self.current = max(self.start, min(self.seq_len, kept))
        return self.current

    def state_dict(self):
        return {"current": self.current}

    def load_state_dict(self, sd):
        self.current = sd["current"]
