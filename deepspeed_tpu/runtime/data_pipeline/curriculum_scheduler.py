"""Curriculum difficulty scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py`` — fixed_linear /
fixed_root / fixed_discrete / custom schedules of e.g. sequence length,
consumed by the legacy engine hook ``curriculum_seqlen``
(engine.py:1692-1696)). Pure host-side math."""

import math


class CurriculumScheduler:
    """config: {"curriculum_type": "seqlen", "min_difficulty": M,
    "max_difficulty": N, "schedule_type": "fixed_linear" | "fixed_root" |
    "fixed_discrete" | "custom", "schedule_config": {...}}"""

    def __init__(self, config):
        self.config = dict(config)
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            assert key in self.config, f"curriculum config needs '{key}'"
        self.min_difficulty = int(self.config["min_difficulty"])
        self.max_difficulty = int(self.config["max_difficulty"])
        self.schedule_type = self.config["schedule_type"]
        sc = dict(self.config.get("schedule_config", {}))
        self.schedule = sc
        self.custom_get_difficulty = None
        if self.schedule_type == "fixed_linear":
            assert "total_curriculum_step" in sc and "difficulty_step" in sc
        elif self.schedule_type == "fixed_root":
            assert "total_curriculum_step" in sc and "difficulty_step" in sc \
                and "root_degree" in sc
        elif self.schedule_type == "fixed_discrete":
            assert "difficulty" in sc and "max_step" in sc
            assert len(sc["difficulty"]) == len(sc["max_step"]) + 1
        elif self.schedule_type == "custom":
            pass
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type}")
        self.state = {"current_difficulty": self.min_difficulty}

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn

    def _root(self, step, degree):
        sc = self.schedule
        frac = min(1.0, step / sc["total_curriculum_step"]) ** (1.0 / degree)
        d = self.min_difficulty + frac * (self.max_difficulty -
                                          self.min_difficulty)
        # round UP to the difficulty_step grid, capped at max
        q = sc["difficulty_step"]
        return int(min(self.max_difficulty, math.ceil(d / q) * q))

    def get_difficulty(self, global_steps):
        if self.schedule_type == "fixed_linear":
            return self._root(global_steps, 1)
        if self.schedule_type == "fixed_root":
            return self._root(global_steps, self.schedule["root_degree"])
        if self.schedule_type == "fixed_discrete":
            sc = self.schedule
            for difficulty, max_step in zip(sc["difficulty"], sc["max_step"]):
                if global_steps <= max_step:
                    return difficulty
            return sc["difficulty"][-1]
        assert self.custom_get_difficulty is not None, \
            "custom schedule needs set_custom_get_difficulty"
        return self.custom_get_difficulty(global_steps)

    def update_difficulty(self, global_steps):
        self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]

    def get_current_difficulty(self):
        return self.state["current_difficulty"]

    def state_dict(self):
        return dict(self.state)

    def load_state_dict(self, sd):
        self.state.update(sd)
