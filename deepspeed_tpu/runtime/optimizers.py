"""Optimizer factory: config ``optimizer.type`` -> optax transform.

Reference: ``runtime/engine.py:1226`` (``_configure_basic_optimizer``) selects
Adam/AdamW/FusedAdam/CPUAdam/Lamb/OnebitAdam/OnebitLamb/ZeroOneAdam. On TPU
the "fused" variants are moot (XLA fuses the update), so every Adam spelling
maps to one XLA-fused implementation; the 1-bit communication-compressed
variants fall back to their uncompressed parents for now (grad compression is
a comm-layer concern here, not an optimizer one).

The returned transform is wrapped in ``optax.inject_hyperparams`` so the
learning rate is a state field the engine can drive from an LR schedule
without recompiling.
"""

import optax

from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import logger


def _lr_of(params, default=1e-3):
    return params.get("lr", default)


def build_optimizer(name, params=None, gradient_clipping=0.0):
    """Build an optax GradientTransformation from a DeepSpeed optimizer
    config section. Returns (tx, static_lr)."""
    params = dict(params or {})
    name = (name or C.ADAMW_OPTIMIZER).lower()
    # normalize reference spellings
    aliases = {
        "fusedadam": C.ADAM_OPTIMIZER,
        "cpuadam": C.ADAM_OPTIMIZER,  # host-offload handled by ZeRO offload path
        "deepspeedcpuadam": C.ADAM_OPTIMIZER,
        "fusedlamb": C.LAMB_OPTIMIZER,
    }
    name = aliases.get(name, name)

    lr = params.pop("lr", 1e-3)
    betas = params.pop("betas", (0.9, 0.999))
    eps = params.pop("eps", 1e-8)
    weight_decay = params.pop("weight_decay", 0.0)
    adam_w_mode = params.pop("adam_w_mode", True)
    momentum = params.pop("momentum", 0.0)
    bias_correction = params.pop("bias_correction", True)
    freeze_step = params.pop("freeze_step", 100)
    var_freeze_step = params.pop("var_freeze_step", 100000)
    var_update_scaler = params.pop("var_update_scaler", 16)
    params.pop("torch_adam", None)
    # the engine consumes comm_backend_name (compressed grad sync);
    # local-step knobs are subsumed by the engine's sync (zoadam.py)
    params.pop("comm_backend_name", None)
    params.pop("cuda_aware", None)
    params.pop("local_step_scaler", None)
    params.pop("local_step_clipper", None)
    for k in list(params):
        logger.warning(f"Optimizer param '{k}' ignored on TPU backend")

    def make(learning_rate):
        lr_ = learning_rate
        if name == C.ZERO_ONE_ADAM_OPTIMIZER:
            from deepspeed_tpu.runtime.fp16.onebit import zero_one_adam
            return zero_one_adam(lr_, b1=betas[0], b2=betas[1], eps=eps,
                                 weight_decay=weight_decay,
                                 var_freeze_step=var_freeze_step,
                                 var_update_scaler=var_update_scaler)
        if name == C.ONEBIT_ADAM_OPTIMIZER:
            from deepspeed_tpu.runtime.fp16.onebit import onebit_adam
            return onebit_adam(lr_, b1=betas[0], b2=betas[1], eps=eps,
                               weight_decay=weight_decay,
                               freeze_step=freeze_step)
        if name == C.ONEBIT_LAMB_OPTIMIZER:
            from deepspeed_tpu.runtime.fp16.onebit import onebit_lamb
            return onebit_lamb(lr_, b1=betas[0], b2=betas[1], eps=eps,
                               weight_decay=weight_decay,
                               freeze_step=freeze_step)
        if name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER):
            if name == C.ADAM_OPTIMIZER and not adam_w_mode:
                return optax.adam(lr_, b1=betas[0], b2=betas[1], eps=eps)
            return optax.adamw(lr_, b1=betas[0], b2=betas[1], eps=eps,
                               weight_decay=weight_decay)
        if name == C.LAMB_OPTIMIZER:
            return optax.lamb(lr_, b1=betas[0], b2=betas[1], eps=eps,
                              weight_decay=weight_decay)
        if name == C.SGD_OPTIMIZER:
            return optax.sgd(lr_, momentum=momentum or None)
        if name == C.ADAGRAD_OPTIMIZER:
            return optax.adagrad(lr_, eps=eps)
        if name == C.LION_OPTIMIZER:
            return optax.lion(lr_, b1=betas[0], b2=betas[1],
                              weight_decay=weight_decay)
        raise ValueError(f"Unknown optimizer type: {name}")

    tx = optax.inject_hyperparams(make)(lr)
    return tx, lr
