"""ZeRO-Offload / ZeRO-Infinity: host-RAM (and NVMe) optimizer state.

Reference: the CPU-offload path of ``runtime/zero/stage_1_and_2.py``
(``async_accumulate_grad_in_cpu_via_gpu`` :1031, cpu_adam step :1636) and
the NVMe tier ``runtime/swap_tensor/partitioned_param_swapper.py:1`` /
``optimizer_utils.py`` over the aio handle.

TPU-native shape of the idea: the chip keeps only the **bf16 compute
copy** of the params; fp32 master params + Adam moments live in host
numpy buffers updated by the C++ host kernel (``csrc/host_adam.cpp``).
Per step:

  1. backward: bf16 grads start an async D2H per leaf (half the PCIe
     traffic of fp32, like the reference's fp16 grad copies) and are
     accumulated into fp32 host buffers,
  2. step: per leaf — unscale/clip + fused Adam on host (producing the
     new bf16 bits in the same pass), optionally streaming moments
     from/to NVMe with double-buffered async reads/writes,
  3. the new bf16 leaves are device_put back with their shardings.

Dynamic loss scaling runs host-side with the same skip/hysteresis
semantics as the in-jit scaler (runtime/fp16/loss_scaler.py).
"""

import os

import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import (DeepSpeedCPUAdam, axpy,
                                             has_inf_nan, l2_norm_sq)
from deepspeed_tpu.ops.op_builder import CPUAdamBuilder
from deepspeed_tpu.utils.logging import logger


def _to_f32(np_arr):
    """bf16(ml_dtypes)/f16/f32 numpy -> contiguous f32."""
    if np_arr.dtype == np.float32:
        return np.ascontiguousarray(np_arr)
    lib = CPUAdamBuilder().load() if CPUAdamBuilder().is_compatible() else None
    if lib is not None and np_arr.dtype.itemsize == 2 and \
            np_arr.dtype.name == "bfloat16":
        src = np.ascontiguousarray(np_arr).view(np.uint16)
        out = np.empty(src.size, np.float32)
        import ctypes
        lib.ds_bf16_to_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), src.size)
        return out.reshape(np_arr.shape)
    return np_arr.astype(np.float32)


class HostLossScaler:
    """Host mirror of DynamicLossScaler semantics (reference
    runtime/fp16/loss_scaler.py:264)."""

    def __init__(self, fp16_cfg, enabled):
        # static mode (loss_scale != 0) keeps the configured scale fixed
        # (reference LossScaler); only dynamic mode adjusts on overflow
        self.enabled = bool(enabled) and (
            fp16_cfg is None or bool(fp16_cfg.dynamic_loss_scale))
        if enabled and fp16_cfg is not None:
            self.loss_scale = float(fp16_cfg.initial_dynamic_scale)
            self.scale_window = int(fp16_cfg.loss_scale_window)
            self.min_scale = float(fp16_cfg.min_loss_scale)
            self.hysteresis = int(fp16_cfg.hysteresis)
            self.factor = 2.0
        else:
            self.loss_scale = 1.0
            self.scale_window = 1 << 30
            self.min_scale = 1.0
            self.hysteresis = 1
            self.factor = 2.0
        self._good_steps = 0
        self._bad_count = 0

    def update(self, overflow):
        if not self.enabled:
            return
        if overflow:
            self._good_steps = 0
            self._bad_count += 1
            if self._bad_count >= self.hysteresis:
                self.loss_scale = max(self.loss_scale / self.factor,
                                      self.min_scale)
                self._bad_count = 0
        else:
            self._good_steps += 1
            if self._good_steps >= self.scale_window:
                self.loss_scale *= self.factor
                self._good_steps = 0


class NvmeMomentStore:
    """Adam moments on NVMe with double-buffered async IO.

    One file per (leaf, moment); read of leaf i+1 is submitted before the
    update of leaf i runs, write-back of leaf i is submitted after — the
    reference's pipeline_read/pipeline_write behavior
    (swap_tensor/optimizer_utils.py)."""

    def __init__(self, nvme_path, sizes, aio_config=None, fresh=True):
        from deepspeed_tpu.ops.aio import AioHandle
        self.dir = os.path.join(nvme_path, "zero_offload_moments")
        os.makedirs(self.dir, exist_ok=True)
        kw = {}
        if aio_config is not None:
            kw = dict(block_size=aio_config.block_size,
                      queue_depth=aio_config.queue_depth,
                      thread_count=aio_config.thread_count)
        self.read_handle = AioHandle(**kw)
        self.write_handle = AioHandle(**kw)
        self.sizes = sizes
        for i, n in enumerate(sizes):
            for tag in ("m", "v"):
                path = self._path(i, tag)
                # fresh (the default, matching a newly-constructed
                # optimizer): ALWAYS zero-fill — a reused nvme_path must
                # not warm-start Adam from a previous run's moments;
                # resume goes through load_state_dict, which rewrites
                # these files anyway
                if fresh or not os.path.exists(path):
                    np.zeros(n, np.float32).tofile(path)

    def _path(self, i, tag):
        return os.path.join(self.dir, f"leaf{i}_{tag}.bin")

    def prefetch(self, i):
        bufs = (np.empty(self.sizes[i], np.float32),
                np.empty(self.sizes[i], np.float32))
        self.read_handle.async_pread(bufs[0], self._path(i, "m"))
        self.read_handle.async_pread(bufs[1], self._path(i, "v"))
        return bufs

    def fetch_wait(self):
        self.read_handle.wait()

    def writeback(self, i, m, v):
        self.write_handle.async_pwrite(m, self._path(i, "m"))
        self.write_handle.async_pwrite(v, self._path(i, "v"))

    def flush(self):
        self.write_handle.wait()


class NvmeParamTier:
    """ZeRO-Infinity parameter tier: fp32 master params, fp32 gradient
    accumulators AND the at-rest compute-dtype copy live in per-leaf
    NVMe files (reference ``swap_tensor/partitioned_param_swapper.py``
    semantics: the steady-state working set in RAM is a couple of leaf
    buffers, not the model).

    Layout: ``<nvme_path>/zero_param_tier/leaf{i}_{master|acc|param}.bin``.
    The param (compute-copy) files are written with page-cached pwrites so
    the engine's ``np.memmap`` views — the H2D source at dispatch time —
    stay coherent; master/acc IO goes through the aio handle pair with
    prefetch-next-leaf double buffering.

    Gradient accumulation is a read-modify-write per (leaf, micro batch);
    the first accumulate after a consumed window overwrites instead
    (``_acc_valid``), so no zero-fill pass is needed. Each RMW also
    refreshes the leaf's grad-norm/overflow stats, so the optimizer sweep
    needs no extra pre-pass over the accumulators."""

    def __init__(self, nvme_path, aio_config=None, param_dtype="bf16"):
        from deepspeed_tpu.ops.aio import AioHandle
        self.dir = os.path.join(nvme_path, "zero_param_tier")
        os.makedirs(self.dir, exist_ok=True)
        kw = {}
        if aio_config is not None:
            kw = dict(block_size=aio_config.block_size,
                      queue_depth=aio_config.queue_depth,
                      thread_count=aio_config.thread_count)
        self.read_handle = AioHandle(**kw)
        self.write_handle = AioHandle(**kw)
        self.param_dtype = param_dtype          # "bf16" | "f32"
        self.sizes = []
        self.shapes = []
        self._acc_valid = []
        self._norm_sq = []
        self._inf = []
        self.stats = {"nvme_read_bytes": 0, "nvme_write_bytes": 0,
                      "nvme_wait_s": 0.0}
        self.peak_buffer_bytes = 0
        self._live_bytes = 0

    def _p(self, i, tag):
        return os.path.join(self.dir, f"leaf{i}_{tag}.bin")

    def _track(self, *bufs):
        self._live_bytes += sum(b.nbytes for b in bufs)
        self.peak_buffer_bytes = max(self.peak_buffer_bytes,
                                     self._live_bytes)

    def _untrack(self, *bufs):
        self._live_bytes -= sum(b.nbytes for b in bufs)

    # ------------------------------------------------------------- init
    def add_leaf(self, master_f32_flat, shape):
        """Persist one leaf's master + compute copy; returns its index."""
        i = len(self.sizes)
        self.sizes.append(master_f32_flat.size)
        self.shapes.append(tuple(shape))
        master_f32_flat.tofile(self._p(i, "master"))
        self._write_param_file(i, master_f32_flat)
        self._acc_valid.append(False)
        self._norm_sq.append(0.0)
        self._inf.append(False)
        return i

    def _write_param_file(self, i, master_f32_flat):
        if self.param_dtype == "bf16":
            from deepspeed_tpu.ops.adam.cpu_adam import f32_to_bf16
            buf = f32_to_bf16(master_f32_flat).view(np.uint16)
        elif self.param_dtype == "f16":
            buf = master_f32_flat.astype(np.float16)
        else:
            buf = master_f32_flat
        # Page-cached write (no O_DIRECT) keeps the engine's memmap
        # views of the param files coherent. In-place r+b (never "wb"):
        # a truncate would yank pages out from under the live mappings
        # — a concurrent reader (async checkpoint writer faulting a
        # page) would SIGBUS past the shrunken EOF.
        path = self._p(i, "param")
        mode = "r+b" if os.path.exists(path) else "wb"
        with open(path, mode) as f:
            f.write(np.ascontiguousarray(buf).tobytes())
        self.stats["nvme_write_bytes"] += buf.nbytes

    def param_memmaps(self):
        """The at-rest compute copies as memory-mapped views (mode r+ =
        MAP_SHARED, so post-step pwrites show through). The engine hands
        these straight to jax.device_put: pages stream file->H2D on
        demand and the page cache — not the process — holds what fits."""
        import ml_dtypes
        out = []
        for i, (n, shape) in enumerate(zip(self.sizes, self.shapes)):
            if self.param_dtype == "bf16":
                mm = np.memmap(self._p(i, "param"), np.uint16, "r+",
                               shape=(n,))
                out.append(mm.view(ml_dtypes.bfloat16).reshape(shape))
            elif self.param_dtype == "f16":
                out.append(np.memmap(self._p(i, "param"), np.float16,
                                     "r+", shape=shape))
            else:
                out.append(np.memmap(self._p(i, "param"), np.float32,
                                     "r+", shape=shape))
        return out

    # ------------------------------------------------------ accumulation
    def accumulate(self, i, grad):
        """RMW one leaf's fp32 accumulator on NVMe. ``grad`` is a dense
        array (any float dtype) or a sparse ``(indices, values)`` pair."""
        n = self.sizes[i]
        if self._acc_valid[i]:
            acc = np.empty(n, np.float32)
            self._track(acc)
            self.read_handle.async_pread(acc, self._p(i, "acc"))
            self.read_handle.wait()
            self.stats["nvme_read_bytes"] += acc.nbytes
        else:
            acc = np.zeros(n, np.float32)
            self._track(acc)
        if isinstance(grad, tuple):
            idx, vals = grad
            np.add.at(acc.reshape(self.shapes[i]), np.asarray(idx),
                      _to_f32(np.asarray(vals)))
        else:
            axpy(acc, _to_f32(grad).reshape(-1))
        self._norm_sq[i] = l2_norm_sq(acc)
        self._inf[i] = bool(has_inf_nan(acc))
        self.write_handle.async_pwrite(acc, self._p(i, "acc"))
        self.write_handle.wait()
        self.stats["nvme_write_bytes"] += acc.nbytes
        self._untrack(acc)
        self._acc_valid[i] = True

    def grad_stats(self):
        """(sum of squared norms, any-overflow) over valid accumulators."""
        return sum(self._norm_sq), any(self._inf)

    # -------------------------------------------------------- step sweep
    def prefetch(self, i):
        """Submit async reads of leaf i's (master, acc); pair with
        :meth:`wait_fetched`."""
        bufs = (np.empty(self.sizes[i], np.float32),
                np.empty(self.sizes[i], np.float32))
        self._track(*bufs)
        self.read_handle.async_pread(bufs[0], self._p(i, "master"))
        self.read_handle.async_pread(bufs[1], self._p(i, "acc"))
        self.stats["nvme_read_bytes"] += 2 * bufs[0].nbytes
        return bufs

    def wait_fetched(self):
        import time as _t
        t0 = _t.perf_counter()
        self.read_handle.wait()
        self.stats["nvme_wait_s"] += _t.perf_counter() - t0

    def writeback(self, i, master):
        """Persist leaf i's updated master + compute copy; marks the
        accumulator consumed."""
        self.write_handle.async_pwrite(master, self._p(i, "master"))
        self.stats["nvme_write_bytes"] += master.nbytes
        self._write_param_file(i, master)
        self._acc_valid[i] = False

    def read_master(self, i):
        buf = np.empty(self.sizes[i], np.float32)
        self.read_handle.async_pread(buf, self._p(i, "master"))
        self.read_handle.wait()
        return buf

    def write_master(self, i, master_f32_flat):
        np.ascontiguousarray(master_f32_flat, np.float32).tofile(
            self._p(i, "master"))
        self._write_param_file(i, master_f32_flat)

    def flush(self):
        self.write_handle.wait()

    def release(self, *bufs):
        self._untrack(*bufs)

    def pop_stats(self):
        out = dict(self.stats,
                   peak_buffer_bytes=self.peak_buffer_bytes)
        for k in self.stats:
            self.stats[k] = 0.0 if isinstance(self.stats[k], float) else 0
        self.peak_buffer_bytes = self._live_bytes
        return out


class HostOffloadOptimizer:
    """Flat-per-leaf host optimizer driving the ZeRO-Offload step."""

    def __init__(self, opt_name, opt_params, *, gradient_clipping=0.0,
                 fp16_cfg=None, fp16_enabled=False, offload_cfg=None,
                 aio_config=None, param_nvme_path=None, param_dtype="bf16"):
        p = dict(opt_params or {})
        name = (opt_name or "adamw").lower()
        self.opt = DeepSpeedCPUAdam(
            lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=name in ("adamw", "cpu_adamw"))
        self.clip = float(gradient_clipping or 0.0)
        self.scaler = HostLossScaler(fp16_cfg, fp16_enabled)
        self.device = getattr(offload_cfg, "device", "cpu")
        self.nvme_path = getattr(offload_cfg, "nvme_path", None)
        self.aio_config = aio_config
        # ZeRO-Infinity parameter tier (offload_param.device == "nvme"):
        # masters + accumulators + at-rest compute copies on NVMe
        self.param_tier = None
        self._param_nvme_path = param_nvme_path
        self._param_dtype = param_dtype
        self.master = None       # list of flat fp32 arrays
        self.names = None        # checkpoint leaf names, tree order
        self.moments = None      # list of (m, v) or None when on NVMe
        self.nvme = None
        self.acc = None          # fp32 grad accumulators
        self.step_count = 0
        self.skipped_steps = 0
        # per-phase wall-time accounting (bench instrumentation —
        # VERDICT r3 weak #2 demanded the breakdown): reset via
        # pop_phase_stats()
        self.phase = {"d2h_accum_s": 0.0, "host_adam_s": 0.0,
                      "h2d_emit_s": 0.0, "accum_calls": 0}

    # ------------------------------------------------------------- state
    def init_master(self, host_leaves, names=None):
        """host_leaves: list of numpy arrays (any float dtype) in tree
        order; copied into flat fp32 master buffers. ``names`` (optional)
        are the checkpoint leaf names in the same order — persisted with
        the state so consolidation pairs master buffers by name, never by
        enumeration order."""
        self.names = list(names) if names is not None else None
        if self._param_nvme_path:
            # parameter tier: ``host_leaves`` may be a GENERATOR (the
            # engine device_gets one leaf at a time) — each master is
            # persisted and freed before the next leaf lands, so init
            # RAM is one leaf, not the model
            self.param_tier = NvmeParamTier(self._param_nvme_path,
                                            self.aio_config,
                                            self._param_dtype)
            sizes, shapes = [], []
            for a in host_leaves:
                flat = _to_f32(a).reshape(-1)
                self.param_tier.add_leaf(flat, a.shape)
                sizes.append(flat.size)
                shapes.append(a.shape)
            self.master = None
            self.shapes = shapes
            logger.info(
                f"ZeRO-Infinity param tier: {len(sizes)} leaves "
                f"({sum(sizes) * 4 / 1e9:.2f} GB master + "
                f"{sum(sizes) * 4 / 1e9:.2f} GB accum + compute copies) "
                f"on NVMe at {self.param_tier.dir}")
        else:
            self.master, self.shapes = [], []
            for a in host_leaves:
                self.master.append(_to_f32(a).reshape(-1).copy())
                self.shapes.append(a.shape)
            sizes = [m.size for m in self.master]
        self.sizes = sizes
        if str(self.device) == "nvme" or self.param_tier is not None:
            path = self.nvme_path or self._param_nvme_path
            assert path, "offload_optimizer.nvme_path required"
            self.nvme = NvmeMomentStore(path, sizes, self.aio_config)
            logger.info(f"ZeRO-Infinity: {len(sizes)} moment pairs "
                        f"({2 * sum(sizes) * 4 / 1e9:.2f} GB) on NVMe at "
                        f"{self.nvme.dir}")
        else:
            self.moments = [self.opt.init_state(n) for n in sizes]

    def accumulate(self, host_grad_leaves):
        """Add one micro-batch's grads into the fp32 accumulators
        (reference async_accumulate_grad_in_cpu_via_gpu). A leaf is
        either a dense array or a row-sparse ``(indices, values)`` pair
        (the engine's sparse_gradients embedding path — reference
        SparseTensor + engine.py:2303): sparse pairs scatter-add into
        the accumulator, so only touched rows crossed the link."""
        if self.param_tier is not None:
            for i, g in enumerate(host_grad_leaves):
                self.param_tier.accumulate(i, g)
            self.acc = "nvme"      # sentinel: a window is pending
            return
        if self.acc is None:
            self.acc = [np.zeros(m.size, np.float32) for m in self.master]
        for a, g, shape in zip(self.acc, host_grad_leaves, self.shapes):
            if isinstance(g, tuple):
                idx, vals = g
                np.add.at(a.reshape(shape), np.asarray(idx),
                          _to_f32(np.asarray(vals)))
            else:
                axpy(a, _to_f32(g).reshape(-1))

    # -------------------------------------------------------------- step
    def step(self, lr, on_leaf=None):
        """Unscale+clip+Adam over all leaves; returns (leaves, metrics).
        Clears the accumulators.

        ``on_leaf(i, bf16_leaf) -> result`` (optional) is called right
        after each leaf's update, replacing that leaf in the returned
        list with its result — the engine passes an async device_put so
        the H2D of leaf i overlaps the host Adam of leaf i+1 (the
        reference overlaps its CPU step with copy streams,
        stage_1_and_2.py:1031)."""
        assert self.acc is not None, "no grads accumulated"
        if self.param_tier is not None:
            return self._step_param_tier(lr, on_leaf)
        scale = self.scaler.loss_scale
        overflow = any(has_inf_nan(a) for a in self.acc)
        self.scaler.update(overflow)
        gnorm_sq = sum(l2_norm_sq(a) for a in self.acc)
        gnorm = (gnorm_sq ** 0.5) / scale
        clip_coef = 1.0
        if self.clip > 0.0 and gnorm > self.clip:
            clip_coef = self.clip / (gnorm + 1e-6)

        import time as _time
        raw_emit = (lambda i, l: l) if on_leaf is None else on_leaf

        def emit(i, l):
            t0 = _time.perf_counter()
            out = raw_emit(i, l)
            self.phase["h2d_emit_s"] += _time.perf_counter() - t0
            return out

        _t_adam0 = _time.perf_counter()
        _emit0 = self.phase["h2d_emit_s"]
        leaves = []
        if overflow:
            self.skipped_steps += 1
            from deepspeed_tpu.ops.adam.cpu_adam import f32_to_bf16
            for i, (mstr, shape) in enumerate(zip(self.master, self.shapes)):
                leaves.append(emit(i, f32_to_bf16(mstr).reshape(shape)))
            self.acc = None
            return leaves, self._metrics(gnorm, overflow)

        self.step_count += 1
        n = len(self.master)
        pending_write = None
        if self.nvme is not None:
            next_bufs = self.nvme.prefetch(0)
        for i in range(n):
            if self.nvme is not None:
                self.nvme.fetch_wait()
                m, v = next_bufs
                if i + 1 < n:
                    next_bufs = self.nvme.prefetch(i + 1)
            else:
                m, v = self.moments[i]
            out = np.empty(self.master[i].size, np.uint16)
            self.opt.step_flat(self.master[i], m, v, self.acc[i], lr=lr,
                               grad_scale=scale, clip_coef=clip_coef,
                               step=self.step_count, bf16_out=out)
            leaves.append(emit(i, out.reshape(self.shapes[i])))
            if self.nvme is not None:
                if pending_write is not None:
                    # bound in-flight buffers to one leaf (double buffer)
                    self.nvme.flush()
                self.nvme.writeback(i, m, v)
                pending_write = i
        if self.nvme is not None:
            self.nvme.flush()
        self.acc = None
        self.phase["host_adam_s"] += (
            _time.perf_counter() - _t_adam0
            - (self.phase["h2d_emit_s"] - _emit0))
        return leaves, self._metrics(gnorm, overflow)

    def _step_param_tier(self, lr, on_leaf=None):
        """Optimizer sweep with EVERYTHING on NVMe: per leaf, the
        (master, accumulator) pair and the Adam moments stream in with
        prefetch-next-leaf double buffering, the host kernel updates,
        and master + moments + the compute copy stream back out. RAM
        holds at most two leaves' buffers (tracked in
        ``param_tier.peak_buffer_bytes``). ``on_leaf`` is ignored — the
        engine's next dispatch re-reads the updated compute copies via
        its memmap views, so nothing is emitted."""
        import time as _time
        scale = self.scaler.loss_scale
        gnorm_sq, overflow = self.param_tier.grad_stats()
        self.scaler.update(overflow)
        gnorm = (gnorm_sq ** 0.5) / scale
        clip_coef = 1.0
        if self.clip > 0.0 and gnorm > self.clip:
            clip_coef = self.clip / (gnorm + 1e-6)
        if overflow:
            self.skipped_steps += 1
            # accumulators are consumed (next window overwrites); files
            # unchanged, so the at-rest copies already hold the params
            self.param_tier._acc_valid = [False] * len(self.sizes)
            self.acc = None
            return [], self._metrics(gnorm, overflow)

        self.step_count += 1
        t0 = _time.perf_counter()
        n = len(self.sizes)
        tier = self.param_tier
        next_state = tier.prefetch(0)
        next_moments = self.nvme.prefetch(0)
        for i in range(n):
            tier.wait_fetched()
            self.nvme.fetch_wait()
            master, acc = next_state
            m, v = next_moments
            if i + 1 < n:
                next_state = tier.prefetch(i + 1)
                next_moments = self.nvme.prefetch(i + 1)
            self.opt.step_flat(master, m, v, acc, lr=lr,
                               grad_scale=scale, clip_coef=clip_coef,
                               step=self.step_count)
            tier.flush()            # bound in-flight writes (double buf)
            self.nvme.flush()
            tier.writeback(i, master)
            self.nvme.writeback(i, m, v)
            tier.release(master, acc)
        tier.flush()
        self.nvme.flush()
        self.acc = None
        self.phase["host_adam_s"] += _time.perf_counter() - t0
        return [], self._metrics(gnorm, overflow)

    def pop_phase_stats(self):
        """Per-phase wall times since the last call (the bench embeds
        these; engine adds the D2H/accumulate worker and join-stall
        numbers it measures on its side)."""
        out = dict(self.phase)
        for k in self.phase:
            self.phase[k] = 0.0 if isinstance(self.phase[k], float) else 0
        return out

    def _metrics(self, gnorm, overflow):
        return {"grad_norm": gnorm, "overflow": overflow,
                "loss_scale": self.scaler.loss_scale}

    # ------------------------------------------------------- checkpoint
    def iter_state_entries(self):
        """Stream the checkpoint entries one array at a time (the
        ZeRO-Infinity tier must never hold a model-sized dict: masters
        and moments read back from NVMe per leaf). Keys match
        state_dict()'s, so either form round-trips through
        load_state_dict."""
        yield "step_count", np.asarray(self.step_count)
        yield "skipped_steps", np.asarray(self.skipped_steps)
        yield "loss_scale", np.asarray(self.scaler.loss_scale)
        if self.names is not None:
            yield "leaf_names", np.array(self.names)
        for i in range(len(self.sizes)):
            yield f"master_{i}", (
                self.param_tier.read_master(i)
                if self.param_tier is not None else self.master[i])
            if self.moments is not None:
                m, v = self.moments[i]
            else:
                m, v = self.nvme.prefetch(i)
                self.nvme.fetch_wait()
            yield f"m_{i}", m
            yield f"v_{i}", v

    def state_dict(self):
        """Materialized form of :meth:`iter_state_entries` (tests and the
        RAM-mode snapshot path; the tier streams instead)."""
        return dict(self.iter_state_entries())

    def load_state_dict(self, d):
        def scalar(key):   # scalars round-trip as (1,) (npz writer)
            return np.asarray(d[key]).reshape(-1)[0]
        self.step_count = int(scalar("step_count"))
        self.skipped_steps = int(scalar("skipped_steps"))
        self.scaler.loss_scale = float(scalar("loss_scale"))
        # pair saved master_{j}/m_{j}/v_{j} entries with live leaves by
        # *name* when both sides recorded names; positional pairing would
        # silently swap optimizer state if the model's flatten order
        # changed between save and load
        n_leaves = len(self.sizes)
        index_of = {i: i for i in range(n_leaves)}
        if "leaf_names" in d and self.names is not None:
            saved = [str(s) for s in d["leaf_names"]]
            pos = {n: j for j, n in enumerate(saved)}
            missing = [n for n in self.names if n not in pos]
            if missing:
                raise KeyError(
                    f"offload state missing master entries for leaves "
                    f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
            index_of = {i: pos[n] for i, n in enumerate(self.names)}
        for i in range(n_leaves):
            j = index_of[i]
            if d[f"master_{j}"].size != self.sizes[i]:
                raise ValueError(
                    f"offload master_{j} has {d[f'master_{j}'].size} "
                    f"elements but live leaf {i} has {self.sizes[i]}")
            if self.param_tier is not None:
                # refreshes the at-rest compute copy too
                self.param_tier.write_master(
                    i, np.asarray(d[f"master_{j}"], np.float32))
            else:
                self.master[i][:] = d[f"master_{j}"]
            if self.moments is not None:
                self.moments[i][0][:] = d[f"m_{j}"]
                self.moments[i][1][:] = d[f"v_{j}"]
            else:
                self.nvme.writeback(i, np.ascontiguousarray(d[f"m_{j}"]),
                                    np.ascontiguousarray(d[f"v_{j}"]))
        if self.nvme is not None:
            self.nvme.flush()

    def bf16_master_leaves(self):
        from deepspeed_tpu.ops.adam.cpu_adam import f32_to_bf16
        if self.param_tier is not None:
            return [f32_to_bf16(self.param_tier.read_master(i)).reshape(s)
                    for i, s in enumerate(self.shapes)]
        return [f32_to_bf16(m).reshape(s)
                for m, s in zip(self.master, self.shapes)]
