"""ZeRO config (reference: ``deepspeed/runtime/zero/config.py`` and
``offload_config.py``).

On TPU the stages translate to sharding policy, not bookkeeping:
  stage 0 — params/grads/opt-state replicated over the data axis
  stage 1 — optimizer state sharded over the data axis
  stage 2 — + gradient (accumulator) sharded
  stage 3 — + parameters sharded (fsdp); XLA inserts the just-in-time
            all-gathers the reference does with module hooks
Offload configs select the host-RAM / disk paths (ZeRO-Offload/Infinity).
"""

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: int = Field(0, ge=0, le=3)

    # Bucketing / overlap knobs exist for config compatibility; XLA's
    # latency-hiding scheduler supersedes manual bucketing on TPU.
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    stage3_max_live_parameters: int = Field(1_000_000_000, ge=0)
    stage3_max_reuse_distance: int = Field(1_000_000_000, ge=0)
    stage3_prefetch_bucket_size: int = Field(50_000_000, ge=0)
    stage3_param_persistence_threshold: int = Field(100_000, ge=0)
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_gather_fp16_weights_on_model_save: bool = Field(
        False, json_schema_extra={"deprecated": True,
                                  "new_param": "stage3_gather_16bit_weights_on_model_save"})

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False

    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param",
                                 "new_param_fn": lambda x: DeepSpeedZeroOffloadParamConfig(device="cpu") if x else None})
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer",
                                 "new_param_fn": lambda x: DeepSpeedZeroOffloadOptimizerConfig(device="cpu") if x else None})

    @model_validator(mode="after")
    def _overlap_comm_default(self):
        if self.overlap_comm is None:
            # Reference defaults overlap_comm on for stage 3 only
            # (zero/config.py `overlap_comm_valid`); same here.
            object.__setattr__(self, "overlap_comm", self.stage == 3)
        return self
