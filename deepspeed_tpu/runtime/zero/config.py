"""ZeRO config (reference: ``deepspeed/runtime/zero/config.py`` and
``offload_config.py``).

On TPU the stages translate to sharding policy, not bookkeeping:
  stage 0 — params/grads/opt-state replicated over the data axis
  stage 1 — optimizer state sharded over the data axis
  stage 2 — + gradient (accumulator) sharded
  stage 3 — + parameters sharded (fsdp); XLA inserts the just-in-time
            all-gathers the reference does with module hooks
Offload configs select the host-RAM / disk paths (ZeRO-Offload/Infinity).
"""

from enum import Enum
from typing import ClassVar, Dict, Optional

from pydantic import Field, model_validator

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """ZeRO-3 parameter offload (reference offload_config.py). On TPU,
    `device: cpu` keeps the at-rest compute copy in pinned host memory,
    streamed to HBM inside the jitted step. `device: nvme` is the
    ZeRO-Infinity parameter tier (reference
    swap_tensor/partitioned_param_swapper.py): fp32 master, gradient
    accumulators AND the at-rest compute copy live in per-leaf NVMe
    files; dispatches stream params NVMe->HBM through the page cache and
    the optimizer sweep double-buffers leaf state through the aio
    handles — host RAM never holds a full model-sized buffer."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(100_000_000, ge=0)
    max_in_cpu: int = Field(1_000_000_000, ge=0)
    pin_memory: bool = False

    _inert_fields: ClassVar[Dict[str, str]] = {
        "buffer_count": "XLA schedules the host->HBM streams; no staging "
                        "buffer pool",
        "buffer_size": "XLA schedules the host->HBM streams; no staging "
                       "buffer pool",
        "max_in_cpu": "the full compute copy lives in host memory",
        "pin_memory": "the at-rest copy is always in pinned host memory",
    }


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    _inert_fields: ClassVar[Dict[str, str]] = {
        "buffer_count": "NVMe moment IO is double-buffered (2 in flight)",
        "pin_memory": "host buffers are plain numpy; the runtime DMAs "
                      "from pageable memory",
        "pipeline_read": "NVMe reads are always prefetched one leaf ahead",
        "pipeline_write": "NVMe write-back is always async",
        "fast_init": "master init is a device_get, already batched",
    }

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


_XLA_SCHED = ("XLA's latency-hiding scheduler decides gather/prefetch " \
              "lifetime and bucketing under jit")

class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    stage: int = Field(0, ge=0, le=3)

    _inert_fields: ClassVar[Dict[str, str]] = {
        "stage3_max_live_parameters": _XLA_SCHED,
        "stage3_max_reuse_distance": _XLA_SCHED,
        "stage3_prefetch_bucket_size": _XLA_SCHED,
        "reduce_bucket_size": _XLA_SCHED,
        "allgather_bucket_size": _XLA_SCHED,
        "contiguous_gradients": "gradients are laid out by XLA",
        "reduce_scatter": "grad partitioning is a sharding spec; XLA picks "
                          "the collective",
        "allgather_partitions": "param gathers are XLA-inserted",
        "overlap_comm": _XLA_SCHED,
        "legacy_stage1": "GPU-implementation detail",
        "round_robin_gradients": "GPU-implementation detail",
        "zero_hpz_partition_size": "ZeRO++ hierarchical partitioning is "
                                   "not implemented",
        "zero_quantized_weights": "ZeRO++ quantized weights are not "
                                  "implemented",
        "zero_quantized_gradients": "ZeRO++ quantized gradients are not "
                                    "implemented (1-bit optimizers cover "
                                    "compressed grad sync)",
        "sub_group_size": "no sub-group flat buffers; params stay "
                          "tree-structured",
        "cpu_offload_use_pin_memory": "host buffers are plain numpy",
        "ignore_unused_parameters": "jax autodiff produces zero grads for "
                                    "unused params",
        "elastic_checkpoint": "checkpoints are world-size-independent by "
                              "construction",
        "load_from_fp32_weights": "the fp32 master is always authoritative "
                                  "when present",
    }

    # Bucketing / overlap knobs exist for config compatibility; XLA's
    # latency-hiding scheduler supersedes manual bucketing on TPU.
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(500_000_000, ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(500_000_000, ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False

    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    stage3_max_live_parameters: int = Field(1_000_000_000, ge=0)
    stage3_max_reuse_distance: int = Field(1_000_000_000, ge=0)
    stage3_prefetch_bucket_size: int = Field(50_000_000, ge=0)
    stage3_param_persistence_threshold: int = Field(100_000, ge=0)
    stage3_gather_16bit_weights_on_model_save: bool = False
    stage3_gather_fp16_weights_on_model_save: bool = Field(
        False, json_schema_extra={"deprecated": True,
                                  "new_param": "stage3_gather_16bit_weights_on_model_save"})

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = Field(1, ge=0)
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False

    sub_group_size: int = Field(1_000_000_000, ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param",
                                 "new_param_fn": lambda x: DeepSpeedZeroOffloadParamConfig(device="cpu") if x else None})
    cpu_offload_use_pin_memory: Optional[bool] = None
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer",
                                 "new_param_fn": lambda x: DeepSpeedZeroOffloadOptimizerConfig(device="cpu") if x else None})

    @model_validator(mode="after")
    def _overlap_comm_default(self):
        if self.overlap_comm is None:
            # Reference defaults overlap_comm on for stage 3 only
            # (zero/config.py `overlap_comm_valid`); same here.
            object.__setattr__(self, "overlap_comm", self.stage == 3)
        return self
