"""Tiled linear layers (reference ``runtime/zero/tiling.py`` —
``TiledLinear`` splits a large linear into a grid of smaller linears so
ZeRO-3 can gather/release one tile at a time instead of the whole
matrix).

TPU form: a flax module computing the same function as Dense through an
[in_splits x out_splits] grid of tile kernels. Each tile is its own
param, so fsdp sharding (and any future per-tile gather policy) applies
tile-by-tile; output is mathematically identical to the monolithic
Dense with the concatenated kernel."""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def _splits(total, parts):
    base = total // parts
    rem = total % parts
    sizes = [base + (1 if i < rem else 0) for i in range(parts)]
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    return sizes, bounds


class TiledLinear(nn.Module):
    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        in_dim = x.shape[-1]
        in_sizes, in_bounds = _splits(in_dim, self.in_splits)
        out_sizes, out_bounds = _splits(self.features, self.out_splits)

        outs = []
        for j, out_n in enumerate(out_sizes):
            acc = None
            for i, in_n in enumerate(in_sizes):
                kernel = self.param(
                    f"tile_{i}_{j}", nn.initializers.lecun_normal(),
                    (in_n, out_n))
                xi = x[..., in_bounds[i]:in_bounds[i + 1]]
                part = xi @ kernel
                acc = part if acc is None else acc + part
            outs.append(acc)
        y = jnp.concatenate(outs, axis=-1)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros_init(),
                               (self.features,))
        return y
