from deepspeed_tpu.runtime.comm.compressed import (  # noqa: F401
    compressed_allreduce, onebit_quantize)
