"""Error-compensated 1-bit compressed allreduce.

Reference: ``deepspeed/runtime/comm/nccl.py:15`` (``NcclBackend
.compressed_allreduce``) / ``mpi.py`` — the comm backend behind the
1-bit Adam/LAMB optimizers: tensors are reduced as sign bits + one scale,
with per-worker and per-server error feedback carrying the quantization
residual into the next step.

TPU shape: the same two-phase exchange over a mesh axis inside
``shard_map`` —
  1. worker: add worker error, take the sign (packed 8/bit-byte via
     ``jnp.packbits``) and one fp32 scale; ``all_to_all`` ships each
     worker its chunk of everyone's signs (1/32 the bytes of fp32
     grads, plus n scales);
  2. server (= every worker, for its chunk): decode, average, compress
     again with server error feedback; ``all_gather`` the re-compressed
     chunk back.

On a single-axis mesh XLA would emit a bandwidth-optimal fp32 allreduce
anyway; this op is for DCN-connected multi-slice topologies (the
reference's Ethernet story — BASELINE.md 1-bit row: up to 5x comm
reduction) and for algorithm parity of the 1-bit optimizers.
"""

import jax
import jax.numpy as jnp
from jax import lax


def onebit_quantize(x, error):
    """x + error -> (signs bool, scale, new_error); scale preserves the
    l2 norm (reference's ||c|| / sqrt(n) server scale)."""
    c = x + error
    n = c.size
    scale = jnp.linalg.norm(c.ravel()) / jnp.sqrt(float(n))
    q = jnp.where(c >= 0, scale, -scale)
    return c >= 0, scale, c - q


def _decode(signs, scale):
    return jnp.where(signs, scale, -scale)


def compressed_allreduce(x, worker_error, server_error, axis_name):
    """1-bit averaged allreduce of `x` over `axis_name` (call under
    shard_map). Returns (avg [same shape], new_worker_error,
    new_server_error). Padding to n*8 elements is internal."""
    n = lax.psum(1, axis_name)
    shape = x.shape
    flat = x.ravel()
    size = flat.size
    pad = (-size) % (n * 8)
    flat = jnp.pad(flat, (0, pad))
    we = jnp.pad(worker_error.ravel(), (0, pad)) \
        if worker_error.size == size else worker_error

    signs, scale, new_we = onebit_quantize(flat, we)
    chunk = flat.size // n
    packed = jnp.packbits(signs.reshape(n, chunk), axis=1)   # [n, chunk/8]

    # phase 1: chunk i of every worker lands on worker i
    recv = lax.all_to_all(packed, axis_name, 0, 0, tiled=False)  # [n, c/8]
    scales = lax.all_gather(scale, axis_name)                    # [n]
    decoded = _decode(jnp.unpackbits(recv, axis=1).astype(bool),
                      scales[:, None])                           # [n, chunk]
    avg = decoded.mean(axis=0)                                   # [chunk]

    # phase 2: server-side recompress + gather back
    se = server_error.ravel()
    se = jnp.pad(se, (0, avg.size - se.size)) if se.size != avg.size else se
    s_signs, s_scale, new_se = onebit_quantize(avg, se)
    packed2 = jnp.packbits(s_signs)
    out_packed = lax.all_gather(packed2, axis_name)              # [n, c/8]
    out_scales = lax.all_gather(s_scale, axis_name)              # [n]
    out = _decode(jnp.unpackbits(out_packed, axis=1).astype(bool),
                  out_scales[:, None]).reshape(-1)
    out = out[:size].reshape(shape)
    return out, new_we[:size].reshape(shape), new_se
