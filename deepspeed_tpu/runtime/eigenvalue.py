"""Hessian max-eigenvalue estimation by power iteration (reference
``runtime/eigenvalue.py`` — used to schedule MoQ quantization by layer
curvature). The torch version power-iterates with autograd v-products;
jax makes the Hessian-vector product a one-liner (jvp over grad), so the
whole estimator jits."""

import jax
import jax.numpy as jnp


class Eigenvalue:
    def __init__(self, verbose=False, max_iter=100, tol=1e-2,
                 stability=1e-6, gas_boundary_resolution=1):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose
        self.gas_boundary_resolution = gas_boundary_resolution
        # jitted power-step per loss_fn identity: traced args are
        # (fparams, extra_args, v, mask), so repeated calls — every
        # group, every gas boundary — reuse ONE compiled program as long
        # as the caller passes the same loss_fn object and shapes
        self._step_cache = {}

    def compute_eigenvalue(self, loss_fn, params, rng=None, mask=None,
                           extra_args=()):
        """Largest |eigenvalue| of d2 loss / d params2 by power iteration.
        ``loss_fn(params, *extra_args) -> scalar``. Returns
        (eigenvalue, eigenvector).

        ``mask`` (pytree of 0/1 like params) restricts the iteration to
        a parameter subspace — the per-BLOCK eigenvalues MoQ schedules
        bits with (reference eigenvalue.py:73 iterates per layer
        module; here the projection PHP of the Hessian onto the block's
        coordinates is powered directly).

        Non-floating leaves (counters, index tables) are frozen: the
        iteration runs over the float leaves only — integer primals
        admit no float tangents.

        Pass a STABLE ``loss_fn`` object (same identity across calls)
        with the changing data in ``extra_args``: the jitted power step
        is cached per loss_fn, so every group at every gas boundary
        reuses one compiled HVP program. Rayleigh quotient and norms
        reduce in float32 regardless of the param dtype (bf16 noise is
        the same order as the default tol)."""
        flat, treedef = jax.tree_util.tree_flatten(params)
        is_f = tuple(jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                     for l in flat)
        fpos = [i for i, f in enumerate(is_f) if f]
        fparams = [flat[i] for i in fpos]
        frozen = [flat[i] for i in range(len(flat)) if not is_f[i]]
        mask_f = None if mask is None else \
            [jax.tree_util.tree_flatten(mask)[0][i] for i in fpos]

        key = (id(loss_fn), treedef, is_f, mask is None)
        cached = self._step_cache.get(key)
        # the cache holds a strong reference to loss_fn: a dead object's
        # id could otherwise be reused by a different function
        power_step = cached[1] if cached is not None else None
        if power_step is None:
            stability = self.stability

            def make_merge():
                def merge(fl, fr):
                    it, rt = iter(fl), iter(fr)
                    leaves = [next(it) if f else next(rt) for f in is_f]
                    return jax.tree_util.tree_unflatten(treedef, leaves)
                return merge

            merge = make_merge()

            @jax.jit
            def power_step(fparams, frozen, v, mask_f, extra):
                grad_fn = jax.grad(
                    lambda fl: loss_fn(merge(fl, frozen), *extra))
                hv = jax.jvp(grad_fn, (fparams,), (v,))[1]
                if mask_f is not None:
                    # cast the mask product back: the next iteration's
                    # tangent dtype must match the primal's
                    hv = [(x * m).astype(x.dtype)
                          for x, m in zip(hv, mask_f)]
                f32 = [x.astype(jnp.float32) for x in hv]
                eig = sum(jnp.vdot(a.astype(jnp.float32), b).real
                          for a, b in zip(v, f32))
                hn = jnp.sqrt(sum(jnp.vdot(l, l) for l in f32)).real
                return [(x / (hn.astype(x.dtype) + stability))
                        for x in hv], eig

            self._step_cache[key] = (loss_fn, power_step)

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        keys = jax.random.split(rng, max(len(fpos), 1))
        v = [jax.random.normal(k, l.shape, jnp.asarray(l).dtype)
             for k, l in zip(keys, fparams)]
        if mask_f is not None:
            v = [(x * m).astype(x.dtype) for x, m in zip(v, mask_f)]
        n = float(jnp.sqrt(sum(
            jnp.vdot(l.astype(jnp.float32), l.astype(jnp.float32))
            for l in v)).real)
        v = [x / (n + self.stability) for x in v]

        eig = 0.0
        for _ in range(self.max_iter):
            v, new_eig = power_step(fparams, frozen, v, mask_f,
                                    tuple(extra_args))
            new_eig = float(new_eig)
            if abs(new_eig - eig) < self.tol * max(abs(new_eig), 1e-12):
                eig = new_eig
                break
            eig = new_eig
        # rebuild a full-tree eigenvector (zeros on frozen leaves)
        full = [jnp.zeros_like(l) for l in flat]
        for i, x in zip(fpos, v):
            full[i] = x
        vec = jax.tree_util.tree_unflatten(treedef, full)
        return float(eig), vec

    @staticmethod
    def normalize_eigenvalues(values):
        """|ev| / max|ev| with zeros mapped to 1.0 (reference
        eigenvalue.py:149 post_process)."""
        mx = max((abs(v) for v in values), default=0.0)
        if mx == 0.0:
            return [1.0 for _ in values]
        return [abs(v) / mx if v != 0.0 else 1.0 for v in values]
