"""Hessian max-eigenvalue estimation by power iteration (reference
``runtime/eigenvalue.py`` — used to schedule MoQ quantization by layer
curvature). The torch version power-iterates with autograd v-products;
jax makes the Hessian-vector product a one-liner (jvp over grad), so the
whole estimator jits."""

import jax
import jax.numpy as jnp


class Eigenvalue:
    def __init__(self, verbose=False, max_iter=100, tol=1e-2,
                 stability=1e-6, gas_boundary_resolution=1):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose
        self.gas_boundary_resolution = gas_boundary_resolution

    def compute_eigenvalue(self, loss_fn, params, rng=None):
        """Largest |eigenvalue| of d2 loss / d params2 by power iteration.
        loss_fn: params -> scalar. Returns (eigenvalue, eigenvector)."""
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        def norm(t):
            return jnp.sqrt(sum(jnp.vdot(l, l)
                                for l in jax.tree.leaves(t))).real

        rng = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])
        n = norm(v)
        v = jax.tree.map(lambda x: x / (n + self.stability), v)

        eig = jnp.float32(0.0)
        for _ in range(self.max_iter):
            hv = hvp(v)
            new_eig = sum(jnp.vdot(a, b).real for a, b in zip(
                jax.tree.leaves(v), jax.tree.leaves(hv)))
            hn = norm(hv)
            v = jax.tree.map(lambda x: x / (hn + self.stability), hv)
            if abs(float(new_eig) - float(eig)) < self.tol * max(
                    abs(float(new_eig)), 1e-12):
                eig = new_eig
                break
            eig = new_eig
        return float(eig), v
