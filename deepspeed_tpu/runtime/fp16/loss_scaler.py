"""Loss scaling for fp16 training.

Reference: ``deepspeed/runtime/fp16/loss_scaler.py`` (264 LoC) —
``LossScaler`` (static) and ``DynamicLossScaler`` (grow/backoff on overflow
with hysteresis). Here the scaler state is a pytree that lives **inside the
jitted train step** so the overflow check and skip-step decision happen on
device with no host sync (SURVEY.md §7 "hard parts" #4).
"""

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class LossScaleState:
    loss_scale: jnp.ndarray        # f32 scalar
    good_steps: jnp.ndarray        # i32 scalar: consecutive non-overflow steps
    hysteresis: jnp.ndarray        # i32 scalar: remaining tolerated overflows
    # static config
    scale_window: int = flax.struct.field(pytree_node=False, default=1000)
    min_scale: float = flax.struct.field(pytree_node=False, default=1.0)
    scale_factor: float = flax.struct.field(pytree_node=False, default=2.0)
    init_hysteresis: int = flax.struct.field(pytree_node=False, default=2)
    dynamic: bool = flax.struct.field(pytree_node=False, default=True)
    # reference loss_scaler.py:191-196: with consecutive_hysteresis=False
    # (the reference default) hysteresis only replenishes at scale-window
    # growth; True replenishes on every non-overflow step.
    consecutive_hysteresis: bool = flax.struct.field(pytree_node=False,
                                                     default=False)


def make_loss_scale_state(fp16_config=None, enabled=True):
    """Build scaler state from an Fp16Config; disabled/bf16 -> unit scale."""
    if fp16_config is None or not enabled:
        return LossScaleState(loss_scale=jnp.float32(1.0),
                              good_steps=jnp.int32(0),
                              hysteresis=jnp.int32(1),
                              dynamic=False)
    return LossScaleState(
        loss_scale=jnp.float32(fp16_config.initial_dynamic_scale),
        good_steps=jnp.int32(0),
        hysteresis=jnp.int32(fp16_config.hysteresis),
        scale_window=fp16_config.loss_scale_window,
        min_scale=fp16_config.min_loss_scale,
        init_hysteresis=fp16_config.hysteresis,
        dynamic=fp16_config.dynamic_loss_scale,
        consecutive_hysteresis=getattr(fp16_config, "consecutive_hysteresis",
                                       False))


def has_overflow(grads):
    """True if any grad entry is non-finite (reference ``CheckOverflow``,
    runtime/utils.py:173). Works on sharded global arrays under jit: the
    reduction is global automatically."""
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.bool_(False)
    finite = [jnp.isfinite(g).all() for g in leaves]
    return ~jnp.stack(finite).all()


def update_scale(state: LossScaleState, overflow):
    """One reference `update_scale` step, traced (lax.cond-free, pure where)."""
    if not state.dynamic:
        return state
    # overflow path
    hysteresis_left = state.hysteresis - 1
    exhausted = hysteresis_left <= 0
    dec_scale = jnp.maximum(state.loss_scale / state.scale_factor,
                            state.min_scale)
    new_scale_ovf = jnp.where(exhausted, dec_scale, state.loss_scale)
    new_hyst_ovf = jnp.where(exhausted, jnp.int32(state.init_hysteresis),
                             hysteresis_left)
    # success path
    grown = (state.good_steps + 1) % state.scale_window == 0
    new_scale_ok = jnp.where(grown, state.loss_scale * state.scale_factor,
                             state.loss_scale)
    new_good_ok = jnp.where(grown, jnp.int32(0), state.good_steps + 1)
    if state.consecutive_hysteresis:
        new_hyst_ok = jnp.int32(state.init_hysteresis)
    else:
        # replenish only when the scale grows (reference :191-196)
        new_hyst_ok = jnp.where(grown, jnp.int32(state.init_hysteresis),
                                state.hysteresis)

    return state.replace(
        loss_scale=jnp.where(overflow, new_scale_ovf, new_scale_ok),
        good_steps=jnp.where(overflow, jnp.int32(0), new_good_ok),
        hysteresis=jnp.where(overflow, new_hyst_ovf, new_hyst_ok))


class DynamicLossScaler:
    """Host-side convenience wrapper keeping the reference class surface."""

    def __init__(self, init_scale=2**16, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=2, consecutive_hysteresis=False):
        self.state = LossScaleState(
            loss_scale=jnp.float32(init_scale), good_steps=jnp.int32(0),
            hysteresis=jnp.int32(delayed_shift), scale_window=scale_window,
            min_scale=min_scale, scale_factor=scale_factor,
            init_hysteresis=delayed_shift,
            consecutive_hysteresis=consecutive_hysteresis)

    @property
    def loss_scale(self):
        return float(self.state.loss_scale)

    def update_scale(self, overflow):
        self.state = update_scale(self.state, jnp.bool_(overflow))

    def backward(self, loss):
        return loss * self.state.loss_scale


class LossScaler(DynamicLossScaler):
    """Static loss scaler (reference ``LossScaler``)."""

    def __init__(self, scale=1.0):
        super().__init__(init_scale=scale)
        self.state = self.state.replace(dynamic=False)
