from deepspeed_tpu.runtime.fp16.onebit.adam import onebit_adam  # noqa: F401
from deepspeed_tpu.runtime.fp16.onebit.lamb import onebit_lamb  # noqa: F401
from deepspeed_tpu.runtime.fp16.onebit.zoadam import zero_one_adam  # noqa: F401
