"""1-bit Adam (reference ``runtime/fp16/onebit/adam.py:13``).

Algorithm (Tang et al.): a warmup phase runs plain Adam while the
variance estimate stabilizes; after ``freeze_step`` the variance is
FROZEN and only the momentum is communicated — compressed to one bit per
element with error feedback. Here as an optax transformation:

  * warmup (step < freeze_step): standard Adam m/v updates;
  * post-warmup: ``m = b1*m + (1-b1)*g``; the update uses the 1-bit
    quantized momentum (sign * l2-preserving scale) with the
    quantization residual carried in an error buffer; ``v`` stays
    frozen (the reference's compressed momentum exchange).

On TPU meshes the gradient all-reduce is emitted by XLA from shardings,
so the quantization here provides the *algorithm* (frozen variance +
error-compensated 1-bit momentum); the explicit compressed collective
for DCN-scale bandwidth savings is ``runtime/comm/compressed.py``.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime.comm.compressed import onebit_quantize


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates
    nu: optax.Updates
    error: optax.Updates


def onebit_adam(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, freeze_step=100):
    """optax transformation implementing 1-bit Adam."""

    def init(params):
        z = lambda: jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                                 params)
        return OnebitAdamState(count=jnp.zeros((), jnp.int32),
                               mu=z(), nu=z(), error=z())

    def update(grads, state, params=None):
        count = state.count + 1
        frozen = count > freeze_step
        mu = jax.tree.map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, grads)
        # warmup keeps updating v; post-freeze keeps the old v
        nu = jax.tree.map(
            lambda v, g: jnp.where(
                frozen, v, b2 * v + (1 - b2) *
                jnp.square(g.astype(jnp.float32))),
            state.nu, grads)

        # two passes (not one tree of pairs: tuple-containing param
        # pytrees would make pair-vs-container ambiguous)
        def q_value(m, e):
            signs, scale, _ = onebit_quantize(m, e)
            return jnp.where(frozen, jnp.where(signs, scale, -scale), m)

        def q_error(m, e):
            _, _, new_e = onebit_quantize(m, e)
            return jnp.where(frozen, new_e, e)

        m_used = jax.tree.map(q_value, mu, state.error)
        error = jax.tree.map(q_error, mu, state.error)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count,
                                    freeze_step).astype(jnp.float32)
        def step(m, v, p):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return (-learning_rate * upd).astype(p.dtype)

        updates = jax.tree.map(step, m_used, nu,
                               params if params is not None else mu)
        return updates, OnebitAdamState(count, mu, nu, error)

    return optax.GradientTransformation(init, update)
