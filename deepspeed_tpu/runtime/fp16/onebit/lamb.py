"""1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py:14``): the 1-bit
Adam scheme with LAMB's layerwise trust-ratio scaling of the update."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime.fp16.onebit.adam import onebit_adam


class OnebitLambState(NamedTuple):
    inner: object


def onebit_lamb(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, freeze_step=100, max_coeff=10.0,
                min_coeff=0.01):
    inner = onebit_adam(1.0, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay, freeze_step=freeze_step)

    def init(params):
        return OnebitLambState(inner=inner.init(params))

    def update(grads, state, params=None):
        raw, inner_state = inner.update(grads, state.inner, params)

        def trust(u, p):
            pn = jnp.linalg.norm(p.astype(jnp.float32).ravel())
            un = jnp.linalg.norm(u.astype(jnp.float32).ravel())
            ratio = jnp.where(un > 0, pn / jnp.maximum(un, 1e-12), 1.0)
            ratio = jnp.clip(jnp.where(pn > 0, ratio, 1.0),
                             min_coeff, max_coeff)
            return (learning_rate * ratio * u.astype(jnp.float32)) \
                .astype(p.dtype)

        updates = jax.tree.map(trust, raw,
                               params if params is not None else raw)
        return updates, OnebitLambState(inner=inner_state)

    return optax.GradientTransformation(init, update)
