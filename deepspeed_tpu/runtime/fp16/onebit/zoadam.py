"""0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py:13``, Lu et al.).

0/1 Adam extends 1-bit Adam with *adaptive* state freezing: instead of
one warmup/frozen split, the variance is refreshed only at
exponentially-spaced steps (interval multiplied by ``var_update_scaler``
each refresh) until ``var_freeze_step``, after which it is frozen for
good; the momentum is exchanged 1-bit-compressed with error feedback
throughout (the "1" bit), and on non-refresh steps the reference also
skips synchronization entirely for ``local_step_*`` intervals (the "0"
bit — workers take local steps and periodically average parameters).

Here as an optax transformation: the variance-refresh schedule and the
error-compensated 1-bit momentum are implemented exactly; the local-step
parameter averaging is subsumed by the engine's gradient sync (XLA psum
or the compressed collective), so ``local_step_scaler``/``clipper`` are
accepted for config parity and noted as inert by the optimizer factory.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from deepspeed_tpu.runtime.comm.compressed import onebit_quantize


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray        # i32 steps taken
    mu: optax.Updates         # momentum (fp32)
    nu: optax.Updates         # variance (fp32), refresh-gated
    error: optax.Updates      # 1-bit quantization residual
    next_refresh: jnp.ndarray  # i32 step of the next variance refresh
    interval: jnp.ndarray     # i32 current refresh interval


def zero_one_adam(learning_rate=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                  weight_decay=0.0, var_freeze_step=100000,
                  var_update_scaler=16, cuda_aware=False):
    """optax transformation implementing 0/1 Adam's variance schedule +
    error-compensated 1-bit momentum."""
    del cuda_aware  # GPU-transport flag; no meaning on TPU

    def init(params):
        z = lambda: jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                                 params)
        return ZeroOneAdamState(
            count=jnp.zeros((), jnp.int32), mu=z(), nu=z(), error=z(),
            next_refresh=jnp.ones((), jnp.int32),
            interval=jnp.ones((), jnp.int32))

    def update(grads, state, params=None):
        count = state.count + 1
        # refresh the variance only when the schedule says so, and never
        # after var_freeze_step (reference zoadam var update policy)
        refresh = jnp.logical_and(count >= state.next_refresh,
                                  count <= var_freeze_step)
        mu = jax.tree.map(
            lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32),
            state.mu, grads)
        nu = jax.tree.map(
            lambda v, g: jnp.where(
                refresh, b2 * v + (1 - b2) *
                jnp.square(g.astype(jnp.float32)), v),
            state.nu, grads)
        interval = jnp.where(refresh, state.interval * var_update_scaler,
                             state.interval)
        next_refresh = jnp.where(refresh, count + interval,
                                 state.next_refresh)

        # 1-bit error-compensated momentum (two passes; see onebit/adam.py
        # for why values and errors are mapped separately)
        def q_value(m, e):
            signs, scale, _ = onebit_quantize(m, e)
            return jnp.where(signs, scale, -scale)

        def q_error(m, e):
            _, _, new_e = onebit_quantize(m, e)
            return new_e

        m_used = jax.tree.map(q_value, mu, state.error)
        error = jax.tree.map(q_error, mu, state.error)

        bc1 = 1 - b1 ** count.astype(jnp.float32)
        # bias correction follows the number of variance refreshes the
        # reference tracks; freezing means bc2 saturates
        bc2 = 1 - b2 ** jnp.minimum(
            count, var_freeze_step).astype(jnp.float32)

        def step(m, v, p):
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                upd = upd + weight_decay * p.astype(jnp.float32)
            return (-learning_rate * upd).astype(p.dtype)

        updates = jax.tree.map(step, m_used, nu,
                               params if params is not None else mu)
        return updates, ZeroOneAdamState(count, mu, nu, error,
                                         next_refresh, interval)

    return optax.GradientTransformation(init, update)
