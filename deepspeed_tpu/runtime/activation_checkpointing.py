"""Activation checkpointing (reference
``runtime/activation_checkpointing/checkpointing.py`` — Megatron-style
``CheckpointFunction`` :474 with ``partition_activations`` :366 and CPU
checkpointing :461).

TPU mapping:
  * recompute-instead-of-save is ``jax.checkpoint`` (remat) — models
    apply it per block (``GPTConfig.remat``), and the engine can wrap
    the whole loss with a named policy (``remat_policy``).
  * ``cpu_checkpointing`` — saved residuals live in PINNED HOST memory
    between forward and backward (``offload_dot_with_no_batch_dims`` /
    ``save_and_offload_only_these_names``): the reference's
    checkpoint-to-CPU for long sequences, expressed as a remat policy
    so XLA schedules the transfers.
  * ``partition_activations`` is subsumed: under SPMD the saved
    residuals carry the program's shardings (batch/sequence-sharded by
    construction); there is no replicated per-TP-rank activation copy
    to slice up. The key is accepted and marked inert.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import logger

_POLICIES = {
    name: getattr(jax.checkpoint_policies, name)
    for name in ("everything_saveable", "nothing_saveable",
                 "dots_saveable", "checkpoint_dots",
                 "dots_with_no_batch_dims_saveable",
                 "checkpoint_dots_with_no_batch_dims")
    if hasattr(jax.checkpoint_policies, name)
}


def _offload_policy_usable(mesh):
    """True when this backend executes host-offloaded remat residuals
    under SPMD. The CPU SPMD partitioner rejects placement annotations
    it cannot shard ("side-effect HLO must have sharding") in programs
    richer than any cheap probe, so multi-device non-TPU meshes are
    excluded outright; the probe covers the rest."""
    if mesh is not None and mesh.devices.size > 1 and \
            jax.default_backend() != "tpu":
        return False
    try:
        pol = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")

        def f(x, w):
            g = jax.checkpoint(
                lambda a, b: jnp.sum(jnp.tanh(a @ b)), policy=pol)
            return jax.grad(g)(x, w)

        n = mesh.shape.get("data", 1) if mesh is not None else 1
        x = jnp.ones((max(n, 1) * 2, 4))
        w = jnp.ones((4, 4))
        if mesh is not None:
            x = jax.device_put(x, NamedSharding(mesh, P("data")))
            w = jax.device_put(w, NamedSharding(mesh, P()))
        jax.block_until_ready(jax.jit(f)(x, w))
        return True
    except Exception:
        return False


def resolve_policy(cfg, mesh=None):
    """jax.checkpoint policy (or None = no wrapping) for an
    ActivationCheckpointingConfig."""
    if cfg.cpu_checkpointing:
        # keep matmul outputs, but in host memory: the long-sequence
        # activation footprint leaves HBM between fwd and bwd
        if cfg.remat_policy:
            logger.warning("cpu_checkpointing overrides remat_policy="
                           f"{cfg.remat_policy!r}")
        if _offload_policy_usable(mesh):
            return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                "device", "pinned_host")
        logger.warning(
            "cpu_checkpointing: backend rejects host-offloaded remat "
            "residuals under SPMD; saving dot products in device memory "
            "instead (dots_with_no_batch_dims_saveable)")
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy:
        if cfg.remat_policy not in _POLICIES:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r}; choose from "
                f"{sorted(_POLICIES)} (or enable cpu_checkpointing)")
        return _POLICIES[cfg.remat_policy]
    return None


def wrap_loss_fn(loss_fn, cfg, mesh=None):
    """Wrap a ``loss_fn(params, batch, rng, **kw)`` with jax.checkpoint
    per the config section; returns loss_fn unchanged when the section
    requests nothing. Extra kwargs (e.g. the engine's ``pld_theta``)
    pass through as traced positionals via closure conversion."""
    policy = resolve_policy(cfg, mesh)
    if policy is None:
        return loss_fn
    inner = jax.checkpoint(
        lambda params, batch, rng, **kw: loss_fn(params, batch, rng, **kw),
        policy=policy, prevent_cse=False)
    inner.__wrapped_by_activation_checkpointing__ = True
    return inner
