"""NVMe tensor swapping (ZeRO-Infinity disk tier).

Reference: ``deepspeed/runtime/swap_tensor/`` — ``partitioned_param_swapper``,
``optimizer_utils``, ``aio_config``. The TPU-native build keeps the swap
machinery small: :class:`~deepspeed_tpu.runtime.zero.offload.NvmeMomentStore`
streams optimizer moments through the C++ aio handle
(``csrc/aio.cpp`` via ``deepspeed_tpu.ops.aio.AioHandle``) with
double-buffered prefetch/writeback, and the host optimizer consumes them
leaf by leaf (runtime/zero/offload.py).
"""

from deepspeed_tpu.ops.aio import AioHandle  # noqa: F401
from deepspeed_tpu.runtime.zero.offload import NvmeMomentStore  # noqa: F401
