"""Learning-rate schedules.

Reference: ``deepspeed/runtime/lr_schedules.py`` — ``LRRangeTest`` (:258),
``OneCycle`` (:361), ``WarmupLR`` (:626), ``WarmupDecayLR``. Here each
schedule is a pure ``step -> lr`` function (jit-friendly, drives
``optax.inject_hyperparams``), wrapped in a small class that keeps the
reference's ``step()/get_lr()/state_dict()/load_state_dict()`` surface.
"""

import math

VALID_LR_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR",
                     "WarmupCosineLR"]


def warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000,
              warmup_type="log"):
    """WarmupLR: ramp from min to max then hold (reference :626)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        if step >= warmup_num_steps:
            return warmup_max_lr
        if warmup_type == "log":
            gamma = math.log(step + 1) / math.log(warmup_num_steps)
        else:
            gamma = (step + 1) / warmup_num_steps
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * min(1.0, gamma)

    return schedule


def warmup_decay_lr(total_num_steps, warmup_min_lr=0.0, warmup_max_lr=0.001,
                    warmup_num_steps=1000, warmup_type="log"):
    """WarmupDecayLR: warmup then linear decay to zero."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        if step < warmup_num_steps:
            return base(step)
        frac = (total_num_steps - step) / max(1, total_num_steps - warmup_num_steps)
        return warmup_max_lr * max(0.0, frac)

    return schedule


def warmup_cosine_lr(total_num_steps, warmup_min_ratio=0.0, warmup_num_steps=1000,
                     cos_min_ratio=0.0001, warmup_max_lr=0.001):
    def schedule(step):
        if step < warmup_num_steps:
            ratio = warmup_min_ratio + (1 - warmup_min_ratio) * (step / max(1, warmup_num_steps))
            return warmup_max_lr * ratio
        progress = (step - warmup_num_steps) / max(1, total_num_steps - warmup_num_steps)
        progress = min(1.0, progress)
        cos = 0.5 * (1 + math.cos(math.pi * progress))
        return warmup_max_lr * (cos_min_ratio + (1 - cos_min_ratio) * cos)

    return schedule


def lr_range_test(lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                  lr_range_test_step_rate=1.0, lr_range_test_staircase=False):
    """LRRangeTest (reference :258): lr grows (continuously or staircase)."""

    def schedule(step):
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = math.floor(interval)
        return lr_range_test_min_lr * (1 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr, cycle_max_lr, cycle_first_step_size=2000,
              cycle_second_step_size=None, cycle_first_stair_count=0,
              cycle_second_stair_count=None, decay_step_size=0,
              decay_lr_rate=0.0, **_unused):
    """OneCycle (reference :361), momentum cycling handled by optimizer betas
    being static on TPU (momentum cycle is a rarely-used extra)."""
    second = cycle_second_step_size if cycle_second_step_size is not None \
        else cycle_first_step_size

    def schedule(step):
        if step <= cycle_first_step_size:
            frac = step / cycle_first_step_size
            return cycle_min_lr + (cycle_max_lr - cycle_min_lr) * frac
        cycle_end = cycle_first_step_size + second
        if step <= cycle_end:
            frac = (step - cycle_first_step_size) / second
            return cycle_max_lr - (cycle_max_lr - cycle_min_lr) * frac
        # decay phase
        if decay_step_size > 0:
            decay_intervals = (step - cycle_end) / decay_step_size
            return max(0.0, cycle_min_lr * (1 - decay_lr_rate) ** decay_intervals)
        return cycle_min_lr

    return schedule


SCHEDULE_BUILDERS = {
    "WarmupLR": warmup_lr,
    "WarmupDecayLR": warmup_decay_lr,
    "WarmupCosineLR": warmup_cosine_lr,
    "LRRangeTest": lr_range_test,
    "OneCycle": one_cycle,
}


def get_lr_schedule(name, params):
    if name not in SCHEDULE_BUILDERS:
        raise ValueError(f"Unknown LR schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_BUILDERS[name](**params)


class LRScheduler:
    """Stateful wrapper with the torch-style interface the reference exposes."""

    def __init__(self, schedule_fn):
        self.schedule_fn = schedule_fn
        self.last_step = 0

    def step(self, increment=1):
        self.last_step += increment

    def get_lr(self):
        return [self.schedule_fn(self.last_step)]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_step": self.last_step}

    def load_state_dict(self, sd):
        self.last_step = sd["last_step"]
