"""Sparse gradient representation (reference ``runtime/sparse_tensor.py``
— SparseTensor wrapping index/value pairs for sparse embedding-grad
allreduce, engine ``sparse_allreduce_bucket`` engine.py:2312).

On TPU, embedding grads come out of autodiff dense (scatter-add), but
row-sparse exchange still pays when the touched-vocab fraction is small
and the reduction crosses DCN. The class keeps the reference's surface
(to_coo_tensor/to_dense, add) over jax arrays."""

import jax.numpy as jnp


class SparseTensor:
    """Row-sparse [rows, dim] tensor as (indices [nnz], values [nnz, dim])."""

    def __init__(self, indices, values, dense_shape):
        self.indices = indices
        self.values = values
        self.dense_size = tuple(dense_shape)

    @classmethod
    def from_dense(cls, dense, max_rows=None):
        """Keep the top `max_rows` rows by l2 norm (a static nnz so the
        result shape is jit-stable; defaults to all rows)."""
        norms = jnp.linalg.norm(dense, axis=tuple(range(1, dense.ndim)))
        k = max_rows or dense.shape[0]
        idx = jnp.argsort(norms)[::-1][:k]
        return cls(idx, dense[idx], dense.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def add(self, other):
        assert self.dense_size == other.dense_size
        return SparseTensor(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]),
            self.dense_size)

    def sparse_size(self):
        return self.indices.size + self.values.size

    def __str__(self):
        return (f"SparseTensor(indices={self.indices.size}, "
                f"dense_size={self.dense_size})")
