"""DeepSpeedEngine: the central training wrapper, TPU-native.

Reference: ``deepspeed/runtime/engine.py`` (3268 LoC) — ``forward`` :1653,
``backward`` :1795, ``step`` :1991, ``save_checkpoint`` :2818,
``load_checkpoint`` :2513. The torch engine mutates module state and drives
collectives through hooks; here the train state (params, optimizer state,
loss-scale state) is a pytree of **globally-sharded jax.Arrays** and each
micro batch is exactly ONE jitted dispatch:

  gas == 1:    _step_gas1(state, batch, rng, lr) -> loss, state', metrics
  gas > 1:     _micro_first(params, scale, batch, rng)      -> loss, acc
               _micro_next(params, scale, acc, batch, rng)  -> loss, acc
               _step_last(state, acc, batch, rng, lr) -> loss, state', metrics

The boundary step fuses forward+backward+optimizer-apply into one XLA
program: grads never round-trip through a persistent fp32 accumulator for
gas=1 and the optimizer update fuses into the backward epilogue. The fp32
optimizer moments are donated and alias in place; master params are NOT
donated so they stay readable between backward() and step() (reference
engine semantics: state mutates at step).

ZeRO stages are sharding choices (parallel/sharding.py), not code paths:
grads/optimizer state/params pick up a `data`-axis dimension at stages 2/1/3
and XLA emits the reduce-scatters and all-gathers the reference implements
manually (stage_1_and_2.py:894, stage3.py:1076, utils.py:918). The fp16
overflow check + skip-step + dynamic loss scale update run **inside** the
jitted step (no host sync), reproducing the reference's skip semantics.

The user-facing ``forward()/backward()/step()`` trio keeps reference call
shape: forward computes loss+grads in one fused pass (JAX can't backprop an
already-returned loss), backward accumulates, step applies at the gradient
accumulation boundary.
"""

import json
import os
import time
from typing import Any, Optional

import flax.struct
import flax.traverse_util
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.parallel import sharding as shd
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.parallel.topology import make_mesh
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.fp16.loss_scaler import (LossScaleState, has_overflow,
                                                    make_loss_scale_state,
                                                    update_scale)
from deepspeed_tpu.runtime.lr_schedules import LRScheduler, get_lr_schedule
from deepspeed_tpu.runtime.optimizers import build_optimizer
from deepspeed_tpu.tracing import NULL_TRACER, jit_cache_size
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (BACKWARD_GLOBAL_TIMER,
                                       FORWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER,
                                       NoopTimer, SynchronizedWallClockTimer,
                                       ThroughputTimer)

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray                 # i32: global (optimizer) steps attempted
    skipped_steps: jnp.ndarray        # i32: overflow-skipped steps
    params: Any                       # fp32 master params
    opt_state: Any
    scaler: LossScaleState


class DeepSpeedEngine:
    """Training engine. Build through :func:`deepspeed_tpu.initialize`."""

    def __init__(self, model, config, loss_fn=None, mesh=None,
                 training_data=None, lr_scheduler=None, collate_fn=None,
                 example_batch=None, seed=0, dont_change_device=False,
                 model_input_fn=None, client_optimizer=None):
        self.module = model
        self.client_lr_scheduler = lr_scheduler
        self.model_input_fn = model_input_fn

        # --- mesh first: the batch invariant needs the data-axis size ---
        raw = config if isinstance(config, dict) else None
        if raw is None and isinstance(config, str):
            with open(config) as f:
                raw = json.load(f)
        if mesh is None:
            from deepspeed_tpu.runtime.config import MeshConfig
            mesh = make_mesh(MeshConfig(**(raw or {}).get("mesh", {}) or {}))
        self.mesh = mesh
        dist.set_mesh(mesh)
        self.dp_world_size = mesh.shape["data"]
        self.mp_world_size = mesh.shape["model"]

        self._config = DeepSpeedConfig(raw if raw is not None else config,
                                       dp_world_size=self.dp_world_size)
        self.zero_stage = self._config.zero_optimization_stage
        # ZeRO-Offload / ZeRO-Infinity: host-RAM (or NVMe) optimizer state
        # (runtime/zero/offload.py; reference stage_1_and_2.py CPU path)
        def _dev(cfg):
            if cfg is None:
                return "none"
            return str(cfg.device.value if hasattr(cfg.device, "value")
                       else cfg.device)

        _oc = self._config.zero_config.offload_optimizer
        self._offload_cfg = _oc if _dev(_oc) != "none" else None
        # Training-time ZeRO-3 parameter offload (reference stage3.py:445-480
        # + swap_tensor/partitioned_param_swapper.py): the at-rest compute
        # copy of the params lives in PINNED HOST memory and streams to HBM
        # inside the jitted step (XLA schedules each leaf's transfer next to
        # its consumer); gradients stream back out to host memory, where the
        # host optimizer consumes them.
        _pc = self._config.zero_config.offload_param
        self._offload_param = _dev(_pc) != "none"
        if self._offload_param and self.zero_stage < 3:
            logger.warning("offload_param requires ZeRO stage 3 (reference "
                           "zero/config.py); ignoring for stage "
                           f"{self.zero_stage}")
            self._offload_param = False
        if self._offload_param and self._offload_cfg is None:
            # params on host with optimizer state on device would free the
            # small fraction and keep the big one: optimizer state (fp32
            # master + moments, 12B/param) dwarfs the bf16 compute copy.
            # Imply the host-optimizer tier, like ZeRO-Infinity.
            logger.warning(
                "offload_param without offload_optimizer: enabling host "
                "optimizer offload (optimizer state is 6x the bytes of the "
                "bf16 params)")
            from deepspeed_tpu.runtime.zero.config import \
                DeepSpeedZeroOffloadOptimizerConfig
            self._offload_cfg = DeepSpeedZeroOffloadOptimizerConfig(
                device=_dev(_pc), nvme_path=_pc.nvme_path)
        self._offload = None
        self._params_nvme = False   # set by _ensure_initialized when
        # offload_param.device == "nvme" (ZeRO-Infinity param tier)
        if self._offload_cfg is not None:
            # single worker = FIFO grad accumulation off the main thread
            from concurrent.futures import ThreadPoolExecutor
            self._offload_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="zero_offload")
            self._offload_futs = []
        self.compute_dtype = DTYPES[self._config.precision_dtype]
        self.fp16_enabled = self._config.fp16.enabled
        self.bfloat16_enabled = self._config.bf16.enabled
        jax.config.update("jax_default_matmul_precision",
                          self._config.matmul_precision) \
            if self._config.matmul_precision != "default" else None

        if loss_fn is None:
            from deepspeed_tpu.runtime.pipe.module import PipelineModule
            if isinstance(model, PipelineModule) and \
                    model.schedule == "1f1b":
                loss_fn = model.make_loss_fn()
        self.loss_fn = loss_fn or self._default_loss_fn()
        # pre-wrap reference: the activation-checkpointing wrapper takes
        # **kw, which would defeat signature checks (e.g. pld_theta)
        self._raw_loss_fn = self.loss_fn
        # activation checkpointing section (reference checkpointing.py:474):
        # remat the whole loss under a named policy / host-offload the
        # saved dot products (cpu_checkpointing)
        from deepspeed_tpu.runtime.activation_checkpointing import \
            wrap_loss_fn
        self.loss_fn = wrap_loss_fn(self.loss_fn,
                                    self._config.activation_checkpointing,
                                    mesh=self.mesh)
        self._rng = jax.random.PRNGKey(seed)
        self._example_batch = example_batch

        # optimizer: a client-supplied optax transform wins over the config
        # one (reference engine.py:1176 "client vs config optimizer")
        opt_cfg = self._config.optimizer
        if client_optimizer is not None:
            self.optimizer_name = "client"
            self.tx = client_optimizer
            self._base_lr = float(opt_cfg.params.get("lr", 0.0)) \
                if opt_cfg.params else 0.0
            # a client optimizer owns its own hyperparams unless the client
            # also handed us a schedule to drive
            self._drive_lr = lr_scheduler is not None or \
                (self._config.scheduler.type is not None)
        else:
            self.optimizer_name = opt_cfg.type or "adamw"
            self.tx, self._base_lr = build_optimizer(
                self.optimizer_name, opt_cfg.params,
                gradient_clipping=self._config.gradient_clipping)
            self._drive_lr = True

        # 1-bit compressed gradient sync (reference runtime/comm/nccl.py:15,
        # the comm backend behind the onebit optimizers): a onebit
        # optimizer type + params.comm_backend_name routes the
        # data-parallel gradient reduction through compressed_allreduce
        # under shard_map instead of the XLA psum — sign bits + one scale
        # on the wire (BASELINE.md: up to 5x comm reduction on
        # Ethernet-class links; on TPU this targets the DCN hop).
        self._compressed_axis = None
        _onebit_types = ("onebitadam", "onebitlamb", "zerooneadam")
        _cbn = (opt_cfg.params or {}).get("comm_backend_name")
        if client_optimizer is None and _cbn and \
                (opt_cfg.type or "").lower() in _onebit_types:
            _other = [a for a in ("model", "expert", "pipe", "sequence")
                      if self.mesh.shape.get(a, 1) > 1]
            if _other:
                logger.warning(
                    "comm_backend_name: compressed grad sync supports pure "
                    f"data parallelism; mesh has {_other} — using XLA psum")
            elif self._offload_cfg is not None:
                logger.warning(
                    "comm_backend_name: compressed grad sync does not "
                    "compose with the host-offload grad path — using "
                    "XLA psum")
            elif self.mesh.shape["data"] > 1:
                self._compressed_axis = "data"

        # lr schedule
        self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)

        # bookkeeping
        self.micro_steps = 0           # micro batches seen since init
        self.global_steps = 0          # optimizer steps taken (host mirror)
        self.global_samples = 0
        self.state: Optional[TrainState] = None
        self._grad_acc = None          # running grad sum (gas > 1 windows)
        self._pending = None           # forward() result awaiting backward()
        self._next_state = None        # boundary result awaiting step()
        self._next_metrics = None
        self._last_metrics = {}
        self.gas = self._config.gradient_accumulation_steps

        self._data_sampler = None        # data-efficiency v2 sampler
        self._data_sampler_state = None  # restored before deepspeed_io runs
        # pluggable checkpoint backend (checkpoint/backend.py; reference
        # checkpoint_engine.py:9 ABC + Nebula variant)
        from deepspeed_tpu.checkpoint.backend import get_checkpoint_engine
        self.checkpoint_engine = get_checkpoint_engine(
            self._config.checkpoint_engine)

        # progressive layer drop: theta(t) computed host-side per forward
        # and handed to the model through the loss fn (reference
        # engine.py:1139 progressive_layer_drop + :2021 update_state)
        self.progressive_layer_drop = None
        if self._config.pld.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                ProgressiveLayerDrop)
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=self._config.pld.theta, gamma=self._config.pld.gamma)
            import inspect
            try:
                ps = inspect.signature(self._raw_loss_fn).parameters
                accepts = "pld_theta" in ps or any(
                    p.kind == p.VAR_KEYWORD for p in ps.values())
            except (TypeError, ValueError):
                accepts = True
            if not accepts:
                raise ValueError(
                    "progressive_layer_drop is enabled but the loss_fn "
                    "does not accept a pld_theta kwarg — add "
                    "`pld_theta=None` to its signature and pass it into "
                    "the model call (models/gpt2.py consumes it)")
        # random-LTD (reference data_routing/basic_layer.py:14 wired at
        # engine.py:1698): the kept-token count is a SHAPE, so it enters
        # the program as a build-time constant; each schedule milestone
        # rebuilds the jitted fns (one recompile per milestone — size
        # step_size so a full run pays a handful)
        self._rltd_cfg = None
        self._rltd = None
        self._rltd_keep = None
        de = self._config.data_efficiency or {}
        # same falsy defaults as the data_sampling gate in deepspeed_io
        # and the reference data_pipeline/config.py: every level of the
        # data_efficiency section is off unless explicitly enabled
        dr = de.get("data_routing", {}) if de.get("enabled") else {}
        rl = dr.get("random_ltd", {}) if dr.get("enabled") else {}
        if rl.get("enabled"):
            self._rltd_cfg = rl
            import inspect
            try:
                ps = inspect.signature(self._raw_loss_fn).parameters
                accepts = "rltd_keep" in ps or any(
                    p.kind == p.VAR_KEYWORD for p in ps.values())
            except (TypeError, ValueError):
                accepts = True
            if not accepts:
                raise ValueError(
                    "random_ltd is enabled but the loss_fn does not "
                    "accept an rltd_keep kwarg — add `rltd_keep=None` "
                    "to its signature and pass it into the model call "
                    "(models/gpt2.py consumes it)")
        # compression-aware training: runtime built once params exist
        # (_ensure_initialized); strengths ride the batch as traced
        # scalars so schedule changes never recompile
        self._compression = None
        # MoQ: eigenvalue-scheduled quantization periods (reference
        # engine.py:2014-2026)
        self.eigenvalue = None
        self._gas_boundary_ctr = 0
        if self._config.eigenvalue.enabled:
            from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
            ev = self._config.eigenvalue
            self.eigenvalue = Eigenvalue(
                verbose=ev.verbose, max_iter=ev.max_iter, tol=ev.tol,
                stability=ev.stability,
                gas_boundary_resolution=ev.gas_boundary_resolution)
        # PLD / compression / random-LTD compose with the 1-bit path:
        # the reserved schedule scalars ride the batch REPLICATED into
        # the shard_map (batch_specs in _build_jitted_fns) and the local
        # loss threads them exactly like the SPMD fwd_bwd does

        self.timers = SynchronizedWallClockTimer() \
            if self._config.wall_clock_breakdown else NoopTimer()

        # monitor
        from deepspeed_tpu.monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(self._config.monitor_config)

        # throughput reporting rides the monitor event stream when a
        # sink is enabled (train/samples_per_s*), else the legacy print
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print,
            monitor=self.monitor)

        # host-side span tracing (deepspeed_tpu/tracing.py): the shared
        # no-op singleton unless a supervisor/caller installs a real
        # tracer — tracing off must stay byte-identical (no device op,
        # no new jit signature; pinned by tests/unit/test_train_trace.py)
        self.tracer = NULL_TRACER

        dist.configure(self._config)
        # comm.log_summary's periodic report rides the same monitor
        # stream as ThroughputTimer when the engine's sinks are
        # enabled (comm/<op>/* gauges); without one the legacy print
        # is preserved byte-for-byte.  Last engine wins (weakly held —
        # a discarded engine's monitor detaches with it)
        dist.attach_monitor(self.monitor if self.monitor.enabled
                            else None)

        self.training_dataloader = self.deepspeed_io(training_data, collate_fn) \
            if training_data is not None else None

        if example_batch is not None:
            self._ensure_initialized(example_batch)

    # ------------------------------------------------------------------ config
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self.zero_stage

    def get_global_grad_norm(self):
        return self._last_metrics.get("grad_norm")

    # reference accessor surface (engine.py:480-857 exposes ~120 of
    # these; the ones client code commonly touches)
    def get_mom(self):
        """Current (beta1, beta2) per param group (reference get_mom)."""
        betas = (self._config.optimizer.params or {}).get(
            "betas", (0.9, 0.999))
        return [tuple(betas)]

    def global_rank(self):
        return jax.process_index()

    def world_size(self):
        return jax.process_count()

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def fp16_enabled(self):
        return bool(self._config.fp16.enabled)

    def bfloat16_enabled(self):
        return bool(self._config.bf16.enabled)

    def zero_offload_optimizer(self):
        return self._offload is not None

    def wall_clock_breakdown(self):
        return bool(self._config.wall_clock_breakdown)

    def steps_per_print(self):
        return self._config.steps_per_print

    def monitor_enabled(self):
        return bool(self.monitor.enabled)

    @property
    def loss_scale(self):
        if self._offload is not None:
            return float(self._offload.scaler.loss_scale)
        if self.state is None:
            return 1.0
        return float(jax.device_get(self._live_state().scaler.loss_scale))

    @property
    def skipped_steps(self):
        if self._offload is not None:
            return self._offload.skipped_steps
        if self.state is None:
            return 0
        return int(jax.device_get(self._live_state().skipped_steps))

    def is_gradient_accumulation_boundary(self):
        return (self.micro_steps + 1) % self.gas == 0

    def _default_loss_fn(self):
        """Default contract: module(input_ids) -> logits, next-token CE.
        MoE aux losses sown under "intermediates" (moe/layer.py) are added
        with the model's `moe_loss_coef` (reference adds l_aux in the client
        loss; the engine folds it in for the default path)."""
        from deepspeed_tpu.models.gpt2 import gpt2_loss_fn
        module = self.module
        coef = getattr(getattr(module, "cfg", None), "moe_loss_coef", None)
        moe_coef = 0.01 if coef is None else float(coef)

        def loss_fn(params, batch, rng, pld_theta=None, rltd_keep=None):
            rngs = None
            kw = {}
            if rng is not None:
                # "gating" feeds MoE's stochastic drop policies (RTS /
                # RSample); unused rngs are free in flax
                rngs = {"dropout": rng,
                        "gating": jax.random.fold_in(rng, 3)}
            if pld_theta is not None:   # progressive layer drop active
                r = rng if rng is not None else jax.random.PRNGKey(0)
                rngs = dict(rngs or {})
                rngs["pld"] = jax.random.fold_in(r, 1)
                kw["pld_theta"] = pld_theta
            if rltd_keep is not None:   # random-LTD token dropping
                r = rng if rng is not None else jax.random.PRNGKey(0)
                rngs = dict(rngs or {})
                rngs["rltd"] = jax.random.fold_in(r, 2)
                kw["rltd_keep"] = rltd_keep
            logits, mut = module.apply(
                {"params": params}, batch["input_ids"], rngs=rngs,
                mutable=["intermediates"], **kw)
            loss = gpt2_loss_fn(logits, batch)
            aux = [v for path, v in
                   flax.traverse_util.flatten_dict(
                       mut.get("intermediates", {})).items()
                   if path[-1] == "moe_aux_loss"]
            if aux:
                # sow stores a tuple per call site
                terms = [jnp.asarray(x) for tup in aux for x in tup]
                loss = loss + moe_coef * sum(terms)
            return loss

        return loss_fn

    def _configure_lr_scheduler(self, client_scheduler):
        if client_scheduler is not None:
            # a bare schedule callable (step -> lr) gets the LRScheduler
            # interface; an LRScheduler (or duck-typed object with
            # get_lr/step) passes through
            if not isinstance(client_scheduler, LRScheduler) and \
                    callable(client_scheduler) and \
                    not hasattr(client_scheduler, "get_lr"):
                return LRScheduler(client_scheduler)
            return client_scheduler
        s = self._config.scheduler
        if s.type:
            return LRScheduler(get_lr_schedule(s.type, s.params))
        return None

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_lr()
        return [self._base_lr]

    # ------------------------------------------------------------- init params
    def _ensure_initialized(self, batch):
        if self.state is not None:
            return
        t0 = time.time()
        mesh = self.mesh
        host_batch = jax.tree.map(np.asarray, batch)
        init_rng, self._rng = jax.random.split(self._rng)

        example_input = self._model_input(host_batch)

        def init_fn(rng):
            return self.module.init(rng, self._example_like(example_input))

        boxed_shapes = jax.eval_shape(init_fn, init_rng)
        boxed_shapes = boxed_shapes.get("params", boxed_shapes)
        logical = shd.get_logical_specs(boxed_shapes)
        shapes = shd.unbox(boxed_shapes)

        persist = int(self._config.zero_config
                      .stage3_param_persistence_threshold) \
            if self.zero_stage >= 3 else 0
        self.param_pspecs = shd.tree_pspecs(mesh, shapes, logical,
                                            self.zero_stage, kind="param",
                                            persist_threshold=persist)
        opt_param_pspecs = shd.tree_pspecs(mesh, shapes, logical,
                                           self.zero_stage, kind="opt")
        if self._offload_cfg is not None:
            self.opt_pspecs = ()   # optimizer state lives on the host
        else:
            opt_shapes = jax.eval_shape(self.tx.init, shapes)
            self.opt_pspecs = shd.opt_state_pspecs(opt_shapes, shapes,
                                                   opt_param_pspecs)
        self.grad_pspecs = opt_param_pspecs if self.zero_stage >= 2 \
            else self.param_pspecs

        param_sh = shd.tree_shardings(mesh, self.param_pspecs)
        opt_sh = shd.tree_shardings(mesh, self.opt_pspecs)
        self._grad_sh = shd.tree_shardings(mesh, self.grad_pspecs)

        def init_params(r):
            variables = init_fn(r)
            return shd.unbox(variables.get("params", variables))

        params = jax.jit(init_params, out_shardings=param_sh)(init_rng)
        if self._offload_cfg is not None:
            # ZeRO-Offload: pull the fp32 master to host, keep only the
            # compute-dtype copy on the chip, moments live host/NVMe.
            from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
            _pc = self._config.zero_config.offload_param
            self._params_nvme = bool(
                self._offload_param and _pc is not None and
                str(getattr(_pc, "device", "none")) == "nvme")
            param_nvme_path = None
            if self._params_nvme:
                param_nvme_path = _pc.nvme_path or \
                    getattr(self._offload_cfg, "nvme_path", None)
                assert param_nvme_path, \
                    "offload_param.device=nvme needs offload_param." \
                    "nvme_path (or offload_optimizer.nvme_path)"
            self._offload = HostOffloadOptimizer(
                self.optimizer_name, self._config.optimizer.params,
                gradient_clipping=self._config.gradient_clipping,
                fp16_cfg=self._config.fp16, fp16_enabled=self.fp16_enabled,
                offload_cfg=self._offload_cfg,
                aio_config=self._config.aio_config,
                param_nvme_path=param_nvme_path,
                param_dtype={jnp.bfloat16: "bf16",
                             jnp.float16: "f16"}.get(self.compute_dtype,
                                                     "f32"))
            from deepspeed_tpu.checkpoint.engine import param_leaf_names
            leaf_names = param_leaf_names(params)
            # sparse embedding grads (reference sparse_gradients +
            # SparseTensor, engine.py:2303): embedding-table leaves ship
            # their grads D2H as (touched-row indices, rows) instead of
            # the dense [vocab, d] table. Decided from names + shapes of
            # the (still-device) tree — host_leaves may be a one-shot
            # generator below.
            self._sparse_positions = frozenset(
                i for i, (n, l) in enumerate(
                    zip(leaf_names, jax.tree.leaves(params)))
                if self._config.sparse_gradients_enabled and l.ndim == 2
                and any(t in n.lower()
                        for t in ("wte", "wpe", "embed"))) or None
            if self._params_nvme:
                # one leaf in RAM at a time: each master streams to NVMe
                # before the next device_get lands
                host_leaves = (np.asarray(jax.device_get(l))
                               for l in jax.tree.leaves(params))
            else:
                host_leaves = [np.asarray(jax.device_get(l))
                               for l in jax.tree.leaves(params)]
            self._offload.init_master(host_leaves, names=leaf_names)
            compute_dtype = self.compute_dtype
            if self._params_nvme:
                # ZeRO-Infinity param tier: the device/pinned copies are
                # dropped entirely — state.params becomes the tier's
                # memmap views over the NVMe files (written in compute
                # dtype by init_master; no on-device cast needed). Each
                # dispatch device_puts them to the (device-kind)
                # shardings, so pages stream NVMe -> page cache -> HBM
                # on demand and the buffers die with the dispatch; the
                # optimizer sweep rewrites the files through the SAME
                # page cache, so the next dispatch reads the updated
                # bytes. RAM holds the evictable page cache, never a
                # pinned full copy.
                treedef = jax.tree.structure(params)
                del params
                params = jax.tree_util.tree_unflatten(
                    treedef, self._offload.param_tier.param_memmaps())
                self._param_mat_sh = param_sh
                self._injit_materialize = False
                log_dist("ZeRO-Infinity: at-rest params on NVMe "
                         f"({self._offload.param_tier.dir}); per-dispatch "
                         "page-cached streaming", ranks=[0])
            else:
                cast_fn = jax.jit(
                    lambda p: jax.tree.map(
                        lambda x: x.astype(compute_dtype), p),
                    out_shardings=param_sh, donate_argnums=(0,))
                params = cast_fn(params)
            if not self._params_nvme and self._offload_param:
                # at-rest compute copy in pinned host memory; the jitted
                # step streams leaves to HBM per use (same mechanism the
                # inference engine proves for ZeRO-Inference,
                # inference/engine.py _materialize) and writes grads back
                # to host memory. Between steps the chip holds no params.
                host_sh = jax.tree.map(
                    lambda s: s.with_memory_kind("pinned_host"), param_sh)
                params = jax.tree.map(jax.device_put, params, host_sh)
                self._param_mat_sh = param_sh   # device-kind shardings
                # Streaming strategy: prefer materializing INSIDE the
                # jitted step (XLA schedules each leaf's — or, with
                # scan_layers, each layer slice's — transfer next to its
                # consumer and frees it after last use: params larger
                # than HBM train). Some backends reject memory-space
                # transfers of sharded arrays under SPMD ("side-effect
                # ops cannot be replicated"); probe once and fall back to
                # an eager pre-dispatch transfer (full bf16 tree resident
                # for the dispatch) when unsupported.
                self._injit_materialize = self._probe_injit_materialize(
                    params, param_sh, host_sh)
                self._grad_sh_dev = self._grad_sh
                if self._injit_materialize:
                    # host-kind grad shardings: _micro_offload device_puts
                    # each grad leaf to these inside the program, so grads
                    # leave HBM before the dispatch returns and the host
                    # optimizer reads pinned memory directly
                    self._grad_sh = jax.tree.map(
                        lambda s: s.with_memory_kind("pinned_host"),
                        self._grad_sh)
                log_dist("ZeRO-3 param offload: at-rest params in pinned "
                         "host memory, "
                         + ("in-program streaming"
                            if self._injit_materialize else
                            "per-dispatch transfer (backend rejects "
                            "in-program memory-space moves)"), ranks=[0])
                param_sh = host_sh
            self._param_treedef = jax.tree.structure(params)
            self._param_sh_flat = jax.tree.leaves(param_sh)
            opt_state = ()      # optimizer state lives on the host
        else:
            opt_state = jax.jit(self.tx.init, out_shardings=opt_sh)(params)

        scaler = make_loss_scale_state(self._config.fp16, self.fp16_enabled)
        self.state = TrainState(step=jnp.int32(0), skipped_steps=jnp.int32(0),
                                params=params, opt_state=opt_state,
                                scaler=scaler)
        # pin state shardings so the apply step can't silently reshard params,
        # and commit the scalar fields to the mesh (replicated) so every leaf
        # lives on the same device set
        rep = NamedSharding(mesh, P())
        self._state_sh = jax.tree.map(lambda _: rep, self.state).replace(
            params=param_sh, opt_state=opt_sh)
        if getattr(self, "_params_nvme", False):
            # the memmap leaves must NOT be committed to devices here:
            # they stream per dispatch (a device_put now would pin the
            # full model in HBM for the run)
            mm_params = self.state.params
            scalars = jax.tree.map(
                jax.device_put, self.state.replace(params=()),
                self._state_sh.replace(params=()))
            self.state = scalars.replace(params=mm_params)
        else:
            self.state = jax.tree.map(jax.device_put, self.state,
                                      self._state_sh)
        if self._compressed_axis:
            # per-worker error-feedback buffers for the compressed
            # collective (reference worker_error/server_error,
            # runtime/comm/nccl.py): leading dp axis = one slice per
            # worker. Not checkpointed — the residual re-accumulates
            # within a step after resume.
            n = mesh.shape[self._compressed_axis]

            def we_leaf(s):
                sh = NamedSharding(mesh, P(self._compressed_axis,
                                           *([None] * len(s.shape))))
                return jax.device_put(
                    jnp.zeros((n,) + tuple(s.shape), jnp.float32), sh)

            def se_leaf(s):
                size = int(np.prod(s.shape or (1,)))
                chunk = (size + (-size) % (n * 8)) // n
                sh = NamedSharding(mesh, P(self._compressed_axis, None))
                return jax.device_put(jnp.zeros((n, chunk), jnp.float32),
                                      sh)

            self._onebit_we = jax.tree.map(we_leaf, shapes)
            self._onebit_se = jax.tree.map(se_leaf, shapes)
        if self._config.compression_training:
            from deepspeed_tpu.compression.compress import CompressionRuntime
            self._compression = CompressionRuntime(
                self._config.compression_training, self.state.params,
                num_heads=getattr(getattr(self.module, "cfg", None),
                                  "num_heads", None))
            log_dist("compression-aware training: "
                     f"{len(self._compression)} config groups active",
                     ranks=[0])
        # sparse embedding gradients on the dense-DP path (reference
        # engine.py:2303 sparse allreduce in plain DP; the offload path
        # has its own D2H variant). Engaged when data parallelism is
        # real and the fused gas window / onebit / offload are not
        # claiming the step.
        self._sparse_dp = False
        if self._config.sparse_gradients_enabled and \
                self._offload is None and not self._compressed_axis and \
                mesh.shape.get("data", 1) > 1 and self.gas == 1 and \
                self.zero_stage <= 2 and \
                self.progressive_layer_drop is None and \
                self._compression is None and self._rltd_cfg is None and \
                not self._config.compression_training:
            if getattr(getattr(self.module, "cfg", None),
                       "tie_embeddings", False):
                raise ValueError(
                    "sparse_gradients with a TIED embedding head: the "
                    "lm head's backward produces a DENSE [vocab, d] "
                    "grad on wte every step, so there is nothing "
                    "sparse to ship — untie the embeddings or disable "
                    "sparse_gradients")
            from deepspeed_tpu.checkpoint.engine import param_leaf_names
            names = param_leaf_names(self.state.params)
            lv = jax.tree.leaves(self.state.params)
            self._sparse_dp_positions = frozenset(
                i for i, (nm, l) in enumerate(zip(names, lv))
                if l.ndim == 2 and any(t in nm.lower()
                                       for t in ("wte", "wpe", "embed")))
            ids = self._model_input(batch)
            self._sparse_dp_tokens = int(
                np.prod(np.shape(ids)) // mesh.shape["data"])
            self._sparse_dp = bool(self._sparse_dp_positions)
            if self._sparse_dp:
                log_dist(
                    "sparse_gradients: dense-DP embedding grads sync as "
                    f"(indices, rows) over 'data' — "
                    f"{len(self._sparse_dp_positions)} leaves, "
                    f"{self._sparse_dp_tokens} rows/shard budget",
                    ranks=[0])
        self._build_jitted_fns()
        n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
        log_dist(f"engine initialized: {n_params / 1e6:.2f}M params, mesh="
                 f"{dict(mesh.shape)}, zero_stage={self.zero_stage}, "
                 f"dtype={self._config.precision_dtype}, "
                 f"init took {time.time() - t0:.1f}s", ranks=[0])

    def _model_input(self, batch):
        """The tensor the module's __call__ consumes, for shape inference.
        Override with model_input_fn for exotic batch layouts."""
        if self.model_input_fn is not None:
            return self.model_input_fn(batch)
        if isinstance(batch, dict):
            for key in ("input_ids", "x", "inputs", "tokens"):
                if key in batch:
                    return batch[key]
            return next(iter(batch.values()))
        if isinstance(batch, (tuple, list)):
            return batch[0]
        return batch

    def _example_like(self, x):
        return jnp.asarray(x)

    def _batch_sharding(self, batch):
        mesh = self.mesh
        def f(leaf):
            arr = np.asarray(leaf)
            spec = P("data") if arr.ndim >= 1 and \
                arr.shape[0] % mesh.shape["data"] == 0 else P()
            return NamedSharding(mesh, spec)
        return jax.tree.map(f, batch)

    def _put_batch(self, batch):
        sh = self._batch_sharding(batch)
        return jax.tree.map(lambda x, s: jax.device_put(jnp.asarray(x), s),
                            batch, sh)

    # --------------------------------------------------------------- jitted fns
    def _build_jitted_fns(self):
        loss_fn = self.loss_fn
        compute_dtype = self.compute_dtype
        gas = float(self.gas)
        tx = self.tx
        clip_norm = float(self._config.gradient_clipping or 0.0)
        predivide = float(self._config.gradient_predivide_factor or 1.0)
        drive_lr = self._drive_lr

        def cast(p):
            return jax.tree.map(
                lambda x: x.astype(compute_dtype)
                if x.dtype == jnp.float32 and compute_dtype != jnp.float32 else x, p)

        rltd_keep_static = self._rltd_keep

        # in-program param streaming (ZeRO-3 param offload): host-kind
        # params enter the program; XLA places each transfer next to its
        # consumer and frees the device buffer after last use
        mat_sh = self._param_mat_sh \
            if getattr(self, "_injit_materialize", False) else None

        def materialize(p):
            if mat_sh is None:
                return p
            return jax.tree.map(jax.device_put, p, mat_sh)


        # pipeline loss_fns hand back (loss, grads) from one interleaved
        # 1F1B scan — cheaper than value_and_grad, which would run the
        # forward-only pipeline AND the backward's forward slots
        loss_and_grads = getattr(loss_fn, "loss_and_grads", None)

        comp = self._compression

        RESERVED = ("_ds_pld_theta", "_ds_comp")

        def pop_reserved(batch):
            """Split the reserved schedule scalars (injected by
            forward() as TRACED values, so per-step changes never
            recompile) out of the batch: -> (clean_batch, extras,
            loss_kw). ONE implementation shared by the SPMD fwd_bwd and
            the 1-bit shard_map local loss."""
            extras = {}
            if isinstance(batch, dict) and any(k in batch
                                               for k in RESERVED):
                batch = dict(batch)
                for k in RESERVED:
                    if k in batch:
                        extras[k] = batch.pop(k)
            loss_kw = {"pld_theta": extras["_ds_pld_theta"]} \
                if "_ds_pld_theta" in extras else {}
            if rltd_keep_static is not None:
                # a shape constant: baked into this build of the
                # jitted fns (forward() rebuilds at schedule milestones)
                loss_kw["rltd_keep"] = rltd_keep_static
            return batch, extras, loss_kw

        def make_prep(extras, mat=True):
            """The shared param-preparation closure (cast [+ in-jit
            materialize] + compression apply) — ONE implementation for
            the SPMD and 1-bit paths; ``mat=False`` on the per-worker
            path, where offload streaming is excluded by construction."""
            def prep(p):
                p = cast(materialize(p) if mat else p)
                if comp is not None and "_ds_comp" in extras:
                    p = comp.apply(p, extras["_ds_comp"])
                return p
            return prep

        def fwd_bwd(params, scale, batch, rng):
            batch, extras, loss_kw = pop_reserved(batch)
            prep = make_prep(extras)

            if loss_and_grads is not None:
                assert not extras and rltd_keep_static is None, \
                    "compression/pld/random_ltd do not compose with the " \
                    "fused 1F1B pipeline loss yet"
                loss, grads = loss_and_grads(cast(materialize(params)), batch)
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.float32) * (scale / gas), grads)
                return loss, grads

            def scaled_loss(p):
                loss = loss_fn(prep(p), batch, rng, **loss_kw)
                return loss.astype(jnp.float32) * scale / gas, loss

            (s_loss, loss), grads = jax.value_and_grad(
                scaled_loss, has_aux=True)(params)
            return loss, grads

        # Overflow check + skip-step are fp16 loss-scaling machinery
        # (reference FP16_Optimizer); bf16/fp32 training never skips
        # (reference BF16_Optimizer has no CheckOverflow). Gating it out
        # also deletes a full isfinite pass over the grad tree that the
        # fused gas window can't fuse into the adam update (~2.4ms/window
        # at GPT-2-small bench shapes).
        check_overflow = self.fp16_enabled

        def apply_grads(state, acc, lr):
            scale = state.scaler.loss_scale
            grads = jax.tree.map(lambda g: g / (scale * predivide), acc)
            overflow = has_overflow(grads) if check_overflow \
                else jnp.bool_(False)

            gnorm = optax.global_norm(grads)
            if clip_norm > 0.0:
                factor = jnp.minimum(1.0, clip_norm / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * factor, grads)

            opt_state = state.opt_state
            # drive the LR schedule value into inject_hyperparams state
            # (skipped for a client optimizer with no schedule: its own
            # hyperparams stand)
            if drive_lr and hasattr(opt_state, "hyperparams"):
                hp = dict(opt_state.hyperparams)
                hp["learning_rate"] = jnp.asarray(lr, jnp.float32)
                opt_state = opt_state._replace(hyperparams=hp)

            updates, new_opt = tx.update(grads, opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)

            # skip-step on overflow (reference stage_1_and_2.py:1636 semantics)
            if check_overflow:
                new_params = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new_params,
                    state.params)
                new_opt = jax.tree.map(
                    lambda n, o: jnp.where(overflow, o, n), new_opt,
                    opt_state)

            scaler = update_scale(state.scaler, overflow)
            new_state = state.replace(
                step=state.step + 1,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
                params=new_params, opt_state=new_opt, scaler=scaler)
            metrics = {"grad_norm": gnorm, "overflow": overflow,
                       "loss_scale": scaler.loss_scale}
            return new_state, metrics

        # One fused dispatch per micro batch; the boundary step folds the
        # optimizer apply into the same XLA program so the whole train step
        # is a single executable (no persistent fp32 accumulator at gas=1).
        # Only opt_state is donated: params must stay readable between
        # backward() and step() (reference engine semantics — state mutates
        # at step), and the optimizer moments are the bulk of the bytes.
        def step_gas1(params, opt_state, rest, batch, rng, lr):
            state = rest.replace(params=params, opt_state=opt_state)
            loss, grads = fwd_bwd(params, state.scaler.loss_scale, batch, rng)
            new_state, metrics = apply_grads(state, grads, lr)
            return loss, new_state, metrics

        self._step_gas1 = jax.jit(
            step_gas1, donate_argnums=(1,),
            out_shardings=(None, self._state_sh, None))

        def micro_first(params, scale, batch, rng):
            return fwd_bwd(params, scale, batch, rng)

        self._micro_first = jax.jit(
            micro_first, out_shardings=(None, self._grad_sh))

        # offload-mode micro dispatch: flat per-leaf grads, with
        # embedding leaves row-sparsified on device so only touched rows
        # cross the host link (reference sparse_allreduce, engine.py:2303).
        # When the backend supports in-program memory-space moves
        # (_injit_materialize), each grad leaf is moved to pinned host
        # memory INSIDE the program — the leaves never sit in HBM between
        # dispatch and the host optimizer. The output structure depends on
        # the traced batch shape (sparse leaves become (idx, rows, n)
        # tuples), so this is an in-body device_put rather than jit
        # out_shardings.
        sparse_pos = getattr(self, "_sparse_positions", None)
        injit_grads_to_host = (self._offload is not None and
                               getattr(self, "_injit_materialize", False))
        if injit_grads_to_host:
            grad_host_sh = jax.tree.leaves(self._grad_sh)  # host-kind
            host_rep = NamedSharding(
                self.mesh, P(), memory_kind="pinned_host")

        def micro_offload(params, scale, batch, rng):
            loss, grads = fwd_bwd(params, scale, batch, rng)
            leaves = jax.tree.leaves(grads)
            if sparse_pos:
                tokens = int(np.prod(
                    jnp.shape(self._model_input(batch)))) or 1
                out = []
                for i, g in enumerate(leaves):
                    k = min(tokens, g.shape[0]) if g.ndim == 2 else 0
                    if i in sparse_pos and 0 < k < g.shape[0]:
                        rn = jnp.sum(jnp.abs(g), axis=1)
                        n_touched = jnp.sum(rn > 0).astype(jnp.int32)
                        idx = jnp.nonzero(rn > 0, size=k,
                                          fill_value=0)[0]
                        # mask pad slots POSITIONALLY: nonzero's fill
                        # index 0 may itself be a touched row, so a
                        # value-based mask would scatter row 0's grad
                        # once per pad slot
                        valid = (jnp.arange(k) <
                                 jnp.minimum(n_touched, k)).astype(g.dtype)
                        # n_touched rides along so the host can detect a
                        # DENSE grad hitting this leaf (tied-embedding
                        # head) and fail loudly instead of truncating
                        out.append((idx, g[idx] * valid[:, None],
                                    n_touched))
                    else:
                        out.append(g)
                leaves = out
            if injit_grads_to_host:
                leaves = [
                    tuple(jax.device_put(part, host_rep) for part in g)
                    if isinstance(g, tuple)
                    else jax.device_put(g, grad_host_sh[i])
                    for i, g in enumerate(leaves)]
            return loss, leaves

        self._micro_offload = jax.jit(micro_offload)

        def micro_next(params, scale, acc, batch, rng):
            loss, grads = fwd_bwd(params, scale, batch, rng)
            return loss, jax.tree.map(jnp.add, acc, grads)

        self._micro_next = jax.jit(
            micro_next, donate_argnums=(2,),
            out_shardings=(None, self._grad_sh))

        def step_last(params, opt_state, rest, acc, batch, rng, lr):
            state = rest.replace(params=params, opt_state=opt_state)
            loss, grads = fwd_bwd(params, state.scaler.loss_scale, batch, rng)
            acc = jax.tree.map(jnp.add, acc, grads)
            new_state, metrics = apply_grads(state, acc, lr)
            return loss, new_state, metrics

        self._step_last = jax.jit(
            step_last, donate_argnums=(1, 3),
            out_shardings=(None, self._state_sh, None))

        # Fused full accumulation window: all gas micro batches + the
        # optimizer apply in ONE dispatch (train_batch uses this when the
        # whole window's data is available). Kills the 3-dispatch pattern
        # for the gas>1 regime every large-model config runs (VERDICT r2
        # weak #2); the fp32 accumulator lives only inside the program.
        # The micro loop is UNROLLED, not lax.scan: a scan carrying the
        # params-sized fp32 accumulator measures ~19x slower on v5e (the
        # loop-carried buffer defeats in-place accumulation), while the
        # unrolled body runs at the gas=1 rate.
        n_micro = self.gas

        def step_gasN(params, opt_state, rest, batches, rng, lr):
            state = rest.replace(params=params, opt_state=opt_state)
            scale = state.scaler.loss_scale
            rngs = jax.random.split(rng, n_micro)
            acc, losses = None, []
            for i in range(n_micro):
                b = jax.tree.map(lambda x: x[i], batches)
                loss, grads = fwd_bwd(params, scale, b, rngs[i])
                acc = grads if acc is None else \
                    jax.tree.map(jnp.add, acc, grads)
                losses.append(loss)
            new_state, metrics = apply_grads(state, acc, lr)
            # mean computed in-program: fetching per-micro losses would
            # cost a host round trip per step on relayed devices
            return jnp.mean(jnp.stack(losses)), new_state, metrics

        # params donated too: _train_batch_fused commits the new state
        # before control returns, so no caller can observe the donated
        # buffer, and the old tree hosts the new one instead of a fresh
        # params-sized allocation per window. The forward()/step() split
        # paths do NOT donate params — users legitimately read
        # state.params between backward() and step().
        self._step_gasN = jax.jit(
            step_gasN, donate_argnums=(0, 1),
            out_shardings=(None, self._state_sh, None))

        # Multi-STEP fused driver (train_loop): lax.scan over K complete
        # optimizer steps (windows, when gas > 1) in one dispatch.
        # Per-dispatch host overhead (arg marshaling + runtime round
        # trip; ~6ms/dispatch through a relayed device, ~100us on a
        # local TPU VM) amortizes over K. Unlike the gasN accumulator
        # (unrolled above — its loop-carried fp32 accumulator defeated
        # in-place updates), the scan carry here is the full train state
        # and every carried buffer is rewritten each iteration, so XLA
        # aliases it in place: measured at the per-step device rate.
        win_fn = step_gas1 if n_micro == 1 else step_gasN

        def step_loop(params, opt_state, rest, batches, rngs, lrs):
            def body(carry, xs):
                p, o, r = carry
                b, rng_i, lr_i = xs
                loss, new_state, metrics = win_fn(p, o, r, b, rng_i, lr_i)
                return (new_state.params, new_state.opt_state,
                        new_state.replace(params=None, opt_state=None)), \
                    (loss, metrics)
            (p, o, r), (losses, metrics) = jax.lax.scan(
                body, (params, opt_state, rest), (batches, rngs, lrs))
            last = jax.tree.map(lambda m: m[-1], metrics)
            return losses, r.replace(params=p, opt_state=o), last

        self._step_loop = jax.jit(
            step_loop, donate_argnums=(0, 1),
            out_shardings=(None, self._state_sh, None))

        if getattr(self, "_sparse_dp", False):
            # sparse_gradients on the DENSE data-parallel path
            # (reference sparse_allreduce_no_retain, engine.py:2303): the
            # fwd+bwd runs under shard_map so the embedding grads stay
            # per-worker; embedding leaves sync as (touched-row indices,
            # rows) via all_gather + scatter-add — traffic scales with
            # tokens, not vocab — while every other leaf takes a plain
            # pmean. Tied-embedding heads produce DENSE wte grads, which
            # would overflow the row budget: the sync poisons the result
            # with NaN in that case so training fails loudly instead of
            # silently dropping gradient mass.
            from jax import lax
            mesh = self.mesh
            sparse_pos = self._sparse_dp_positions

            def sparse_sync(grads, k):
                # k (row budget) comes from the TRACED batch shape, so a
                # curriculum/packing change retraces with the right
                # budget instead of NaN-poisoning legitimate grads
                leaves = jax.tree.leaves(grads)
                out = []
                for i, g in enumerate(leaves):
                    if i in sparse_pos and g.ndim == 2 and \
                            0 < k < g.shape[0]:
                        rn = jnp.sum(jnp.abs(g), axis=1)
                        n_touched = jnp.sum(rn > 0)
                        idx = jnp.nonzero(rn > 0, size=k,
                                          fill_value=0)[0]
                        valid = (jnp.arange(k) <
                                 jnp.minimum(n_touched, k)).astype(g.dtype)
                        vals = g[idx] * valid[:, None]
                        all_idx = lax.all_gather(idx, "data")
                        all_vals = lax.all_gather(vals, "data")
                        dense = jnp.zeros_like(g).at[
                            all_idx.reshape(-1)].add(
                            all_vals.reshape(-1, g.shape[1]))
                        dp = all_idx.shape[0]
                        bad = (n_touched > k).astype(g.dtype)
                        out.append(dense / dp +
                                   bad * jnp.float32(jnp.nan).astype(
                                       g.dtype))
                    else:
                        out.append(lax.pmean(g, "data"))
                return jax.tree.unflatten(jax.tree.structure(grads), out)

            def local_fwd_bwd_sparse(params, scale, batch, rng):
                def scaled_loss(p):
                    loss = loss_fn(cast(p), batch, rng)
                    return loss.astype(jnp.float32) * scale, loss

                (_, loss), grads = jax.value_and_grad(
                    scaled_loss, has_aux=True)(params)
                k = int(np.prod(np.shape(self._model_input(batch))))
                return lax.pmean(loss, "data"), sparse_sync(grads, k)

            sm_sparse = jax.shard_map(
                local_fwd_bwd_sparse, mesh=mesh,
                in_specs=(P(), P(), P("data"), P()),
                out_specs=(P(), P()),
                check_vma=False)   # the all_gather makes grads
            # replicated; the rep checker cannot prove it

            def step_sparse_dp(params, opt_state, rest, batch, rng, lr):
                state = rest.replace(params=params, opt_state=opt_state)
                loss, grads = sm_sparse(params, state.scaler.loss_scale,
                                        batch, rng)
                new_state, metrics = apply_grads(state, grads, lr)
                return loss, new_state, metrics

            self._step_sparse_dp = jax.jit(
                step_sparse_dp, donate_argnums=(1,),
                out_shardings=(None, self._state_sh, None))

        if self._compressed_axis:
            # 1-bit compressed grad sync: the whole fwd+bwd runs under
            # shard_map so gradients stay per-worker (no SPMD psum);
            # compressed_allreduce exchanges sign bits + one scale with
            # error feedback, then the boundary apply runs on the
            # (bitwise-identical) synced grads. check_vma off: the
            # all_gather in phase 2 makes outputs replicated, which the
            # rep checker cannot prove.
            from deepspeed_tpu.runtime.comm.compressed import \
                compressed_allreduce
            from jax import lax
            shard_map = jax.shard_map
            ca = self._compressed_axis
            mesh = self.mesh

            def compress_sync(grads, we, se):
                """Error-feedback sign-allreduce over a grad tree; the
                we/se buffers carry a leading per-worker axis inside the
                shard_map ([0] strips it, [None] restores it)."""
                outs = [compressed_allreduce(g, w[0], s_[0], ca)
                        for g, w, s_ in zip(jax.tree.leaves(grads),
                                            jax.tree.leaves(we),
                                            jax.tree.leaves(se))]
                tdef = jax.tree.structure(grads)
                return (jax.tree.unflatten(tdef, [o[0] for o in outs]),
                        jax.tree.unflatten(tdef,
                                           [o[1][None] for o in outs]),
                        jax.tree.unflatten(tdef,
                                           [o[2][None] for o in outs]))

            def batch_specs(batch, stacked=False):
                """Per-leaf specs: the reserved schedule scalars
                (compression strengths, pld theta) ride the batch
                REPLICATED — only real data leaves shard over 'data'.
                This is what lets PLD/compression compose with the
                1-bit path (r4 weak #5). ``stacked`` adds the fused
                window's leading [n_micro] axis to every spec."""
                data_spec = P(None, "data") if stacked else P("data")
                rep_spec = P(None) if stacked else P()
                if not isinstance(batch, dict):
                    return jax.tree.map(lambda _: data_spec, batch)
                return {k: (rep_spec if k in RESERVED
                            else jax.tree.map(lambda _: data_spec, v))
                        for k, v in batch.items()}

            def local_loss(params, batch, rng, scale, div=1.0):
                """One micro's scaled loss + grads for the per-worker
                (shard_map) path; reserved-key and prep handling are the
                shared pop_reserved/make_prep."""
                batch, extras, loss_kw = pop_reserved(batch)
                prep = make_prep(extras, mat=False)

                def scaled_loss(p):
                    loss = loss_fn(prep(p), batch, rng, **loss_kw)
                    return loss.astype(jnp.float32) * scale / div, loss

                return jax.value_and_grad(scaled_loss,
                                          has_aux=True)(params)

            def local_fwd_bwd(params, scale, batch, rng, we, se):
                (_, loss), grads = local_loss(params, batch, rng, scale)
                g_sync, new_we, new_se = compress_sync(grads, we, se)
                return lax.pmean(loss, ca), g_sync, new_we, new_se

            def step_onebit(params, opt_state, rest, batch, rng, lr,
                            we, se):
                state = rest.replace(params=params, opt_state=opt_state)
                # the shard_map builds INSIDE the trace so its in_specs
                # can follow the batch's structure (reserved keys
                # replicated, data leaves sharded)
                sm = shard_map(
                    local_fwd_bwd, mesh=mesh,
                    in_specs=(P(), P(), batch_specs(batch), P(), P(ca),
                              P(ca)),
                    out_specs=(P(), P(), P(ca), P(ca)),
                    check_vma=False)   # phase-2 all_gather makes
                # loss/grads replicated; the rep checker cannot prove it
                loss, grads, we, se = sm(params, state.scaler.loss_scale,
                                         batch, rng, we, se)
                new_state, metrics = apply_grads(state, grads, lr)
                return loss, new_state, metrics, we, se

            self._step_onebit = jax.jit(
                step_onebit, donate_argnums=(1, 6, 7),
                out_shardings=(None, self._state_sh, None, None, None))

            if n_micro > 1:
                # 1-bit x gradient accumulation (reference
                # fp16/onebit/adam.py:13 semantics: error feedback per
                # OPTIMIZER step): micro grads accumulate LOCALLY inside
                # the shard_map — no per-micro sync of any kind — and
                # ONE compressed allreduce fires at the boundary over
                # the accumulated grads
                def local_fwd_bwd_gasN(params, scale, batches, rng,
                                       we, se):
                    rngs = jax.random.split(rng, n_micro)
                    acc, losses = None, []
                    for i in range(n_micro):
                        b = jax.tree.map(lambda x: x[i], batches)
                        (_, loss), grads = local_loss(
                            params, b, rngs[i], scale, div=gas)
                        acc = grads if acc is None else \
                            jax.tree.map(jnp.add, acc, grads)
                        losses.append(loss)
                    g_sync, new_we, new_se = compress_sync(acc, we, se)
                    return (lax.pmean(jnp.mean(jnp.stack(losses)), ca),
                            g_sync, new_we, new_se)

                def step_onebit_gasN(params, opt_state, rest, batches,
                                     rng, lr, we, se):
                    state = rest.replace(params=params,
                                         opt_state=opt_state)
                    sm_n = shard_map(
                        local_fwd_bwd_gasN, mesh=mesh,
                        in_specs=(P(), P(),
                                  batch_specs(batches, stacked=True),
                                  P(), P(ca), P(ca)),
                        out_specs=(P(), P(), P(ca), P(ca)),
                        check_vma=False)
                    loss, grads, we, se = sm_n(
                        params, state.scaler.loss_scale, batches, rng,
                        we, se)
                    new_state, metrics = apply_grads(state, grads, lr)
                    return loss, new_state, metrics, we, se

                self._step_onebit_gasN = jax.jit(
                    step_onebit_gasN, donate_argnums=(1, 6, 7),
                    out_shardings=(None, self._state_sh, None, None,
                                   None))

    # -------------------------------------------------------------- profiling
    def module_profile(self, batch=None, depth=3, n_steps=3):
        """Per-module measured flops/bytes/latency of one train step
        (reference print_model_profile, profiler.py:23 — but from a real
        device trace: every XLA op's measured time, flop count and HBM
        bytes, attributed to its flax module path via the HLO metadata).
        Returns (records, formatted_table). Trains ``n_steps`` real
        steps on ``batch``."""
        from deepspeed_tpu.profiling.module_profiler import (
            capture_trace, format_profile)
        if batch is None:
            batch = getattr(self, "_last_batch", None)
        if batch is None:
            batch = self._example_batch
        assert batch is not None, "module_profile needs a batch"
        self._ensure_initialized(batch)

        def step():
            # a COMPLETE optimizer step per traced iteration: with
            # gradient accumulation the window's micro dispatches AND
            # the boundary apply (fp32 accumulator + Adam traffic) all
            # land inside the trace
            return self.train_batch(batches=[batch] * self.gas,
                                    sync=False)

        step()   # compile outside the trace window
        records = capture_trace(step, n_steps=n_steps)
        return records, format_profile(records, depth=depth)

    def flops_profile(self, batch=None):
        """Exact flops/bytes of one optimizer step from the compiled XLA
        executables (reference FlopsProfiler.get_total_flops — but from
        the optimizer's own post-fusion HLO, so remat and fusion are
        accounted). Returns a dict; gas>1 sums the micro dispatches."""
        from deepspeed_tpu.profiling.flops_profiler.profiler import (
            cost_analysis, params_count)
        if batch is None:
            batch = getattr(self, "_last_batch", None)
        if batch is None:
            batch = self._example_batch
        assert batch is not None, "flops_profile needs a batch before init"
        cached = getattr(self, "_flops_profile_cache", None)
        if cached is not None:
            return cached
        self._ensure_initialized(batch)
        dev_batch = self._put_batch(batch)
        rng = jax.random.PRNGKey(0)
        lr = float(self.get_lr()[0])
        state = self._live_state()
        rest = state.replace(params=None, opt_state=None)
        if self._offload is not None:
            micro = cost_analysis(self._micro_offload,
                                  self._materialize_params(state.params),
                                  jnp.float32(1.0), dev_batch, rng)
            flops = micro["flops"] * self.gas
            bytes_ = micro["bytes_accessed"] * self.gas
        elif self.gas == 1:
            c = cost_analysis(self._step_gas1, state.params,
                              state.opt_state, rest, dev_batch, rng, lr)
            flops, bytes_ = c["flops"], c["bytes_accessed"]
        else:
            first = cost_analysis(self._micro_first, state.params,
                                  state.scaler.loss_scale, dev_batch, rng)
            grads_sds = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                state.params)
            last = cost_analysis(self._step_last, state.params,
                                 state.opt_state, rest, grads_sds,
                                 dev_batch, rng, lr)
            nxt = cost_analysis(self._micro_next, state.params,
                                state.scaler.loss_scale, grads_sds,
                                dev_batch, rng)
            flops = first["flops"] + (self.gas - 2) * nxt["flops"] + \
                last["flops"]
            bytes_ = first["bytes_accessed"] + \
                (self.gas - 2) * nxt["bytes_accessed"] + \
                last["bytes_accessed"]
        n_params = params_count(state.params)
        tokens_per_step = self.gas * max(
            int(np.prod(np.shape(self._model_input(batch)))), 1)
        out = {"flops_per_step": flops, "bytes_accessed": bytes_,
               "params": n_params,
               "flops_per_token": flops / tokens_per_step}
        self._flops_profile_cache = out   # shapes are fixed per engine
        return out

    def comm_profile(self, batch=None):
        """Static HLO communication ledger of one optimizer step — the
        comm twin of :meth:`flops_profile`, reading the same compiled
        executables through the same lower->compile seam
        (``profiling/comm_ledger.py``): collective counts and
        per-device bytes per mesh axis, ICI vs DCN tier split, loop
        trip counts accounted.  gas>1 sums the micro dispatches exactly
        like the flops accounting.  Analysis-only (one extra compile
        per executable, cached per engine); it can never change tokens,
        losses or compile counts — pinned by
        ``tests/unit/test_comm_telemetry.py``."""
        from deepspeed_tpu.profiling import comm_ledger as _cl
        if batch is None:
            batch = getattr(self, "_last_batch", None)
        if batch is None:
            batch = self._example_batch
        assert batch is not None, "comm_profile needs a batch before init"
        cached = getattr(self, "_comm_profile_cache", None)
        if cached is not None:
            return cached
        self._ensure_initialized(batch)
        dev_batch = self._put_batch(batch)
        rng = jax.random.PRNGKey(0)
        lr = float(self.get_lr()[0])
        state = self._live_state()
        rest = state.replace(params=None, opt_state=None)
        mesh = self.mesh
        if self._offload is not None:
            micro = _cl.ledger_for(
                self._micro_offload,
                self._materialize_params(state.params),
                jnp.float32(1.0), dev_batch, rng, mesh=mesh)
            out = _cl.scale_ledger(micro, self.gas)
        elif self.gas == 1:
            out = _cl.ledger_for(self._step_gas1, state.params,
                                 state.opt_state, rest, dev_batch, rng,
                                 lr, mesh=mesh)
        else:
            first = _cl.ledger_for(self._micro_first, state.params,
                                   state.scaler.loss_scale, dev_batch,
                                   rng, mesh=mesh)
            grads_sds = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                state.params)
            last = _cl.ledger_for(self._step_last, state.params,
                                  state.opt_state, rest, grads_sds,
                                  dev_batch, rng, lr, mesh=mesh)
            nxt = _cl.ledger_for(self._micro_next, state.params,
                                 state.scaler.loss_scale, grads_sds,
                                 dev_batch, rng, mesh=mesh)
            out = _cl.merge_ledgers(
                [first, _cl.scale_ledger(nxt, max(self.gas - 2, 0)),
                 last])
        self._comm_profile_cache = out
        return out

    def set_tracer(self, tracer):
        """Install a host-side span tracer (None restores the shared
        no-op singleton).  Tracing is host bookkeeping only — it can
        never change tokens, losses or compile counts."""
        self.tracer = tracer if tracer is not None else NULL_TRACER

    # jitted train callables whose signature-cache sizes define "the
    # compile count" of a training run (the goodput ledger's
    # compile_warmup detector and the tracing-off parity pin both
    # consume this; mirrors the serving-side *_compile_count methods)
    _TRAIN_JIT_FNS = ("_step_gas1", "_micro_first", "_micro_next",
                      "_step_last", "_step_gasN", "_step_loop",
                      "_micro_offload", "_step_sparse_dp",
                      "_step_onebit", "_step_onebit_gasN")

    def train_compile_counts(self):
        """Compiled-signature counts per jitted train callable (only
        the ones this configuration has built).  Counts come from
        ``tracing.jit_cache_size`` — the ONE compile-count definition
        the serving engine, the goodput ledger's ``compile_warmup``
        detector and the recompile watchdog all share."""
        out = {}
        for name in self._TRAIN_JIT_FNS:
            fn = getattr(self, name, None)
            if fn is not None and hasattr(fn, "_cache_size"):
                out[name.lstrip("_")] = jit_cache_size(fn)
        return out

    def train_compile_count(self):
        """Total compiled train-step signatures (cheap per-step probe)."""
        return sum(self.train_compile_counts().values())

    def _maybe_log_flops(self):
        cfg = self._config.flops_profiler
        if not cfg.enabled or self.global_steps != cfg.profile_step:
            return
        prof = self.flops_profile()
        tflops = prof["flops_per_step"] / 1e12
        log_dist(
            f"flops_profiler @ step {self.global_steps}: "
            f"{tflops:.3f} TFLOPs/step, "
            f"{prof['params'] / 1e6:.1f}M params, "
            f"{prof['bytes_accessed'] / 1e9:.2f} GB accessed/step",
            ranks=[0])

    # ------------------------------------------------------------------ train
    def _probe_injit_materialize(self, host_params, dev_sh, host_sh):
        """True when this backend *executes* memory-space transfers of
        arrays with this param tree's shardings in BOTH directions inside
        jit — host->device for the streamed weights, device->host for the
        grad cotangents. Probes tiny stand-ins carrying each distinct
        PartitionSpec (the failure mode — "side-effect ops cannot be
        replicated" under SPMD — depends on the sharding, not the size,
        and only surfaces at execution)."""
        distinct = {}
        for sh in set(jax.tree.leaves(
                jax.tree.map(lambda s: s, dev_sh),
                is_leaf=lambda x: isinstance(x, NamedSharding))):
            # minimal shape divisible by every mesh axis in the spec
            dims = tuple(
                int(np.prod([self.mesh.shape[a] for a in
                             ((e,) if isinstance(e, str) else e)]))
                if e is not None else 1
                for e in sh.spec)
            distinct[sh] = jnp.zeros(dims or (), self.compute_dtype)
        try:
            def round_trip(ps):
                dev = [jax.device_put(p, s) for p, s in
                       zip(ps, distinct.keys())]
                return [jax.device_put(d, s.with_memory_kind("pinned_host"))
                        for d, s in zip(dev, distinct.keys())]
            host_ins = [jax.device_put(
                v, s.with_memory_kind("pinned_host"))
                for s, v in distinct.items()]
            jax.block_until_ready(jax.jit(round_trip)(host_ins))
            return True
        except Exception:
            return False

    def _fallback_to_eager_streaming(self, err):
        """Some backends accept the tiny probe but reject the real step's
        in-program memory-space moves at execution ("side-effect ops
        cannot be replicated" from the SPMD partitioner). Flip to the
        eager per-dispatch transfer once and rebuild the jitted fns."""
        if not (self._offload_param and
                getattr(self, "_injit_materialize", False)) or \
                "annotate_device_placement" not in str(err):
            return False
        log_dist("ZeRO-3 param offload: backend rejected in-program "
                 "streaming at execution; falling back to per-dispatch "
                 "transfers", ranks=[0])
        self._injit_materialize = False
        self._grad_sh = self._grad_sh_dev
        self._build_jitted_fns()
        if hasattr(self, "_eval_fn"):
            del self._eval_fn
        return True

    def _materialize_params(self, params):
        """ZeRO-3 param offload, eager-fallback path: move the pinned-host
        compute copy to HBM for one dispatch (reference fetch_sub_module,
        partitioned_param_coordinator.py:218). The transfer is async; the
        device buffers die with the dispatch's last use, so between steps
        the chip holds no parameters. When `_injit_materialize` is set the
        transfer happens inside the program instead and this is a no-op."""
        if not self._offload_param or \
                getattr(self, "_param_mat_sh", None) is None or \
                getattr(self, "_injit_materialize", False):
            return params
        return jax.device_put(params, self._param_mat_sh)

    def _live_state(self):
        """The most recent state tree with live (non-donated) buffers.

        At a GAS boundary the fused train step donates the old opt-state
        buffers at forward() dispatch; until step() commits, the
        fully-readable tree is the pending result (params stay live either
        way)."""
        if self._next_state is not None:
            return self._next_state
        if self._pending is not None and self._pending[0] == "commit":
            return self._pending[2]
        return self.state

    def _advance_random_ltd(self, batch):
        """Advance the random-LTD schedule; a new kept-token milestone
        rebuilds the jitted fns (shape constant). Returns quickly when
        the feature is off or the milestone is unchanged."""
        if self._rltd_cfg is None:
            return
        if self._rltd is None:
            from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
                RandomLTDScheduler)
            seq = int(np.shape(self._model_input(batch))[-1])
            rl = self._rltd_cfg
            # 128-aligned milestones keep the gathered subsequence on
            # the flash kernel's block grid
            default_step = 128 if seq % 128 == 0 else 16
            self._rltd = RandomLTDScheduler(
                seq_len=seq,
                start_tokens=rl.get("start_tokens"),
                schedule_steps=rl.get("schedule_steps", 1000),
                step_size=rl.get("step_size", default_step))
        keep = self._rltd.keep_tokens(self.global_steps)
        if keep >= self._rltd.seq_len:
            keep = None      # schedule complete: full sequence
        if keep != self._rltd_keep:
            self._rltd_keep = keep
            if self.state is not None:
                self._build_jitted_fns()
                log_dist(f"random-LTD milestone: keeping "
                         f"{keep or self._rltd.seq_len}/"
                         f"{self._rltd.seq_len} tokens per middle layer",
                         ranks=[0])

    def forward(self, batch, rng=None):
        """One micro batch: fused forward+backward (+optimizer apply at the
        gradient-accumulation boundary), a single jitted dispatch."""
        self._advance_random_ltd(batch)
        self._ensure_initialized(batch)
        assert self._next_state is None, \
            "step() must run before the next forward(): the previous " \
            "boundary step donated the old optimizer-state buffers"
        assert self._pending is None, \
            "backward() must run between forward() calls: forward donates " \
            "buffers that only backward() re-homes (for a loss-only pass " \
            "use eval_batch)"
        self.timers(FORWARD_GLOBAL_TIMER).start()
        self._last_batch = batch   # for flops_profile / diagnostics
        dev_batch = self._inject_reserved_keys(self._put_batch(batch))
        if rng is None:
            rng, self._rng = jax.random.split(self._rng)
        if self._offload is not None:
            # offload mode: grads ship to host in backward(), the host
            # optimizer applies in step() — the jit graph is fwd+bwd only
            scale = jnp.float32(self._offload.scaler.loss_scale)
            try:
                loss, grads = self._micro_offload(
                    self._materialize_params(self.state.params), scale,
                    dev_batch, rng)
            except jax.errors.JaxRuntimeError as e:
                if not self._fallback_to_eager_streaming(e):
                    raise
                loss, grads = self._micro_offload(
                    self._materialize_params(self.state.params), scale,
                    dev_batch, rng)
            self._pending = ("offload", loss, grads)
            self.timers(FORWARD_GLOBAL_TIMER).stop()
            return loss
        if self._compressed_axis and self.gas > 1:
            raise RuntimeError(
                "1-bit compressed sync with gradient accumulation runs "
                "through train_batch(batches=[...]) — the fused window "
                "accumulates micro grads locally and compresses ONCE at "
                "the boundary; the per-micro forward() path would psum "
                "every micro batch, defeating the compression")
        boundary = (self.micro_steps + 1) % self.gas == 0
        rest = self.state.replace(params=None, opt_state=None)
        if self.gas == 1 and self._compressed_axis:
            loss, new_state, metrics, self._onebit_we, self._onebit_se = \
                self._step_onebit(
                    self.state.params, self.state.opt_state, rest,
                    dev_batch, rng, float(self.get_lr()[0]),
                    self._onebit_we, self._onebit_se)
            self._pending = ("commit", loss, new_state, metrics)
        elif self.gas == 1 and getattr(self, "_sparse_dp", False):
            loss, new_state, metrics = self._step_sparse_dp(
                self.state.params, self.state.opt_state, rest,
                dev_batch, rng, float(self.get_lr()[0]))
            self._pending = ("commit", loss, new_state, metrics)
        elif self.gas == 1:
            loss, new_state, metrics = self._step_gas1(
                self.state.params, self.state.opt_state, rest,
                dev_batch, rng, float(self.get_lr()[0]))
            self._pending = ("commit", loss, new_state, metrics)
        elif boundary:
            loss, new_state, metrics = self._step_last(
                self.state.params, self.state.opt_state, rest,
                self._grad_acc, dev_batch, rng, float(self.get_lr()[0]))
            self._grad_acc = None
            self._pending = ("commit", loss, new_state, metrics)
        elif self.micro_steps % self.gas == 0:
            loss, acc = self._micro_first(
                self.state.params, self.state.scaler.loss_scale,
                dev_batch, rng)
            self._pending = ("acc", loss, acc)
        else:
            loss, acc = self._micro_next(
                self.state.params, self.state.scaler.loss_scale,
                self._grad_acc, dev_batch, rng)
            self._grad_acc = None
            self._pending = ("acc", loss, acc)
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss

    def backward(self, loss=None, retain_graph=False, scale_wrt_gas=True):
        """Commit the gradients (or the fused boundary result) of forward()."""
        assert self._pending is not None, \
            "backward() must follow forward() (grads are computed jointly)"
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        kind = self._pending[0]
        if kind == "acc":
            self._grad_acc = self._pending[2]
        elif kind == "offload":
            # async D2H of the (compute-dtype) grads, then host fp32
            # accumulation ON A WORKER THREAD — the main thread returns
            # immediately so the next micro batch dispatches while the
            # grads drain and accumulate (the reference's
            # async_accumulate_grad_in_cpu_via_gpu + side stream,
            # stage_1_and_2.py:1031); step() joins the queue.
            grads = self._pending[2]   # flat list; embedding leaves are
            jax.tree.map(lambda g: g.copy_to_host_async(), grads)

            def drain(ls=grads):
                t0 = time.perf_counter()
                host = []
                for g in ls:
                    if isinstance(g, tuple):
                        idx, vals, n_touched = g
                        if int(n_touched) > idx.shape[0]:
                            raise RuntimeError(
                                f"sparse_gradients: {int(n_touched)} "
                                f"rows of an embedding grad are nonzero "
                                f"but only {idx.shape[0]} fit the "
                                "sparse transfer — the table receives "
                                "dense gradient (tied lm head?); "
                                "disable sparse_gradients")
                        host.append((np.asarray(idx), np.asarray(vals)))
                    else:
                        host.append(np.asarray(g))
                self._offload.accumulate(host)
                self._offload.phase["d2h_accum_s"] += \
                    time.perf_counter() - t0
                self._offload.phase["accum_calls"] += 1

            # backpressure: each queued future pins a device grad tree;
            # bound in-flight trees to 2 (double buffer) so a long gas
            # window can't stack gas grad-sized buffers in HBM
            while len(self._offload_futs) >= 2:
                self._offload_futs.pop(0).result()
            self._offload_futs.append(self._offload_pool.submit(drain))
        else:
            self._next_state = self._pending[2]
            self._next_metrics = self._pending[3]
        self._pending = None
        self.micro_steps += 1
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size
        self.timers(BACKWARD_GLOBAL_TIMER).stop()
        return loss

    def step(self):
        """Commit the optimizer step at the gradient-accumulation boundary.

        The update itself was computed (fused with the last backward) in
        forward(); this publishes the new state and advances schedules."""
        if self.micro_steps % self.gas != 0:
            return  # mid-accumulation: nothing to do (reference no-ops too)
        if self._offload is not None:
            return self._offload_step()
        assert self._next_state is not None, \
            "step() must follow forward()+backward() at the GAS boundary"
        self.timers(STEP_GLOBAL_TIMER).start()
        # host share only: the optimizer math itself was fused into the
        # boundary dispatch — this publishes state + advances schedules
        with self.tracer.span("optimizer_step", cat="train",
                              args={"step": self.global_steps}):
            self.state = self._next_state
            metrics = self._next_metrics
            self._next_state = None
            self._next_metrics = None
            lr = float(self.get_lr()[0])  # the lr this step was taken with
            self.global_steps += 1
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self._last_metrics = metrics
            self._maybe_update_moq()
        self.timers(STEP_GLOBAL_TIMER).stop()
        self._maybe_log_flops()

        if self.monitor.enabled and self.global_steps % \
                self._config.steps_per_print == 0:
            m = jax.device_get(metrics)
            self.monitor.write_events(
                [("Train/Samples/lr", lr, self.global_samples),
                 ("Train/Samples/loss_scale", float(m["loss_scale"]),
                  self.global_samples)])
        return metrics

    def _inject_reserved_keys(self, dev_batch, n_micro=None):
        """Add the compression/pld reserved keys to a device batch
        (fwd_bwd pops them): scalars for the per-micro path, stacked
        [n_micro, ...] for the fused window so the per-micro slice
        ``x[i]`` works. One theta/strength set per optimizer step,
        matching the reference's per-boundary updates."""
        if self._compression is None and \
                self.progressive_layer_drop is None:
            return dev_batch
        assert isinstance(dev_batch, dict), \
            "compression/pld need dict batches (reserved keys ride the " \
            "batch into the jitted step)"
        dev_batch = dict(dev_batch)
        if self.progressive_layer_drop is not None:
            theta = self.progressive_layer_drop.update_state(
                self.global_steps)
            dev_batch["_ds_pld_theta"] = jnp.float32(theta) \
                if n_micro is None else jnp.full((n_micro,), theta,
                                                 jnp.float32)
        if self._compression is not None:
            vec = self._compression.strength_vector(self.global_steps)
            # while every group is still inactive (pre-offset) skip the
            # key entirely: comp.apply would sort/quantize every matched
            # kernel only to return it unchanged. The structure change
            # costs one recompile when the schedule activates.
            if np.any(vec):
                vec = jnp.asarray(vec)
                dev_batch["_ds_comp"] = vec if n_micro is None else \
                    jnp.tile(vec, (n_micro, 1))
        return dev_batch

    def _maybe_update_moq(self):
        """At a gas boundary: recompute MoQ eigenvalue factors every
        ``gas_boundary_resolution`` boundaries."""
        self._gas_boundary_ctr += 1
        if self.eigenvalue is not None and self._compression is not None \
                and self._gas_boundary_ctr % \
                self.eigenvalue.gas_boundary_resolution == 0:
            self._update_moq_eigenvalues()

    def _update_moq_eigenvalues(self):
        """MoQ: per-group Hessian max-eigenvalues stretch each
        weight-quantization group's period, so high-curvature parameters
        quantize slower (reference engine.py:2014-2026 computing
        block_eigenvalue at gas boundaries + quantize.py:70 factor)."""
        import flax.traverse_util
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
        wq = [gi for gi, g in enumerate(self._compression.groups)
              if g[0] == "weight_quantization"]
        if not wq or self._last_batch is None:
            return
        batch = self._put_batch(self._last_batch)
        params = self._live_state().params
        if self._offload is not None and \
                getattr(self, "_param_mat_sh", None) is not None:
            # ZeRO-3 param offload: power-iterate on a device copy (the
            # pinned-host at-rest tree can't feed the jitted HVP on
            # backends without in-program memory-space moves)
            params = jax.device_put(params, self._param_mat_sh)

        # STABLE loss identity across boundaries/groups: the batch rides
        # extra_args so the eigenvalue's jitted power step caches
        if not hasattr(self, "_eig_loss"):
            self._eig_loss = lambda p, b: self.loss_fn(p, b, None)

        flat = flax.traverse_util.flatten_dict(params, sep="/")
        keys, vals = list(flat.keys()), list(flat.values())
        evs = []
        rng = jax.random.PRNGKey(self.global_steps)
        for gi in wq:
            # masks are TRANSIENT device fills (freed after the group's
            # power iteration — caching them would pin groups x
            # model-size of HBM), in the param dtype so the bf16
            # tangents aren't promoted inside jvp
            posset = set(self._compression.groups[gi][4])
            mask = flax.traverse_util.unflatten_dict(
                {k: ((jnp.ones if i in posset else jnp.zeros)(
                    jnp.shape(v), jnp.asarray(v).dtype))
                 for i, (k, v) in enumerate(zip(keys, vals))}, sep="/")
            ev, _ = self.eigenvalue.compute_eigenvalue(
                self._eig_loss, params, rng=rng, mask=mask,
                extra_args=(batch,))
            evs.append(ev)
        normed = Eigenvalue.normalize_eigenvalues(evs)
        self._compression.set_eigenvalue_factors(dict(zip(wq, normed)))
        log_dist(f"MoQ eigenvalues (normalized): "
                 f"{dict(zip(wq, [round(v, 3) for v in normed]))}",
                 ranks=[0])

    def _join_offload(self):
        """Drain the grad-accumulation worker queue (exceptions surface
        here). The measured wait is the portion of the D2H/accumulate
        work NOT hidden behind device compute."""
        futs, self._offload_futs = self._offload_futs, []
        t0 = time.perf_counter()
        # the host-visible share of grad sync in offload mode: D2H +
        # fp32 accumulate not hidden behind device compute
        with self.tracer.span("grad_sync", cat="train", track="device",
                              args={"joined": len(futs)}):
            for f in futs:
                f.result()
        if self._offload is not None:
            self._offload.phase.setdefault("join_stall_s", 0.0)
            self._offload.phase["join_stall_s"] += \
                time.perf_counter() - t0

    def offload_phase_stats(self):
        """Per-phase wall-time breakdown since the last call (ZeRO-
        Offload instrumentation; bench embeds it). ``overlap_fraction``
        = share of the D2H+accumulate host work hidden behind device
        compute (1 - join_stall / d2h_accum)."""
        if self._offload is None:
            return {}
        st = self._offload.pop_phase_stats()
        if self._offload.param_tier is not None:
            tier = self._offload.param_tier.pop_stats()
            st.update({f"param_tier_{k}": v for k, v in tier.items()})
            adam = st.get("host_adam_s", 0.0)
            # share of the NVMe leaf-state reads hidden behind the
            # previous leaf's Adam update (prefetch-next-leaf pipeline)
            st["nvme_prefetch_overlap"] = round(
                max(1.0 - tier["nvme_wait_s"] / adam, 0.0), 4) \
                if adam else None
        d2h = st.get("d2h_accum_s", 0.0)
        stall = st.get("join_stall_s", 0.0)
        st["overlap_fraction"] = round(max(1.0 - stall / d2h, 0.0), 4) \
            if d2h else None
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in st.items()}

    def _offload_step(self):
        """Boundary step in ZeRO-Offload mode: host Adam over the
        accumulated grads, then push the new compute-dtype params back.
        Each leaf's H2D starts (async) the moment its host update
        finishes, so the DMA of leaf i overlaps the Adam of leaf i+1 and
        total time ~ max(host step, transfer), not the sum."""
        self.timers(STEP_GLOBAL_TIMER).start()
        self._join_offload()
        _t_opt = time.monotonic()
        lr = float(self.get_lr()[0])
        if self._params_nvme:
            # ZeRO-Infinity param tier: the sweep rewrites the NVMe
            # files in place; state.params (memmap views) read the new
            # bytes at the next dispatch — nothing to emit or rebuild
            _, metrics = self._offload.step(lr)
            self.state = self.state.replace(
                step=self.state.step + 1,
                skipped_steps=jnp.int32(self._offload.skipped_steps))
        else:
            emit_bf16 = self.compute_dtype == jnp.bfloat16
            if emit_bf16:
                import ml_dtypes

                def put_leaf(i, flat_u16):
                    return jax.device_put(
                        flat_u16.view(ml_dtypes.bfloat16),
                        self._param_sh_flat[i])
                put, metrics = self._offload.step(lr, on_leaf=put_leaf)
            else:
                dt = np.dtype(self.compute_dtype)

                def put_leaf(i, _leaf):
                    arr = self._offload.master[i].reshape(
                        self._offload.shapes[i]).astype(dt)
                    return jax.device_put(arr, self._param_sh_flat[i])
                put, metrics = self._offload.step(lr, on_leaf=put_leaf)
            new_params = jax.tree_util.tree_unflatten(self._param_treedef,
                                                      put)
            self.state = self.state.replace(
                params=new_params, step=self.state.step + 1,
                skipped_steps=jnp.int32(self._offload.skipped_steps))
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._last_metrics = metrics
        self._maybe_update_moq()
        # the host Adam sweep + H2D push IS the optimizer step here
        self.tracer.complete("optimizer_step", _t_opt, time.monotonic(),
                             cat="train",
                             args={"step": self.global_steps,
                                   "offload": True})
        self.timers(STEP_GLOBAL_TIMER).stop()
        self._maybe_log_flops()
        if self.monitor.enabled and self.global_steps % \
                self._config.steps_per_print == 0:
            self.monitor.write_events(
                [("Train/Samples/lr", lr, self.global_samples),
                 ("Train/Samples/loss_scale", float(metrics["loss_scale"]),
                  self.global_samples)])
        return metrics

    def train_batch(self, data_iter=None, batches=None, sync=True):
        """Full step: GAS micro-batches -> one optimizer step. Returns mean
        loss. With gas>1 and the whole window's data in hand, the fused
        single-dispatch step runs instead of gas separate dispatches
        (identical math: same fp32 accumulation and boundary apply).
        ``sync=False`` returns the loss as a device scalar without
        blocking on the transfer.

        NOTE: the fused window DONATES the previous params buffers (they
        alias the new tree in place). A reference obtained via
        ``engine.get_params()`` / ``engine.state.params`` BEFORE the call
        is dead afterwards — re-read it from ``engine.state`` after the
        window (the per-micro forward()/backward()/step() path does not
        donate params and has no such hazard)."""
        assert data_iter is not None or batches is not None or \
            self.training_dataloader is not None
        # fault point: raise / sleep / SIGTERM-self on an exact step —
        # the step about to run (global_steps is pre-increment here)
        fstep = self.global_steps
        faults.fire("train.step", step=fstep)
        if data_iter is None and batches is None:
            data_iter = iter(self.training_dataloader)
        tr = self.tracer
        if batches is None and self.gas > 1:
            with tr.span("data_load", cat="train", track="data",
                         args={"n_micro": self.gas, "step": fstep}):
                batches = [next(data_iter) for _ in range(self.gas)]
        if batches is not None:
            # init BEFORE deciding on the fused path: initialization is
            # what instantiates the offload optimizer that rules it out
            self._ensure_initialized(batches[0])
        if self._can_fuse_window():
            return faults.transform(
                "train.loss", self._train_batch_fused(batches, sync=sync),
                step=fstep)
        losses = []
        self.tput_timer.start()
        for i in range(self.gas):
            if batches is not None:
                batch = batches[i]
            else:
                with tr.span("data_load", cat="train", track="data",
                             args={"micro": i, "step": fstep}):
                    batch = next(data_iter)
            # one span per micro dispatch; gas>1 gets per-micro tracks
            # so the accumulation window reads as parallel timeline rows
            with tr.span("fwd_bwd_dispatch", cat="train",
                         track=f"micro{i}" if self.gas > 1 else "scheduler",
                         args={"micro": i, "step": fstep}):
                loss = self.forward(batch)
                self.backward(loss)
            losses.append(loss)
        metrics = self.step()
        self.tput_timer.stop(global_step=True)
        if not sync and self.global_steps % \
                self._config.steps_per_print != 0:
            # window-mean as a device scalar; no host round trip (same
            # metric the fused path reports)
            return faults.transform("train.loss",
                                    jnp.mean(jnp.stack(losses)), step=fstep)
        with tr.span("device_wait", cat="train", track="device",
                     args={"step": fstep}):
            mean_loss = float(np.mean([jax.device_get(l) for l in losses]))
        self._log_train_step(mean_loss, metrics)
        # fault transform: force a NaN loss on an exact step so the
        # supervisor's divergence watchdog is testable end to end
        return faults.transform("train.loss", mean_loss, step=fstep)

    def _log_train_step(self, mean_loss, metrics):
        """THE steps_per_print train-step log + monitor events (shared by
        the fused and micro train_batch paths so the emitted fields can't
        drift apart)."""
        if self.global_steps % self._config.steps_per_print != 0:
            return
        m = jax.device_get(metrics) if metrics else {}
        lr = float(self.get_lr()[0])
        log_dist(f"step={self.global_steps} loss={mean_loss:.4f} "
                 f"lr={lr:.3e} "
                 f"loss_scale={float(m.get('loss_scale', 1.0)):.0f} "
                 f"grad_norm={float(m.get('grad_norm', 0.0)):.3f}",
                 ranks=[0])
        if self.monitor.enabled:
            self.monitor.write_events(
                [("Train/Samples/train_loss", mean_loss,
                  self.global_samples),
                 ("Train/Samples/lr", lr, self.global_samples),
                 ("Train/Samples/loss_scale",
                  float(m.get("loss_scale", 1.0)), self.global_samples)])

    def _can_fuse_window(self):
        """The scan-fused window applies when a full, aligned window is
        in hand and state lives on device (offload mode accumulates on
        the host instead)."""
        return self.gas > 1 and self._offload is None and \
            self._pending is None and self._next_state is None and \
            self.micro_steps % self.gas == 0

    def _stack_batches(self, batches):
        """Stack gas micro batches along a new leading axis, sharded by
        the per-micro batch rule (_batch_sharding) shifted one axis."""
        stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
        base = self._batch_sharding(batches[0])
        return jax.tree.map(
            lambda x, s: jax.device_put(
                jnp.asarray(x), NamedSharding(self.mesh, P(None, *s.spec))),
            stacked, base)

    def _train_batch_fused(self, batches, sync=True):
        assert len(batches) == self.gas, \
            f"need {self.gas} micro batches, got {len(batches)}"
        self._advance_random_ltd(batches[0])
        self._ensure_initialized(batches[0])
        if not self._can_fuse_window():
            # state became engine-managed mid-window; fall back
            raise RuntimeError("fused window requires an aligned boundary")
        self.tput_timer.start()
        self._last_batch = batches[0]
        tr = self.tracer
        # the whole fused window (fwd+bwd+optimizer apply, grad sync
        # fused inside the XLA program) is ONE async dispatch: batch
        # staging + launch is the host's share; the blocking fetch below
        # is the device's
        fused_span = tr.span("fwd_bwd_dispatch", cat="train",
                             args={"gas": self.gas, "fused": True,
                                   "step": self.global_steps})
        with fused_span:
            dev = self._inject_reserved_keys(self._stack_batches(batches),
                                             n_micro=self.gas)
            rng, self._rng = jax.random.split(self._rng)
            if self._compressed_axis:
                mean_loss_dev, new_state, metrics, self._onebit_we, \
                    self._onebit_se = self._step_onebit_gasN(
                        self.state.params, self.state.opt_state,
                        self.state.replace(params=None, opt_state=None),
                        dev, rng, float(self.get_lr()[0]),
                        self._onebit_we, self._onebit_se)
            else:
                mean_loss_dev, new_state, metrics = self._step_gasN(
                    self.state.params, self.state.opt_state,
                    self.state.replace(params=None, opt_state=None),
                    dev, rng, float(self.get_lr()[0]))
        self.state = new_state
        self.micro_steps += self.gas
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size * self.gas
        self.global_steps += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._last_metrics = metrics
        self._maybe_update_moq()
        self.tput_timer.stop(global_step=True)
        self._maybe_log_flops()
        if sync or self.global_steps % self._config.steps_per_print == 0:
            with tr.span("device_wait", cat="train", track="device",
                         args={"step": self.global_steps}):
                mean_loss_host = float(jax.device_get(mean_loss_dev))
            if self.global_steps % self._config.steps_per_print == 0:
                self._log_train_step(mean_loss_host, metrics)
        # sync=False returns the device scalar (async): a float() fetch
        # per step costs a full host round trip on relayed devices
        return mean_loss_host if sync else mean_loss_dev

    def train_loop(self, batches, sync=False):
        """Run ``len(batches) // gas`` complete optimizer steps in a
        SINGLE jitted dispatch — a lax.scan over full train steps (over
        fused gas windows when gas > 1). Identical math to calling
        forward()/backward()/step() per micro batch; what changes is host
        cost: one dispatch amortizes the per-call overhead (arg
        marshaling + runtime round trip) over the whole span. The old
        state is donated, like the fused gas window.

        Returns the per-window mean losses as a device array ([K],
        async) unless ``sync=True``. PLD / compression / MoQ / 1-bit /
        offload schedules advance per engine-driven step, so they
        require the per-step APIs.
        """
        assert len(batches) % self.gas == 0, \
            f"train_loop needs whole windows: {len(batches)} micro " \
            f"batches with gas={self.gas}; with partial windows use " \
            "train_batch"
        # init BEFORE the composition gates: initialization is what
        # instantiates the offload optimizer / compression runtime the
        # gates check (same ordering rationale as train_batch)
        self._ensure_initialized(batches[0])
        assert self._offload is None and not self._compressed_axis, \
            "train_loop does not compose with host offload or 1-bit sync"
        assert self._compression is None and \
            self.progressive_layer_drop is None and \
            self.eigenvalue is None and self._rltd_cfg is None, \
            "compression/PLD/MoQ/random-LTD schedules advance per " \
            "engine step; drive those through forward()/backward()/step()"
        assert self._pending is None and self._next_state is None, \
            "train_loop cannot start mid-step (pending forward state)"
        assert not getattr(self, "_sparse_dp", False), \
            "sparse_gradients' shard_map grad sync does not ride the " \
            "scan-fused train_loop yet; drive it through " \
            "forward()/backward()/step()"
        k = len(batches) // self.gas
        self.tput_timer.start()
        self._last_batch = batches[0]
        if self.gas == 1:
            dev = self._stack_batches(batches)
        else:
            # [K, gas, ...]: scan axis over windows, unrolled micro axis
            stacked = jax.tree.map(
                lambda *xs: np.stack(xs).reshape(
                    (k, self.gas) + np.shape(xs[0])), *batches)
            base = self._batch_sharding(batches[0])
            dev = jax.tree.map(
                lambda x, s: jax.device_put(
                    jnp.asarray(x),
                    NamedSharding(self.mesh, P(None, None, *s.spec))),
                stacked, base)
        rngs = jax.random.split(self._rng, k + 1)
        self._rng = rngs[0]
        lrs = []
        for _ in range(k):   # the loop really takes k steps: advance the
            lrs.append(float(self.get_lr()[0]))     # schedule as it goes
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
        losses, new_state, metrics = self._step_loop(
            self.state.params, self.state.opt_state,
            self.state.replace(params=None, opt_state=None),
            dev, rngs[1:], jnp.asarray(lrs, jnp.float32))
        self.state = new_state
        self.micro_steps += k * self.gas
        self.global_steps += k
        self.global_samples += self.train_micro_batch_size_per_gpu() * \
            self.dp_world_size * k * self.gas
        self._last_metrics = metrics
        self.tput_timer.stop(global_step=True, steps=k)
        self._maybe_log_flops()
        if self.global_steps % self._config.steps_per_print == 0:
            self._log_train_step(float(jax.device_get(losses[-1])), metrics)
        return jax.device_get(losses) if sync else losses

    def eval_batch(self, batch, _retried=False):
        """Loss-only forward (no grads). Compression-aware training
        evaluates the COMPRESSED model (same strengths the train step
        uses) — validation tracks the network redundancy_clean will
        bake, not the raw fp weights. PLD evaluates at full depth
        (theta=1 semantics), matching the reference."""
        self._ensure_initialized(batch)
        if not hasattr(self, "_eval_fn"):
            loss_fn = self.loss_fn
            compute_dtype = self.compute_dtype
            comp = self._compression
            mat_sh = self._param_mat_sh \
                if getattr(self, "_injit_materialize", False) else None

            def ev(params, batch):
                if mat_sh is not None:
                    params = jax.tree.map(jax.device_put, params, mat_sh)
                p = jax.tree.map(
                    lambda x: x.astype(compute_dtype)
                    if x.dtype == jnp.float32 and compute_dtype != jnp.float32
                    else x, params)
                if isinstance(batch, dict) and "_ds_comp" in batch:
                    batch = dict(batch)
                    p = comp.apply(p, batch.pop("_ds_comp"))
                return loss_fn(p, batch, None)

            self._eval_fn = jax.jit(ev)
        dev_batch = self._put_batch(batch)
        if self._compression is not None:
            vec = self._compression.strength_vector(self.global_steps)
            if np.any(vec):
                assert isinstance(dev_batch, dict)
                dev_batch = dict(dev_batch)
                dev_batch["_ds_comp"] = jnp.asarray(vec)
        try:
            return jax.block_until_ready(self._eval_fn(
                self._materialize_params(self._live_state().params),
                dev_batch))
        except jax.errors.JaxRuntimeError as e:
            if _retried or not self._fallback_to_eager_streaming(e):
                raise
            return self.eval_batch(batch, _retried=True)

    # ------------------------------------------------------------------- io
    def deepspeed_io(self, dataset, collate_fn=None, route="train"):
        de = self._config.data_efficiency or {}
        ds_cfg = de.get("data_sampling", {}) if de.get("enabled") else {}
        if route == "train" and ds_cfg.get("enabled") and \
                ds_cfg.get("curriculum_learning", {}).get("enabled"):
            # data-efficiency v2: difficulty-indexed curriculum sampling
            # (reference data_sampler.py:36, wired at engine.py:1561).
            # Single-controller JAX: the sampler emits the GLOBAL micro
            # batch (dp_rank 0 of 1); the jitted step shards it over the
            # data axis. Sampler state rides in the checkpoint for exact
            # mid-epoch resume.
            from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
                CurriculumIndexLoader, DeepSpeedDataSampler)
            sampler = DeepSpeedDataSampler(
                de, one_epoch_total_samples=len(dataset),
                micro_batch_size=self.train_micro_batch_size_per_gpu()
                * self.dp_world_size,
                gradient_accumulation_steps=self.gas,
                drop_last=self._config.dataloader_drop_last)
            if self._data_sampler_state is not None:
                sampler.load_state_dict(self._data_sampler_state)
                self._data_sampler_state = None
            self._data_sampler = sampler
            return CurriculumIndexLoader(dataset, sampler,
                                         collate_fn=collate_fn)
        return DeepSpeedDataLoader(
            dataset,
            batch_size=self.train_micro_batch_size_per_gpu() * self.dp_world_size,
            collate_fn=collate_fn,
            drop_last=self._config.dataloader_drop_last)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, async_save=False):
        """Reference layout (engine.py:2818): <dir>/<tag>/ + `latest` file.
        Each process writes only its own shards (reference per-rank
        ``*_optim_states.pt``); ``async_save`` drains to disk on a
        background thread (the Nebula-engine capability) — call
        ``wait_checkpoint()`` before relying on the files. The backend
        is pluggable (checkpoint/backend.py, reference
        checkpoint_engine.py:9): ``checkpoint_engine.type`` in the
        config swaps the native npz format for a custom engine."""
        assert self.state is not None, "nothing to save before first forward"
        if async_save and self._params_nvme:
            # state.params are live memmap views over the tier's NVMe
            # files; a background writer racing the next step's in-place
            # file rewrite would snapshot a torn mix of two steps
            logger.warning("async_save is unavailable with the NVMe "
                           "param tier (params are live file views); "
                           "saving synchronously")
            async_save = False
        tag = tag or f"global_step{self.global_steps}"
        path = os.path.join(save_dir, str(tag))
        client = dict(client_state or {})
        client.update({
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "global_samples": self.global_samples,
            "lr_scheduler": self.lr_scheduler.state_dict()
            if isinstance(self.lr_scheduler, LRScheduler) else None,
            "data_sampler": self._data_sampler.state_dict()
            if self._data_sampler is not None else None,
            "compression": self._compression.state_dict()
            if self._compression is not None else None,
        })
        self.wait_checkpoint()

        if self._offload is not None:
            self._join_offload()   # grads in flight mutate the snapshot
            # fp32 master + moments live host-side (reference per-rank
            # *_optim_states.pt). Written NOW, synchronously, THROUGH
            # the backend (the pluggable-engine seam — a Nebula-style
            # backend must see every artifact): the offload optimizer
            # mutates its buffers in place on the next step, and the
            # entry stream reads one leaf at a time, so the
            # ZeRO-Infinity tier never materializes a model-sized dict.
            if jax.process_index() == 0:
                os.makedirs(path, exist_ok=True)
                self.checkpoint_engine.save_aux(
                    path, "host_optim_states",
                    self._offload.iter_state_entries())

        def finalize():
            # save_state runs on_done on PROCESS 0 ONLY, after the
            # durability barrier — single writer for everything below
            if self._config.zero_config \
                    .stage3_gather_16bit_weights_on_model_save:
                # reference engine.py:754: emit one unpartitioned 16-bit
                # weights file next to the sharded checkpoint (shard files
                # are durable here — finalize runs after the barrier);
                # routed through the backend so a remote engine owns it
                self.checkpoint_engine.consolidate_16bit(
                    path, "weights_16bit.npz", dtype=np.float16)
            if save_latest:
                with open(os.path.join(save_dir, "latest"), "w") as f:
                    f.write(str(tag))
            self.checkpoint_engine.commit(tag)

        self.checkpoint_engine.create(tag)
        self._ckpt_writer = self.checkpoint_engine.save(
            path, self._live_state(), client, async_write=async_save,
            on_done=finalize)
        log_dist(f"saved checkpoint {path}", ranks=[0])
        return path

    def wait_checkpoint(self):
        """Join any in-flight async checkpoint write."""
        writer = getattr(self, "_ckpt_writer", None)
        if writer is not None:
            self._ckpt_writer = None  # a failed write must not wedge retries
            writer.wait()

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True, example_batch=None):
        self.wait_checkpoint()
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                logger.warning(f"no 'latest' file in {load_dir}; nothing loaded")
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        if self.state is None:
            batch = example_batch if example_batch is not None \
                else self._example_batch
            assert batch is not None, \
                "load_checkpoint before init needs example_batch"
            self._ensure_initialized(batch)
        self.state, client = self.checkpoint_engine.load(
            path, self.state, mesh=self.mesh)
        have_host_opt = False
        if self._offload is not None:
            with self.checkpoint_engine.load_aux(
                    path, "host_optim_states") as d:
                have_host_opt = d is not None
                if d is not None and load_optimizer_states:
                    # lazy mapping: load_state_dict pulls one entry at
                    # a time (the tier streams each straight to NVMe)
                    self._offload.load_state_dict(d)
            if have_host_opt and not load_optimizer_states:
                # params are authoritative: refresh the master from them
                from deepspeed_tpu.checkpoint.engine import param_leaf_names
                self._offload.init_master(
                    (np.asarray(jax.device_get(l))
                     for l in jax.tree.leaves(self.state.params)),
                    names=param_leaf_names(self.state.params))
        if self._params_nvme:
            if not have_host_opt:
                # checkpoint without host optimizer state: the restored
                # params are authoritative — rebuild the tier from them
                from deepspeed_tpu.checkpoint.engine import \
                    param_leaf_names
                self._offload.init_master(
                    (np.asarray(l)
                     for l in jax.tree.leaves(self.state.params)),
                    names=param_leaf_names(self.state.params))
            # the restore materialized plain arrays; re-point
            # state.params at the tier's (just-refreshed) memmap views
            # so dispatches stream from NVMe again
            self.state = self.state.replace(
                params=jax.tree_util.tree_unflatten(
                    self._param_treedef,
                    self._offload.param_tier.param_memmaps()))
        self.global_steps = client.get("global_steps", 0)
        self.micro_steps = client.get("micro_steps", 0)
        self.global_samples = client.get("global_samples", 0)
        if load_lr_scheduler_states and client.get("lr_scheduler") and \
                isinstance(self.lr_scheduler, LRScheduler):
            self.lr_scheduler.load_state_dict(client["lr_scheduler"])
        if client.get("data_sampler") is not None:
            # restore into the live sampler, or stash for the sampler a
            # later deepspeed_io() builds
            if self._data_sampler is not None:
                self._data_sampler.load_state_dict(client["data_sampler"])
            else:
                self._data_sampler_state = client["data_sampler"]
        if client.get("compression") is not None and \
                self._compression is not None:
            self._compression.load_state_dict(client["compression"])
        log_dist(f"loaded checkpoint {path}", ranks=[0])
        return path, client

    def load_universal_checkpoint(self, path, example_batch=None,
                                  load_optimizer_states=True):
        """Resume TRAINING from a universal checkpoint — per-param fp32
        fragments produced by ``ds_to_universal`` from either a native
        checkpoint or a foreign Megatron tp/pp one (reference
        universal_checkpoint.py:12 + reshape_3d_utils.py: re-slice any
        source partitioning for training resume). Each fragment is
        device_put straight onto the live leaf's sharding, so the
        current mesh/ZeRO stage needs no reshape logic; Adam moments
        load when the source carried them (else the optimizer starts
        fresh, reference load_universal semantics for param-only
        sources)."""
        from deepspeed_tpu.checkpoint.engine import param_leaf_names
        from deepspeed_tpu.checkpoint.universal import load_universal
        self.wait_checkpoint()   # an in-flight async writer reads the
        # live offload buffers this load mutates in place
        if self.state is None:
            batch = example_batch if example_batch is not None \
                else self._example_batch
            assert batch is not None, \
                "load_universal_checkpoint before init needs example_batch"
            self._ensure_initialized(batch)
        meta, frags, moments = load_universal(path)
        names = param_leaf_names(self.state.params)
        missing = [n for n in names if n not in frags]
        if missing:
            raise KeyError(
                f"universal checkpoint at {path} lacks fragments for "
                f"{missing[:5]}{'...' if len(missing) > 5 else ''} "
                f"(has {len(frags)} leaves)")
        leaves = jax.tree.leaves(self.state.params)
        treedef = jax.tree.structure(self.state.params)
        new_leaves = []
        for name, live in zip(names, leaves):
            frag = frags[name]
            if tuple(np.shape(frag)) != tuple(np.shape(live)):
                raise ValueError(
                    f"fragment {name} has shape {np.shape(frag)} but the "
                    f"live leaf is {np.shape(live)}")
            if self._offload is not None:
                new_leaves.append(frag)
            else:
                new_leaves.append(jax.device_put(
                    np.asarray(frag, jax.dtypes.canonicalize_dtype(
                        live.dtype)), live.sharding))
        if self._offload is not None:
            # masters refresh from the fragments; compute copies rebuild
            self._offload.init_master(iter(new_leaves), names=names)
            if self._params_nvme:
                self.state = self.state.replace(
                    params=jax.tree_util.tree_unflatten(
                        self._param_treedef,
                        self._offload.param_tier.param_memmaps()))
            else:
                put = [jax.device_put(
                    np.asarray(l, np.dtype(self.compute_dtype)
                               if self.compute_dtype != jnp.bfloat16
                               else "bfloat16"), s)
                    for l, s in zip(new_leaves, self._param_sh_flat)]
                self.state = self.state.replace(
                    params=jax.tree_util.tree_unflatten(
                        self._param_treedef, put))
            if load_optimizer_states and self._offload.nvme is not None:
                for i, n in enumerate(names):
                    if moments.get(n) is not None:
                        self._offload.nvme.writeback(
                            i, np.ascontiguousarray(moments[n][0]),
                            np.ascontiguousarray(moments[n][1]))
                self._offload.nvme.flush()
            elif load_optimizer_states and self._offload.moments:
                for i, n in enumerate(names):
                    if moments.get(n) is not None:
                        self._offload.moments[i][0][:] = \
                            moments[n][0].reshape(-1)
                        self._offload.moments[i][1][:] = \
                            moments[n][1].reshape(-1)
        else:
            params = jax.tree_util.tree_unflatten(treedef, new_leaves)
            opt_state = self.state.opt_state
            if load_optimizer_states and any(
                    m is not None for m in moments.values()):
                mu = jax.tree_util.tree_unflatten(
                    treedef, [moments[n][0] if moments.get(n) is not None
                              else np.zeros_like(frags[n])
                              for n in names])
                nu = jax.tree_util.tree_unflatten(
                    treedef, [moments[n][1] if moments.get(n) is not None
                              else np.zeros_like(frags[n])
                              for n in names])
                opt_state = self._inject_adam_moments(
                    opt_state, mu, nu,
                    count=int(meta.get("global_steps", 0)))
            self.state = self.state.replace(params=params,
                                            opt_state=opt_state)
        self.global_steps = int(meta.get("global_steps", 0))
        if self._offload is not None:
            # Adam bias correction must continue from the source's step
            # (t=1 would scale the loaded moments ~1/(1-beta) wrong)
            self._offload.step_count = self.global_steps
        if self.lr_scheduler is not None and \
                hasattr(self.lr_scheduler, "step"):
            # fast-forward the schedule to the restored step — a
            # universal source carries no scheduler state (it may come
            # from a different framework), but replaying warmup on a
            # converged model is strictly worse
            try:
                self.lr_scheduler.step(self.global_steps)
            except TypeError:   # client scheduler without increment arg
                for _ in range(self.global_steps):
                    self.lr_scheduler.step()
        log_dist(f"loaded universal checkpoint {path} "
                 f"({len(names)} fragments, source="
                 f"{meta.get('source', 'native')})", ranks=[0])
        return meta

    def _inject_adam_moments(self, opt_state, mu, nu, count=0):
        """Replace the ScaleByAdamState mu/nu trees (optax chain walk)
        and advance its bias-correction count, preserving shardings."""
        import optax

        def put_like(new, old):
            return jax.device_put(
                np.asarray(new, old.dtype),
                old.sharding if hasattr(old, "sharding") else None)

        found = [0]

        def walk(node):
            if isinstance(node, optax.ScaleByAdamState):
                found[0] += 1
                return node._replace(
                    count=jax.device_put(
                        jnp.asarray(count, node.count.dtype),
                        getattr(node.count, "sharding", None)),
                    mu=jax.tree.map(put_like, mu, node.mu),
                    nu=jax.tree.map(put_like, nu, node.nu))
            if isinstance(node, tuple) and not hasattr(node, "_fields"):
                return tuple(walk(c) for c in node)
            if hasattr(node, "_fields"):   # other NamedTuple states
                return type(node)(*(walk(c) for c in node))
            return node

        new = walk(opt_state)
        if not found[0]:
            logger.warning(
                "load_universal_checkpoint: the source carries Adam "
                "moments but no optax ScaleByAdamState was found in "
                "this optimizer's state (wrapped/custom optimizer?) — "
                "optimizer state starts FRESH")
            return opt_state
        if jax.tree.structure(new) == jax.tree.structure(opt_state):
            return new
        logger.warning(
            "load_universal_checkpoint: rebuilding the optimizer state "
            "around the loaded Adam moments changed its tree structure "
            "— moments DISCARDED, optimizer state starts FRESH")
        return opt_state

    # ------------------------------------------------------------------ misc
    def get_params(self):
        return self._live_state().params if self.state is not None else None

    def __call__(self, batch):
        return self.forward(batch)
