"""Multi-host autotuning experiment scheduler.

Reference: ``deepspeed/autotuning/scheduler.py:33`` ``ResourceManager``
— a queue of experiment configs assigned to free nodes, launched as
training subprocesses, each writing a ``metrics.json`` the tuner
collects; finished experiments are skipped on re-run (resumability).

TPU shape: hosts are TPU-VM workers (or pod slices) reachable by ssh —
or ``localhost`` slots for single-host parallelism across chips. Each
experiment materializes as ``exp_<i>.json`` under ``exps_dir``; the
user's training command runs with ``{config}``/``{result_dir}``
substituted and must write ``{result_dir}/metrics.json`` with
``{"metric": <float>}`` (the engine-side convention: measure a few
steps, dump throughput). The in-process :class:`Autotuner` remains the
fast path for one chip; this scheduler is the fan-out for sweeps whose
trials each need a whole slice.
"""

import json
import os
import signal
import subprocess
import time

from deepspeed_tpu.utils.logging import logger


class Experiment:
    def __init__(self, exp_id, name, config, exps_dir, results_dir):
        self.exp_id = exp_id
        self.name = name
        self.config = config
        self.path = os.path.join(exps_dir, f"exp_{exp_id}.json")
        # exp_id in the dir: duplicate names must not share results
        # (stable across re-runs given the same candidate order)
        self.result_dir = os.path.join(results_dir, f"{exp_id}_{name}")
        self.proc = None
        self.host = None
        self.stderr_fh = None

    @property
    def metrics_path(self):
        return os.path.join(self.result_dir, "metrics.json")

    def finished_metric(self):
        if os.path.exists(self.metrics_path):
            try:
                with open(self.metrics_path) as f:
                    return json.load(f).get("metric")
            except (ValueError, OSError):
                return None   # partial write (killed trial): unfinished
        return None


class ExperimentScheduler:
    """Run experiment configs across hosts, one at a time per host.

    ``hosts``: list of ssh-able hostnames; ``localhost`` entries run as
    plain subprocesses (repeat an entry for more concurrent slots).
    ``cmd_template``: the training command with ``{config}`` and
    ``{result_dir}`` placeholders.
    """

    def __init__(self, hosts=None, exps_dir="autotuning_exps",
                 results_dir="autotuning_results", poll_interval=0.2,
                 timeout_per_exp=3600.0):
        self.hosts = list(hosts or ["localhost"])
        self.exps_dir = exps_dir
        self.results_dir = results_dir
        self.poll_interval = poll_interval
        self.timeout_per_exp = timeout_per_exp
        self.experiments = []

    def schedule(self, candidates):
        """candidates: [(name_or_overrides, config_dict), ...] ->
        persisted experiment files (reference schedule_experiments)."""
        os.makedirs(self.exps_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        for i, (name, cfg) in enumerate(candidates):
            if not isinstance(name, str):
                name = "exp_" + "_".join(
                    f"{k.split('.')[-1]}{v}" for k, v in sorted(
                        dict(name).items()))
            exp = Experiment(i, name, cfg, self.exps_dir, self.results_dir)
            with open(exp.path, "w") as f:
                json.dump({"exp_id": i, "name": name, "config": cfg}, f,
                          indent=2)
            self.experiments.append(exp)
        return self.experiments

    def _launch(self, exp, host, cmd_template):
        os.makedirs(exp.result_dir, exist_ok=True)
        cmd = cmd_template.format(config=exp.path,
                                  result_dir=exp.result_dir)
        if host in ("localhost", "127.0.0.1"):
            argv = ["/bin/sh", "-c", cmd]
        else:
            # same transport the multinode launcher uses for TPU-VM
            # workers (launcher/multinode_runner.py ssh/pdsh family).
            # The REMOTE side enforces the deadline too: killing the
            # local ssh client would leave a hung trial holding the
            # slice while the host is handed to the next experiment.
            remote = f"timeout {int(self.timeout_per_exp)}s {cmd}"
            argv = ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]
        exp.stderr_fh = open(os.path.join(exp.result_dir, "stderr.log"),
                             "w")
        # new session => the whole process GROUP can be killed on
        # timeout; killing just the /bin/sh wrapper would orphan the
        # trial, which keeps holding the chip while the host slot is
        # reused and corrupts the next experiment's measurement
        exp.proc = subprocess.Popen(argv, stdout=exp.stderr_fh,
                                    stderr=exp.stderr_fh,
                                    start_new_session=True)
        exp.host = host
        exp.t0 = time.time()
        logger.info(f"autotuning exp {exp.name} -> {host}")

    def run(self, cmd_template):
        """Drain the queue over the host pool; returns (results, best)
        where results is sorted best-first (successful trials by metric
        descending, then failures)."""
        queue = []
        results = []
        for exp in self.experiments:
            m = exp.finished_metric()
            if m is not None:   # resumability: skip completed trials
                logger.info(f"autotuning exp {exp.name}: cached {m}")
                results.append({"exp_id": exp.exp_id, "name": exp.name,
                                "metric": m, "cached": True})
            else:
                queue.append(exp)
        free = list(self.hosts)
        running = []
        while queue or running:
            while queue and free:
                exp = queue.pop(0)
                self._launch(exp, free.pop(0), cmd_template)
                running.append(exp)
            for exp in list(running):
                rc = exp.proc.poll()
                if rc is None:
                    if time.time() - exp.t0 > self.timeout_per_exp + 10:
                        try:   # kill the group, not just the shell
                            os.killpg(os.getpgid(exp.proc.pid),
                                      signal.SIGKILL)
                        except (ProcessLookupError, PermissionError):
                            exp.proc.kill()
                        rc = exp.proc.wait()   # reap (no zombie)
                    else:
                        continue
                running.remove(exp)
                if exp.stderr_fh is not None:
                    exp.stderr_fh.close()
                    exp.stderr_fh = None
                free.append(exp.host)
                m = exp.finished_metric()
                if rc != 0 or m is None:
                    logger.warning(
                        f"autotuning exp {exp.name} failed (rc={rc}); "
                        f"see {exp.result_dir}/stderr.log")
                    results.append({"exp_id": exp.exp_id,
                                    "name": exp.name, "error": rc})
                else:
                    results.append({"exp_id": exp.exp_id,
                                    "name": exp.name, "metric": m,
                                    "host": exp.host})
            time.sleep(self.poll_interval)
        ok = [r for r in results if "metric" in r]
        if not ok:
            raise RuntimeError("autotuning: every experiment failed")
        ok.sort(key=lambda r: -r["metric"])
        results = ok + [r for r in results if "metric" not in r]
        best = next(e for e in self.experiments
                    if e.exp_id == ok[0]["exp_id"])
        with open(os.path.join(self.results_dir, "summary.json"),
                  "w") as f:
            json.dump({"results": results, "best": ok[0]["name"]}, f,
                      indent=2)
        return results, best
