from deepspeed_tpu.autotuning.autotuner import Autotuner  # noqa: F401
from deepspeed_tpu.autotuning.cost_model import FirstOrderCostModel  # noqa: F401
from deepspeed_tpu.autotuning.scheduler import ExperimentScheduler  # noqa: F401
from deepspeed_tpu.autotuning.serving import (MIX_PRESETS,  # noqa: F401
                                              OnlineTuner,
                                              ServingAutotuner,
                                              ServingCostModel,
                                              TrafficMix,
                                              ds_serve_args,
                                              load_mix)
