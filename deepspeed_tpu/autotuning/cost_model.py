"""First-order autotuning cost model: prune the grid before measuring.

Reference: ``deepspeed/autotuning/tuner/cost_model.py:1`` (XGBoost
fitted on measured trials) + ``tuner/model_based_tuner.py:58``
(estimate, measure only the predicted-top configs). The TPU redesign is
ANALYTIC rather than learned: a roofline throughput bound and a
first-order memory model are computable from the candidate config and
model dimensions alone — no measurements needed before pruning, and the
estimates calibrate against the first measured trial (the measured /
predicted ratio carries over to the survivors' ranking).

Memory model (per chip, bytes):
  master+moments fp32: 12 N / dp     (ZeRO stage >= 1 shards it)
  compute params bf16:  2 N          (stage 3 shards: / dp)
  grads fp32:           4 N          (stage >= 2 shards: / dp)
  activations:          A * micro * seq * hidden * layers
with everything optimizer-side dropped when offload_optimizer is on.

Throughput bound: min(flops_per_step / peak_flops,
                      bytes_per_step / hbm_bw) per optimizer step.
"""

from deepspeed_tpu.utils.logging import logger

_ACT_BYTES_PER_TOKEN_PER_LAYER = 34   # bf16 tensors/blk (measured gpt2)


class FirstOrderCostModel:
    def __init__(self, n_params, hidden, num_layers, seq,
                 device_memory=16e9, peak_flops=197e12, hbm_gbps=700.0,
                 dp=1):
        self.n = int(n_params)
        self.hidden = hidden
        self.layers = num_layers
        self.seq = seq
        self.device_memory = device_memory
        self.peak_flops = peak_flops
        self.hbm_bw = hbm_gbps * 1e9
        self.dp = max(dp, 1)

    def _knob(self, cfg, dotted, default):
        node = cfg
        for k in dotted.split("."):
            if not isinstance(node, dict) or k not in node:
                return default
            node = node[k]
        return node

    def estimate(self, cfg):
        micro = int(self._knob(cfg, "train_micro_batch_size_per_gpu", 1))
        gas = int(self._knob(cfg, "gradient_accumulation_steps", 1))
        stage = int(self._knob(cfg, "zero_optimization.stage", 0))
        off_opt = self._knob(
            cfg, "zero_optimization.offload_optimizer", None) is not None
        shard = self.dp if stage >= 1 else 1
        g_shard = self.dp if stage >= 2 else 1
        p_shard = self.dp if stage >= 3 else 1

        n = self.n
        mem = 2 * n / p_shard                    # bf16 compute copy
        if off_opt:
            # ZeRO-Offload: fp32 master/moments AND the fp32 grad
            # accumulators live on host (zero/offload.py); the chip only
            # holds transient compute-dtype grads in flight
            mem += 2 * n / g_shard
        else:
            mem += 12 * n / shard                # fp32 master + m + v
            mem += 4 * n / g_shard               # fp32 grads/accumulator
        act = (_ACT_BYTES_PER_TOKEN_PER_LAYER * micro * self.seq
               * self.hidden * self.layers)
        mem += act

        tokens = micro * gas * self.seq * self.dp
        flops = 6 * n * tokens
        # bytes: weights touched ~3x fwd/bwd + optimizer pass + acts 2x
        bytes_ = (6 * n + (0 if off_opt else 16 * n) + 2 * act * gas)
        t_step = max(flops / (self.peak_flops * self.dp),
                     bytes_ / (self.hbm_bw * self.dp))
        if off_opt:
            # host link round trip dominates offload configs; model it
            # as 2N bf16 over a nominal 10 GB/s host link
            t_step = max(t_step, 4 * n / 10e9)
        return {"memory_bytes": mem, "tokens_per_sec": tokens / t_step,
                "fits": mem < self.device_memory}

    def prune(self, candidates, top_k=None):
        """candidates: [(overrides, cfg), ...] -> (kept, dropped_records).
        Drops predicted-OOM configs outright; with ``top_k`` keeps only
        the top-k by predicted throughput (measurement order = ranked)."""
        scored = []
        dropped = []
        for ov, cfg in candidates:
            est = self.estimate(cfg)
            if not est["fits"]:
                dropped.append({"overrides": ov, "pruned": "memory",
                                "estimate": est})
                continue
            scored.append((est["tokens_per_sec"], ov, cfg, est))
        scored.sort(key=lambda t: -t[0])
        if top_k is not None and len(scored) > top_k:
            for s in scored[top_k:]:
                dropped.append({"overrides": s[1], "pruned": "ranked_out",
                                "estimate": s[3]})
            scored = scored[:top_k]
        logger.info(f"cost model: measuring {len(scored)} of "
                    f"{len(scored) + len(dropped)} candidates")
        return [(ov, cfg, est) for _, ov, cfg, est in scored], dropped
