"""Profile-guided serving config search over the live scorecard.

``ServingAutotuner`` is the seed :class:`~deepspeed_tpu.autotuning.
Autotuner`'s generate-experiments -> measure -> pick-best flow
(reference ``deepspeed/autotuning/autotuner.py``) re-targeted at the
serving tier:

* candidates come from a knob grid over the serving config space
  (pages, page size, horizon, spec mode/K, prefix cache split,
  overlap) — the seed ``candidates()`` generator unchanged;
* the :class:`~deepspeed_tpu.autotuning.serving.cost_model.
  ServingCostModel` prunes analytically-infeasible combos (never
  measured — constructing them would raise) and ranks the rest, so
  only the predicted-top ``measure_top_k`` pay a measurement;
* measurement drives a REAL ``ServingScheduler`` in-process against
  the deterministic load the :class:`~deepspeed_tpu.autotuning.
  serving.traffic.TrafficMix` derives from its seed — same mix + same
  seed means every candidate serves a byte-identical request stream;
* trials run with one untimed warmup replay (compiles every signature
  off the clock) and INTERLEAVED timed repeats, best-of per candidate
  — the PR-8 bench methodology, so rig drift cannot masquerade as a
  knob effect;
* every measured/pruned/failed trial persists through the seed
  ``_persist`` path (merge-into-existing, PR-4 style), and the result
  carries a predicted-vs-measured table plus the Spearman rank
  correlation between the cost model's ranking and the measured one —
  the number ``perf_floor.py`` and the acceptance test pin.
"""

import time

import numpy as np

from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.serving.cost_model import (DEFAULT_KNOBS,
                                                         ServingCostModel)
from deepspeed_tpu.utils.logging import logger

__all__ = ["ServingAutotuner", "DEFAULT_SERVING_SPACE", "ds_serve_args",
           "rank_correlation"]

# the default search grid: small enough to measure on a CPU rig, wide
# enough to cover the knobs that actually move the committed numbers.
# bin/ds_tune --space replaces it wholesale.
DEFAULT_SERVING_SPACE = {
    "decode_horizon_steps": [1, 4, 8],
    "prefix_cache": [False, True],
    "num_pages": [64, 128],
    # the paged-pool dtype is a per-scheduler knob, so trials vary it
    # on the one engine; the analytic pruner prices its bytes-per-page
    # (an int8 candidate fits ~2-4x the pages in a byte budget).
    # weight_dtype is deliberately NOT searched — it is engine state,
    # priced + emitted as a ds_serve flag instead.
    "kv_dtype": ["float32", "int8"],
    # long-context prefill knobs (PR 18): the chunk width, and whether
    # prompts above the threshold route through sequence-parallel
    # prefill.  On a mesh with no sequence axis the threshold candidate
    # prices identically to 0 (the cost model's prefill term gates on
    # the live `sequence_axis_size` signal), so it never costs a
    # measurement slot there.
    "prefill_chunk": [16, 32],
    "seq_parallel_threshold": [0, 256],
}


def _average_ranks(values):
    """Ranks with TIES AVERAGED (the true Spearman convention):
    ordinal argsort-of-argsort ranks would assign tied scores
    arbitrary position-dependent ranks, making the correlation depend
    on candidate enumeration order — two identically-predicted
    candidates must not flip the honesty figure on measurement
    noise."""
    x = np.asarray(values, np.float64)
    order = np.argsort(x, kind="stable")
    ranks = np.empty(len(x), np.float64)
    xs = x[order]
    i = 0
    while i < len(x):
        j = i
        while j + 1 < len(x) and xs[j + 1] == xs[i]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0
        i = j + 1
    return ranks


def rank_correlation(predicted, measured):
    """Spearman rank correlation between two equal-length score lists
    (predicted vs measured tokens/s over the searched candidates),
    ties averaged.  None with fewer than 2 points or a degenerate
    (constant) side."""
    if len(predicted) != len(measured):
        raise ValueError("predicted and measured must pair up")
    if len(predicted) < 2:
        return None
    if np.std(predicted) == 0 or np.std(measured) == 0:
        return None       # a constant side has no ranking to correlate
    pr = _average_ranks(predicted)
    mr = _average_ranks(measured)
    return float(np.corrcoef(pr, mr)[0, 1])


def ds_serve_args(knobs):
    """The ``bin/ds_serve`` flag line equivalent to a tuned knob dict
    (``ds_tune --emit-ds-serve-args`` prints it)."""
    k = ServingCostModel.complete(knobs)
    parts = [
        f"--num-slots {k['num_slots']}",
        f"--num-pages {k['num_pages']}",
        f"--page-size {k['page_size']}",
        f"--max-pages-per-slot {k['max_pages_per_slot']}",
        f"--prefill-chunk {k['prefill_chunk']}",
        f"--decode-horizon {k['decode_horizon_steps']}",
    ]
    if not k["overlap"]:
        parts.append("--no-overlap")
    parts.append("--prefix-cache" if k["prefix_cache"]
                 else "--no-prefix-cache")
    if k["prefix_cache"] and k["prefix_cache_pages"] is not None:
        parts.append(f"--prefix-cache-pages {k['prefix_cache_pages']}")
    mode = k["spec_decode"]
    parts.append(f"--spec-decode {mode if mode not in (None, False) else 'off'}")
    if mode not in (None, False, "off"):
        parts.append(f"--spec-k {k['spec_k']}")
    if k["seq_parallel_threshold"]:
        parts.append(
            f"--seq-parallel-threshold {k['seq_parallel_threshold']}")
    if k["prefill_reserve_frac"] is not None:
        parts.append(
            f"--prefill-reserve-frac {k['prefill_reserve_frac']}")
    if k["kv_dtype"] not in (None, "float32"):
        parts.append(f"--kv-dtype {k['kv_dtype']}")
    if k["weight_dtype"] is not None:
        parts.append(f"--weight-dtype {k['weight_dtype']}")
    if int(k["num_adapters"]) > 0:
        # synthetic roster mirroring the tuner's own measurement rig;
        # swap in real .npz paths for deployment.  --tenants is the
        # operator's file — entitlements/quotas are policy, not knobs.
        roster = ",".join(
            f"a{i}=random:{k['adapter_rank']}:{i}"
            for i in range(int(k["num_adapters"])))
        parts.append(f"--lora {roster} --tenants tenants.json")
    return " ".join(parts)


class ServingAutotuner(Autotuner):
    """Measured search over serving knob candidates for one traffic
    mix.  ``search(engine)`` returns the tuned-config dict;
    ``measure_fn`` is injectable for tests (``(engine, knobs) ->
    tokens_per_sec``) — the default drives a real scheduler."""

    def __init__(self, mix, tuning_space=None, cost_model=None,
                 measure_top_k=4, repeats=2, warmup=1, max_trials=32,
                 results_path=None, max_steps=200000, measure_fn=None,
                 base_knobs=None):
        cost_model = cost_model if cost_model is not None \
            else ServingCostModel(mix)
        # base_knobs overrides the scheduler-default baseline for the
        # knobs the space does NOT search (e.g. a bench comparing
        # default vs tuned at a pinned max_pages_per_slot must search
        # FROM that default, or the 'win' credits an unsearched knob)
        base = dict(DEFAULT_KNOBS)
        if base_knobs:
            unknown = set(base_knobs) - set(DEFAULT_KNOBS)
            if unknown:
                raise ValueError(
                    f"unknown base knobs: {sorted(unknown)}")
            base.update(base_knobs)
        super().__init__(
            base_config=base,
            tuning_space=dict(tuning_space or DEFAULT_SERVING_SPACE),
            metric="tokens_per_sec", warmup_steps=warmup,
            measure_steps=repeats, max_trials=max_trials,
            cost_model=cost_model, prune_top_k=measure_top_k,
            results_path=results_path)
        self.mix = mix
        self.repeats = max(1, int(repeats))
        self.warmup = max(0, int(warmup))
        self.max_steps = int(max_steps)
        self.measure_fn = measure_fn or self._measure_real

    # ------------------------------------------------------- measurement
    def _measure_real(self, engine, knobs):
        """One timed replay of the mix's deterministic load through a
        fresh ServingScheduler built from ``knobs``; returns tokens/s.
        The load replays open-loop against the wall clock exactly like
        ``benchmarks/serving_bench.run_continuous`` (arrivals gate
        submission), minus the retry machinery — the tuner sizes the
        queue to the whole batch."""
        from deepspeed_tpu.serving import ServingScheduler
        k = ServingCostModel.complete(knobs)
        mix = self.mix
        sampled_mode = mix.greedy_fraction < 1.0
        tenancy, adapters = None, []
        if int(k["num_adapters"]) > 0:
            # one tenant entitled to a synthetic full-coverage roster:
            # the trial measures the multi-LoRA decode path (per-slot
            # gather + delta einsums at this rank bucket) under the
            # same mix, with requests striped across the roster + base
            if sampled_mode:
                raise ValueError(
                    "num_adapters > 0 needs a greedy mix: multi-LoRA "
                    "serving rides the greedy decode path")
            from deepspeed_tpu.serving.tenancy import (
                AdapterStore, TenantConfig, TenantRegistry,
                random_adapter)
            mcfg = engine.module.cfg
            store = AdapterStore(mcfg)
            for i in range(int(k["num_adapters"])):
                store.add(f"a{i}", random_adapter(
                    mcfg, int(k["adapter_rank"]), seed=i))
            adapters = store.names() + [None]
            tenancy = TenantRegistry(
                [TenantConfig("tuner", adapters=tuple(store.names()),
                              page_quota=k["tenant_page_quota"])],
                adapter_store=store)
        sched = ServingScheduler(
            engine, num_slots=k["num_slots"], num_pages=k["num_pages"],
            page_size=k["page_size"],
            max_pages_per_slot=k["max_pages_per_slot"],
            prefill_chunk=k["prefill_chunk"],
            seq_parallel_threshold=k["seq_parallel_threshold"],
            prefill_reserve_frac=k["prefill_reserve_frac"],
            decode_horizon_steps=k["decode_horizon_steps"],
            overlap=k["overlap"], prefix_cache=k["prefix_cache"],
            prefix_cache_pages=k["prefix_cache_pages"],
            spec_decode=k["spec_decode"], spec_k=k["spec_k"],
            kv_dtype=k["kv_dtype"],
            # a mixed-temperature mix serves sampled (the scheduler's
            # sampling is loop-level; spec disables itself there)
            do_sample=sampled_mode, temperature=0.7 if sampled_mode
            else 1.0, max_queue=mix.requests + 1, tenancy=tenancy)
        vocab = engine.module.cfg.vocab_size
        prompts, max_new, arrivals, _ = mix.generate(vocab)
        t0 = time.monotonic()
        pending = list(zip(prompts, max_new, arrivals))
        submitted = []
        steps = 0
        while True:
            now = time.monotonic() - t0
            while pending and pending[0][2] <= now:
                p, m, _ = pending.pop(0)
                tkw = {} if tenancy is None else {
                    "tenant": "tuner",
                    "adapter": adapters[len(submitted) % len(adapters)]}
                submitted.append(sched.submit(p, max_new_tokens=m,
                                              **tkw))
            if not sched.step():
                if not pending:
                    break
                time.sleep(max(pending[0][2] -
                               (time.monotonic() - t0), 0.0))
            steps += 1
            if steps >= self.max_steps:
                raise RuntimeError(
                    f"trial exceeded max_steps={self.max_steps}")
        wall = time.monotonic() - t0
        toks = sum(len(r.out_tokens) for r in submitted)
        return toks / wall if wall > 0 else 0.0

    # ------------------------------------------------------------ search
    def search(self, engine):
        """Rank -> prune -> measure -> pick: returns the tuned-config
        dict (knobs, predicted + measured scorecard, the
        predicted-vs-measured table, rank correlation, provenance)."""
        kept, dropped = self.cost_model.prune(
            list(self.candidates()), top_k=self.prune_top_k)
        self.results.extend(dropped)
        kept = kept[:self.max_trials]
        if not kept:
            raise RuntimeError(
                "serving autotuner: every candidate was pruned "
                "infeasible for this mix — widen the space or shrink "
                "the mix's worst-case request")
        # untimed warmup replays: every signature a candidate can hit
        # compiles off the clock (horizon/spec-K buckets, COW copy,
        # batched sampling shapes).  A candidate that fails at RUNTIME
        # despite passing the analytic feasibility check (e.g. a pool
        # the device cannot actually allocate) is recorded and dropped
        # — the seed tuner's record-and-skip contract — instead of
        # aborting the whole search
        warmed = []
        for ov, cfg, est in kept:
            try:
                for _ in range(self.warmup):
                    self.measure_fn(engine, cfg)
            except Exception as e:
                logger.warning(f"serving autotuner: candidate {ov} "
                               f"failed in warmup: "
                               f"{type(e).__name__}: {e}")
                self.results.append({"overrides": ov, "error": str(e)})
                continue
            warmed.append((ov, cfg, est))
        kept = warmed
        if not kept:
            raise RuntimeError("serving autotuner: every measured "
                               "trial failed")
        # interleaved timed repeats (off/on/off/on generalized to N
        # candidates): rig drift lands evenly across candidates instead
        # of on whichever measured last; best-of per candidate since
        # the served work is deterministic and only the rig clock is
        # noisy
        samples = [[] for _ in kept]
        t_search0 = time.monotonic()
        for _ in range(self.repeats):
            for i, (ov, cfg, _) in enumerate(kept):
                t0 = time.monotonic()
                try:
                    samples[i].append(
                        (self.measure_fn(engine, cfg),
                         time.monotonic() - t0))
                except Exception as e:
                    logger.warning(f"serving autotuner: trial {ov} "
                                   f"failed: {type(e).__name__}: {e}")
                    self.results.append({"overrides": ov,
                                         "error": str(e)})
        table = []
        for (ov, cfg, est), ss in zip(kept, samples):
            if not ss:
                continue
            best = max(s[0] for s in ss)
            rec = {"overrides": ov,
                   "metric": round(best, 2),
                   "predicted": est["tokens_per_sec"],
                   "predicted_ttft_ms": est["ttft_ms"],
                   "samples": [round(s[0], 2) for s in ss],
                   "trial_seconds": round(sum(s[1] for s in ss), 3)}
            self.results.append(rec)
            table.append(rec)
        if not table:
            raise RuntimeError("serving autotuner: every measured "
                               "trial failed")
        corr = rank_correlation([r["predicted"] for r in table],
                                [r["metric"] for r in table])
        best = max(table, key=lambda r: r["metric"])
        tuned = {
            "knobs": ServingCostModel.complete(
                {**self.base_config, **best["overrides"]}),
            "overrides": best["overrides"],
            "predicted_tokens_per_sec": best["predicted"],
            "measured_tokens_per_sec": best["metric"],
            "rank_correlation": None if corr is None else round(corr, 4),
            "mix": self.mix.to_dict(),
            "space": {k: list(v) for k, v in self.space.items()},
            "measured": len(table),
            "pruned_infeasible": sum(
                1 for d in dropped if d.get("pruned") == "infeasible"),
            "pruned_ranked_out": sum(
                1 for d in dropped if d.get("pruned") == "ranked_out"),
            "search_seconds": round(time.monotonic() - t_search0, 3),
            # tuning provenance: the serving knob space is PER-TOPOLOGY
            # (per-device pool bytes, collective costs and slot
            # sharding all change with the mesh shape), so the tuned
            # config records the mesh it was measured on and ds_serve
            # --tuned-config refuses to apply it on a different shape
            # (None under an injected measure_fn with no real engine —
            # ds_serve only enforces the check when the field is set)
            "mesh_shape": None if getattr(engine, "mesh", None) is None
            else ({a: int(s) for a, s in engine.mesh.shape.items()
                   if int(s) > 1} or {"data": 1}),
            "table": table,
            # the flag line must describe THE SAME config as "knobs" —
            # overrides alone would complete against the library
            # defaults and contradict a non-default base_config
            "ds_serve_args": ds_serve_args(
                {**self.base_config, **best["overrides"]}),
        }
        self._persist()
        logger.info(
            f"serving autotuner: winner {best['overrides']} at "
            f"{best['metric']:.1f} tok/s (predicted "
            f"{best['predicted']:.1f}; rank corr {corr})")
        return tuned
