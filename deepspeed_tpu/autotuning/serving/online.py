"""Online serving tuner: bounded nudges from the live gauge stream.

The measured search (``search.py``) picks a config for a DECLARED mix;
the :class:`OnlineTuner` handles the traffic the declaration missed.
It watches the gauges the scheduler already maintains — pool free
fraction, preemptions, prefix-cache drains, tokens/s — and nudges ONLY
the knobs the scheduler already re-resolves safely mid-run:

* ``decode_horizon_steps`` — one bucket down under pool pressure (the
  same ladder ``_reserve`` shrinks along, applied proactively), one
  bucket back up after sustained health.  Values stay inside the
  bucket set compiled at construction, so a nudge can never add a jit
  signature.
* ``spec_k`` — the speculation budget ceiling, same bucket ladder
  (the per-request adaptive K already converges under it).
* ``prefix_cache_pages`` — the retention split: under pressure the cap
  steps down and the surplus refcount-free pages drain back to the
  free list NOW (the scheduler's own reclaim path); after sustained
  health the cap steps back toward its configured value.

Safety contract: every nudged knob rides an existing re-resolve path
whose token-exactness the oracle suites already pin — greedy output is
invariant to horizon and spec-K choices (``test_serving_horizon`` /
``test_spec_decode``), and cache retention only changes WHERE KV comes
from, never what it spells.  So an online-nudged run is token-exact vs
``generate()`` by construction; ``tests/unit/test_serving_autotune.py``
re-proves it under forced churn with ``audit_every=1``.

Every decision is observable: a ``serving/tune/nudge`` monitor event
plus the per-knob gauge (``serving/tune/<knob>``), a ``tune_nudge``
tracer instant, and a bounded host-side log — nothing moves silently.

Hysteresis: shrinks fire immediately on a pressured window (capacity
incidents are expensive); grows wait for ``grow_patience`` consecutive
healthy windows, and any nudge starts a ``hold``-window cooldown on
its knob so the controller cannot oscillate at window cadence.
"""

import time
from collections import deque

__all__ = ["OnlineTuner"]


class OnlineTuner:
    """Bounded-step online controller over a live ``ServingScheduler``.

    Constructed standalone and handed to
    ``ServingScheduler(online_tuner=...)``; the scheduler calls
    :meth:`on_step` at barrier steps (host-authoritative state only —
    a chained overlap step's view is stale by design).  One instance
    per scheduler, enforced at bind like ``MemTelemetry``.
    """

    def __init__(self, interval=8, low_free_frac=0.125,
                 high_free_frac=0.5, grow_patience=3, hold=2,
                 cache_step_frac=0.125, min_cache_pages=1,
                 max_nudge_log=256):
        self.interval = max(1, int(interval))
        self.low_free_frac = float(low_free_frac)
        self.high_free_frac = float(high_free_frac)
        self.grow_patience = max(1, int(grow_patience))
        self.hold = max(0, int(hold))
        self.cache_step_frac = float(cache_step_frac)
        self.min_cache_pages = int(min_cache_pages)
        self.nudges = deque(maxlen=int(max_nudge_log))
        self.nudge_count = 0
        self._sched = None
        # bind-time ceilings: a grow never exceeds the configured
        # config (and never leaves the compiled bucket sets)
        self._max_horizon = None
        self._max_spec_k = None
        self._max_cache_pages = None
        self._steps = 0
        self._healthy_windows = 0
        self._cooldown = {}          # knob -> windows remaining
        self._last = None            # previous window's counters
        self._tokens_per_s = None    # EWMA over windows

    @property
    def enabled(self):
        return True

    # ---------------------------------------------------------- binding
    def bind(self, sched):
        if self._sched is not None:
            raise ValueError(
                "this OnlineTuner instance is already bound to another "
                "scheduler; pass online_tuner=True (or a fresh "
                "instance) per scheduler")
        self._sched = sched
        self._max_horizon = sched.decode_horizon_steps
        self._max_spec_k = sched.spec_k
        pc = sched.prefix_cache
        self._max_cache_pages = None if pc is None else pc.max_pages
        self._last = self._counters(sched)

    def _counters(self, sched):
        m = sched.metrics
        return {"t": time.monotonic(),
                "tokens": m.tokens_emitted,
                "preemptions": m.preemptions,
                "cache_evictions": m.cache_evictions,
                "pressure": m.mem_pressure_events}

    # ----------------------------------------------------------- nudging
    def _record(self, sched, knob, value, reason):
        self.nudge_count += 1
        self.nudges.append((sched.step_idx, knob, value, reason))
        sched.metrics.record_tune(sched.step_idx, knob, value)
        if sched.tracer.enabled:
            sched.tracer.instant("tune_nudge", cat="tune",
                                 args={"knob": knob, "value": value,
                                       "reason": reason})
        self._cooldown[knob] = self.hold

    def _bucket_down(self, buckets, cur):
        below = [b for b in buckets if b < cur]
        return below[-1] if below else cur

    def _bucket_up(self, buckets, cur, cap):
        above = [b for b in buckets if cur < b <= cap]
        return above[0] if above else cur

    def _shrink(self, sched, reason):
        """One bounded shrink on the first non-held knob of the ladder:
        cache retention first (reclaimable capacity, zero service
        impact), then speculation budget, then horizon."""
        pc = sched.prefix_cache
        if pc is not None and not self._cooldown.get(
                "prefix_cache_pages"):
            step = max(1, int(self.cache_step_frac *
                              sched.kv.pool.num_pages))
            target = max(self.min_cache_pages, pc.max_pages - step)
            if target < pc.max_pages:
                pc.max_pages = target
                surplus = pc.cached_pages - target
                if surplus > 0:
                    # drain the surplus NOW through the scheduler's own
                    # reclaim path (refcount-free pages only — a shared
                    # page survives under its readers)
                    sched._reclaim_cached(surplus)
                self._record(sched, "prefix_cache_pages", target, reason)
                return True
        if sched._spec is not None and sched.spec_k > 1 and \
                not self._cooldown.get("spec_k"):
            new_k = self._bucket_down(sched.spec_k_buckets, sched.spec_k)
            if new_k < sched.spec_k:
                sched.spec_k = new_k
                self._record(sched, "spec_k", new_k, reason)
                return True
        if sched.decode_horizon_steps > 1 and \
                not self._cooldown.get("decode_horizon"):
            new_h = self._bucket_down(sched.horizon_buckets,
                                      sched.decode_horizon_steps)
            if new_h < sched.decode_horizon_steps:
                sched.decode_horizon_steps = new_h
                self._record(sched, "decode_horizon", new_h, reason)
                return True
        return False

    def _grow(self, sched):
        """One bounded grow back toward the configured config, reverse
        ladder order (horizon first — it carries the throughput)."""
        if sched.decode_horizon_steps < self._max_horizon and \
                not self._cooldown.get("decode_horizon"):
            new_h = self._bucket_up(sched.horizon_buckets,
                                    sched.decode_horizon_steps,
                                    self._max_horizon)
            if new_h > sched.decode_horizon_steps:
                sched.decode_horizon_steps = new_h
                self._record(sched, "decode_horizon", new_h, "recovered")
                return True
        if sched._spec is not None and \
                sched.spec_k < self._max_spec_k and \
                not self._cooldown.get("spec_k"):
            new_k = self._bucket_up(sched.spec_k_buckets, sched.spec_k,
                                    self._max_spec_k)
            if new_k > sched.spec_k:
                sched.spec_k = new_k
                self._record(sched, "spec_k", new_k, "recovered")
                return True
        pc = sched.prefix_cache
        if pc is not None and self._max_cache_pages is not None and \
                pc.max_pages < self._max_cache_pages and \
                not self._cooldown.get("prefix_cache_pages"):
            step = max(1, int(self.cache_step_frac *
                              sched.kv.pool.num_pages))
            target = min(self._max_cache_pages, pc.max_pages + step)
            pc.max_pages = target
            self._record(sched, "prefix_cache_pages", target, "recovered")
            return True
        return False

    # ------------------------------------------------------------- hook
    def on_step(self, sched):
        """Barrier-step hook (the scheduler calls this; chained overlap
        steps never do).  Every ``interval`` barrier steps: read the
        window's gauges, classify it pressured/healthy, apply at most
        ONE bounded nudge."""
        self._steps += 1
        if self._steps % self.interval:
            return
        for knob in list(self._cooldown):
            if self._cooldown[knob] > 0:
                self._cooldown[knob] -= 1
        cur = self._counters(sched)
        last, self._last = self._last, cur
        dt = max(1e-9, cur["t"] - last["t"])
        rate = (cur["tokens"] - last["tokens"]) / dt
        self._tokens_per_s = rate if self._tokens_per_s is None \
            else 0.5 * self._tokens_per_s + 0.5 * rate
        free_frac = sched.kv.pool.free_pages / sched.kv.pool.num_pages
        pressured = (
            free_frac < self.low_free_frac or
            cur["preemptions"] > last["preemptions"] or
            cur["pressure"] > last["pressure"])
        if pressured:
            self._healthy_windows = 0
            self._shrink(sched,
                         "pressure" if free_frac >= self.low_free_frac
                         else f"free_frac={free_frac:.3f}")
            return
        if free_frac >= self.high_free_frac and \
                cur["cache_evictions"] == last["cache_evictions"]:
            self._healthy_windows += 1
            if self._healthy_windows >= self.grow_patience:
                if self._grow(sched):
                    self._healthy_windows = 0
        else:
            self._healthy_windows = 0

    # ------------------------------------------------------------ export
    def summary(self):
        return {
            "nudges": self.nudge_count,
            "tokens_per_s_ewma": None if self._tokens_per_s is None
            else round(self._tokens_per_s, 2),
            "recent": [{"step": s, "knob": k, "value": v, "reason": r}
                       for s, k, v, r in list(self.nudges)[-16:]],
        }
