"""Serving cost model: rank knob candidates before measuring any.

The Vidur (MLSys '24) shape — simulation/cost-guided config search
instead of exhaustive measurement — built from signals this repo
already commits and exports:

* **Horizon amortization curve** — fit to the committed
  ``horizon_sweep`` section of ``benchmarks/serving_results_cpu.json``.
  The family is the amortization law itself, ``R(h) = R_inf * h /
  (h + a)`` (one dispatch's host round-trip amortized over ``h``
  tokens), least-squares fit in the linearized ``1/R = 1/R_inf +
  (a/R_inf)/h`` space.  The fitted curve is monotone in ``h`` by
  construction (pinned by tests/unit/test_serving_autotune.py) —
  individual sweep points are rig-noisy, the law is not.
* **Prefix-cache term** — the committed ``prefix_share.shared``
  speedup (4.03x at 92% shared-token fraction) scaled linearly by the
  mix's shared-token fraction; zero when the cache is off, when the
  retention cap cannot hold the shared prefix's page chain, or when
  the mix has no shared structure.
* **Speculation term** — the committed ``spec_decode`` speedup (1.59x
  at K=32 on the motif mix) scaled by where the candidate's K sits
  between the break-even point (a verify round costs one fused-horizon
  dispatch, so K ~ horizon merely breaks even — the committed section
  documents this) and the committed K; zero off motif traffic, under
  sampling, or with spec off.
* **Pool-pressure term** — expected steady-state page demand (live
  slots x mean pages per resident request, plus the prefix cache's
  retention) against ``num_pages``; demand over capacity discounts
  throughput toward the horizon-shrink/eviction regime instead of
  predicting a throughput the pool cannot host.  Per-request demand is
  billed in the PR-11 unit — page-seconds — and a live
  ``page_seconds_per_request`` signal (``MemTelemetry``'s
  ``summary_fields``) overrides the analytic estimate when supplied.
* **Comm term** — wire bytes per emitted token from the PR-12 HLO
  ledger (``comm_bytes_per_token`` health field / committed ``comm``
  section) against a nominal interconnect bandwidth; zero on the
  1-device CPU rig (honestly — the ledger measures zero collective
  bytes there), live on any sharded mesh.

**Analytic infeasibility** is exact, not fitted: a candidate whose
worst-case request cannot fit its slot's page table is pruned without
measurement, by the same ceil arithmetic ``PagedKVManager.pages_needed``
/ ``PagePool.pages_for_tokens`` use — constructing such a config and
submitting the mix's largest request raises, which the test suite
proves candidate-by-candidate.

The class plugs into the seed :class:`~deepspeed_tpu.autotuning.
Autotuner` through the same ``prune(candidates, top_k)`` contract as
``FirstOrderCostModel``.
"""

import json
import math
import os

from deepspeed_tpu.utils.logging import logger

__all__ = ["ServingCostModel", "DEFAULT_KNOBS", "committed_bench_path"]

# the baseline every knob dict is completed from — mirrors the
# scheduler's own defaults (ServingScheduler.__init__) so a partial
# override candidate prices exactly the config it would construct
DEFAULT_KNOBS = {
    "num_slots": 8,
    "num_pages": 64,
    "page_size": 16,
    "max_pages_per_slot": None,        # scheduler default: ceil(pages/2)
    "prefill_chunk": 16,
    "decode_horizon_steps": 8,
    "overlap": True,
    "prefix_cache": False,
    "prefix_cache_pages": None,        # cache default: whole pool
    "spec_decode": None,
    "spec_k": 8,
    # quantized serving memory (PR 14): the paged-KV pool dtype (a
    # SCHEDULER knob — measurable per trial on one engine) and the
    # weight storage dtype (an ENGINE knob — priced and emitted as a
    # ds_serve flag, never varied inside a measured search)
    "kv_dtype": "float32",
    "weight_dtype": None,              # None = follow the engine dtype
    # sequence-parallel prefill routing (PR 18): prompts with at least
    # this many pending tokens take the sequence-sharded prefill path
    # (0 = off).  Priced by the prefill term below; inert without a
    # live sequence axis (the scheduler degrades, and the model's
    # `sequence_axis_size` live signal defaults to 1).
    "seq_parallel_threshold": 0,
    "prefill_reserve_frac": None,      # scheduler default: whole pool
    # multi-tenant serving (PR 20): the adapter roster size and rank
    # (-> the rank bucket, a jit-signature input AND the per-token
    # delta-einsum cost), and the per-tenant page quota (a feasibility
    # bound exactly like the slot table).  0 adapters = tenancy priced
    # as off (the base path is byte-identical by construction).
    "num_adapters": 0,
    "adapter_rank": 4,
    "tenant_page_quota": None,
}

# dispatch overhead billed in token-equivalents for the TTFT prefill
# term: on the committed CPU rig each prefill chunk pays a host
# round-trip worth roughly one default chunk of compute (the
# horizon-amortization fit makes the same dispatch-dominance claim for
# decode).  Only the RATIO between candidates matters for ranking.
_DISPATCH_TOKEN_EQUIV = 16.0

# nominal interconnect bandwidth for the comm term (bytes/s per
# device).  TPU v4 ICI order of magnitude; only the RATIO between
# candidates matters for ranking, and on a 1-device rig the ledger's
# bytes are zero so the term vanishes entirely.
_NOMINAL_ICI_BYTES_PER_S = 1e11

# per-rank-unit relative cost of the multi-LoRA delta einsums: every
# injected projection pays two [.., in] x [in, r] / [.., r] x [r, out]
# contractions plus the per-slot factor gather, so the slowdown scales
# with the RANK BUCKET, not the adapter count (adapter churn within a
# bucket is free by construction).  A committed ``multi_lora`` bench
# section overrides this prior with the measured figure.
_LORA_RANK_COST = 0.004


def committed_bench_path():
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
        "benchmarks", "serving_results_cpu.json")


def _pages_for_tokens(num_tokens, page_size):
    """EXACTLY PagePool.pages_for_tokens — the analytic feasibility
    check must agree with the pool's own arithmetic to the token."""
    return -(-int(num_tokens) // int(page_size))


class ServingCostModel:
    """Predict ``(tokens_per_sec, ttft_ms)`` for a (knobs, mix) point
    and prune/rank candidate knob dicts for the measured search."""

    def __init__(self, mix, bench=None, bench_path=None,
                 live_signals=None, geometry=None,
                 pool_bytes_budget=None):
        self.mix = mix
        if bench is None:
            bench_path = bench_path or committed_bench_path()
            with open(bench_path) as f:
                bench = json.load(f)
        self.bench = bench
        self.live = dict(live_signals or {})
        # quantized-memory page arithmetic: with the model's KV
        # geometry ({"num_layers", "kv_heads", "head_dim"}) the model
        # prices candidates in BYTES per page — dtype-dependent — and,
        # given a pool byte budget (the HBM the operator is willing to
        # spend), prunes any candidate whose num_pages x
        # bytes_per_page(kv_dtype) exceeds it.  int8/fp8 candidates
        # therefore fit ~2-4x the pages of fp32 in the same budget,
        # which the pressure term then converts into throughput.
        self.geometry = dict(geometry) if geometry else None
        self.pool_bytes_budget = None if pool_bytes_budget is None \
            else int(pool_bytes_budget)
        self._fit_horizon_curve()
        self._fit_reference_terms()

    def page_bytes(self, knobs):
        """Bytes one KV page costs under this candidate's kv_dtype
        (None without geometry) — the exact ops/quant/kv.kv_page_bytes
        arithmetic, so pruning agrees with allocation to the byte."""
        if self.geometry is None:
            return None
        from deepspeed_tpu.ops.quant.kv import kv_page_bytes
        k = self.complete(knobs) if "kv_dtype" not in knobs or \
            "page_size" not in knobs else knobs
        dtype = k.get("kv_dtype") or "float32"
        if dtype not in ("int8", "fp8"):
            import jax.numpy as jnp
            floats = dict(float32=jnp.float32, bfloat16=jnp.bfloat16,
                          float16=jnp.float16)
            if dtype not in floats:
                # pricing an unknown name as fp32 would silently skew
                # every byte figure built on it — reject like the
                # allocator would
                raise ValueError(f"unknown kv_dtype {dtype!r}")
            dtype = floats[dtype]
        return kv_page_bytes(self.geometry["num_layers"],
                             self.geometry["kv_heads"],
                             self.geometry["head_dim"],
                             k["page_size"], dtype)

    # ------------------------------------------------------------ fitting
    def _fit_horizon_curve(self):
        sweep = self.bench.get("horizon_sweep") or {}
        pts = [(int(h), float(r["tokens_per_sec"]))
               for h, r in sweep.items() if r.get("tokens_per_sec")]
        if len(pts) < 2:
            # degenerate bench file: a flat curve still ranks pool and
            # cache terms; horizon becomes a no-op rather than a crash
            base = pts[0][1] if pts else 1000.0
            self._h_intercept, self._h_slope = 1.0 / base, 0.0
            logger.warning("serving cost model: horizon_sweep has "
                           f"{len(pts)} points; horizon term is flat")
            return
        # linearize R(h) = R_inf * h / (h + a)  =>  1/R = c + b/h with
        # c = 1/R_inf, b = a/R_inf; least squares of z=1/R on x=1/h
        xs = [1.0 / h for h, _ in pts]
        zs = [1.0 / r for _, r in pts]
        n = len(pts)
        mx, mz = sum(xs) / n, sum(zs) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        sxz = sum((x - mx) * (z - mz) for x, z in zip(xs, zs))
        b = sxz / sxx if sxx > 0 else 0.0
        c = mz - b * mx
        # positivity clamps keep the curve physical (monotone
        # nondecreasing, finite asymptote) even on adversarial data
        self._h_slope = max(b, 0.0)
        self._h_intercept = max(c, 1e-12)

    def _fit_reference_terms(self):
        bench = self.bench
        ps = bench.get("prefix_share", {}).get("shared", {})
        self._prefix_speedup_ref = float(
            ps.get("speedup_tokens_per_sec") or 1.0)
        self._prefix_ttft_speedup_ref = float(
            ps.get("ttft_p50_speedup") or 1.0)
        psec = bench.get("prefix_share", {})
        sl = float(psec.get("shared_prefix_len") or 96)
        tl = float(psec.get("tail_len") or 8)
        self._prefix_share_ref = sl / (sl + tl)
        sd = bench.get("spec_decode", {})
        self._spec_speedup_ref = float(
            sd.get("speedup_tokens_per_sec") or 1.0)
        self._spec_k_ref = int(sd.get("spec_k") or 32)
        cont = bench.get("continuous", {})
        self._ttft_ref_ms = float(cont.get("ttft_ms_p50") or 100.0)
        # mean prompt length of the committed mixed workload (uniform
        # 4..23) — the TTFT reference's prefill work unit
        self._prompt_ref = 13.5
        comm = bench.get("comm", {})
        self._comm_bytes_per_token = float(
            self.live.get("comm_bytes_per_token",
                          comm.get("bytes_per_token") or 0.0))
        # quantized-KV throughput factor at EQUAL slots, from the
        # committed kv_quant same-slots A/B (1.0 when the section is
        # absent — capacity, not speed, is the quantization claim on
        # the CPU rig; a real-TPU bench refit sharpens this)
        kvq = bench.get("kv_quant", {}).get("same_slots", {})
        self._kv_quant_speed_ref = float(
            kvq.get("speedup_tokens_per_sec") or 1.0)
        # multi-LoRA decode slowdown vs base at the committed rank
        # bucket (1.0 + analytic prior when the section is absent)
        ml = bench.get("multi_lora", {})
        self._lora_slowdown_ref = float(
            ml.get("slowdown_tokens_per_sec") or 0.0)
        self._lora_rank_ref = int(ml.get("rank_bucket") or 0)

    # ------------------------------------------------------- feasibility
    @staticmethod
    def complete(knobs):
        """Fill a partial candidate from the scheduler-default baseline
        (unknown knob names are a config error, not a silent no-op)."""
        unknown = set(knobs) - set(DEFAULT_KNOBS)
        if unknown:
            raise ValueError(f"unknown serving knobs: {sorted(unknown)}; "
                             f"valid: {sorted(DEFAULT_KNOBS)}")
        full = dict(DEFAULT_KNOBS)
        full.update(knobs)
        if full["max_pages_per_slot"] is None:
            # ServingScheduler.__init__'s own default
            full["max_pages_per_slot"] = -(-full["num_pages"] // 2) or 1
        return full

    def infeasible_reason(self, knobs):
        """None when the mix fits this config; otherwise the exact
        reason the scheduler would raise.  Pure page arithmetic — the
        same ceil division ``PagedKVManager.pages_needed`` runs, so a
        pruned candidate is PROVABLY unconstructible for this mix:
        submitting the mix's largest request raises ValueError
        (per-slot table) or the pool OOMs on the first request."""
        k = self.complete(knobs)
        need = self.mix.max_request_tokens
        pages_needed = _pages_for_tokens(need, k["page_size"])
        slot_cap = min(k["max_pages_per_slot"], k["num_pages"])
        if pages_needed > slot_cap:
            return (f"worst-case request of {need} tokens needs "
                    f"{pages_needed} pages > min(max_pages_per_slot="
                    f"{k['max_pages_per_slot']}, num_pages="
                    f"{k['num_pages']}) = {slot_cap}")
        if k["kv_dtype"] not in (None, "float32", "bfloat16", "float16",
                                 "int8", "fp8"):
            return f"unknown kv_dtype {k['kv_dtype']!r}"
        # bytes-per-page is dtype-dependent now: with a pool byte
        # budget, a candidate's page count must FIT it under its own
        # kv_dtype's page bytes (the same arithmetic the allocator
        # bills — a pruned candidate provably over-allocates)
        if self.pool_bytes_budget is not None:
            bpp = self.page_bytes(k)
            if bpp is not None and k["num_pages"] * bpp > \
                    self.pool_bytes_budget:
                return (f"{k['num_pages']} pages x {bpp} B/page "
                        f"(kv_dtype={k['kv_dtype']}) = "
                        f"{k['num_pages'] * bpp} B exceeds the pool "
                        f"budget of {self.pool_bytes_budget} B")
        # a tenant quota below the worst-case request's page need can
        # never admit it (the scheduler sheds with exactly this reason)
        if k["tenant_page_quota"] is not None and \
                pages_needed > int(k["tenant_page_quota"]):
            return (f"worst-case request of {need} tokens needs "
                    f"{pages_needed} pages > tenant_page_quota="
                    f"{k['tenant_page_quota']}")
        return None

    # -------------------------------------------------------- prediction
    def _horizon_tokens_per_s(self, h):
        return 1.0 / (self._h_intercept + self._h_slope / max(1, int(h)))

    def _prefix_factor(self, k):
        mix = self.mix
        if not k["prefix_cache"] or mix.shared_fraction <= 0:
            return 1.0
        # the cache only reuses FULL pages of the shared prefix; a
        # retention cap that cannot hold the chain kills the term
        chain = mix.shared_prefix_len // k["page_size"]
        cap = k["prefix_cache_pages"]
        if chain < 1 or (cap is not None and cap < chain):
            return 1.0
        share = (mix.shared_fraction * mix.shared_prefix_len
                 / max(1, mix.max_prompt_tokens))
        gain = (self._prefix_speedup_ref - 1.0) * \
            (share / self._prefix_share_ref)
        if int(k["num_adapters"]) > 0:
            # per-(tenant, adapter) namespace isolation splits the
            # radix: identical prompts under different adapters never
            # share pages, so the expected hit rate divides across the
            # roster (+1 for the base-model namespace)
            gain /= int(k["num_adapters"]) + 1
        return 1.0 + max(0.0, gain)

    def _lora_factor(self, k):
        """Multi-LoRA decode slowdown: rank-bucket-proportional delta
        einsum cost (adapter count is free within a bucket — the stack
        gather is O(1) per slot).  The committed ``multi_lora`` bench
        section anchors the slope when present; the analytic prior
        prices it otherwise."""
        if int(k["num_adapters"]) <= 0:
            return 1.0
        rb = 1 << (max(1, int(k["adapter_rank"])) - 1).bit_length() \
            if int(k["adapter_rank"]) > 1 else 1
        if self._lora_slowdown_ref > 0 and self._lora_rank_ref > 0:
            slope = (self._lora_slowdown_ref - 1.0) / self._lora_rank_ref
            return 1.0 / (1.0 + max(0.0, slope) * rb)
        return 1.0 / (1.0 + _LORA_RANK_COST * rb)

    def _spec_factor(self, k):
        mix = self.mix
        mode = k["spec_decode"]
        if mode in (None, False, "off") or mix.motif_len <= 0 or \
                mix.greedy_fraction < 1.0:
            return 1.0
        # break-even at K ~ horizon (a verify round costs one fused
        # dispatch and every round is a barrier step — the committed
        # section documents K=8 vs H=8 as parity); the committed win
        # anchors the high end, log-interpolated between the two
        h = max(1, int(k["decode_horizon_steps"]))
        kk = max(1, int(k["spec_k"]))
        lo, hi = math.log2(1 + h), math.log2(1 + self._spec_k_ref)
        if hi <= lo:
            return 1.0
        t = (math.log2(1 + kk) - lo) / (hi - lo)
        gain = (self._spec_speedup_ref - 1.0) * min(max(t, 0.0), 1.0)
        return 1.0 + gain

    def _prefill_work(self, k, unique):
        """Decompose a prompt's prefill into (dispatches, per-device
        compute tokens, routed): the chunked loop pays one dispatch per
        ``prefill_chunk`` tokens; sequence-parallel routing widens the
        chunk to ``prefill_chunk x axis_size`` AND spreads the
        attention/MLP compute over the axis — both effects are what
        bends TTFT sub-linear for long prompts.  The axis size is a
        LIVE signal (``sequence_axis_size``, from the engine's resolved
        plan); it defaults to 1, so the term is honest on a rig without
        a sequence axis — routing there is a scheduler degrade, and the
        model prices it as one."""
        chunk = max(1, int(k["prefill_chunk"]))
        seq = max(1, int(self.live.get("sequence_axis_size", 1)))
        thr = int(k.get("seq_parallel_threshold") or 0)
        routed = thr > 0 and seq > 1 and unique >= thr
        eff = chunk * seq if routed else chunk
        dispatches = -(-int(max(1.0, unique)) // eff)
        compute = float(unique) / (seq if routed else 1)
        return dispatches, compute, routed

    def _page_demand(self, k):
        """Expected steady-state page demand: live slots x mean pages
        resident per request (mid-decode), plus the prefix cache's
        retention appetite.  The per-request figure is the analytic
        page-seconds rate; a live ``page_seconds_per_request`` signal
        (PR-11 telemetry over a real run) replaces it when supplied."""
        mix = self.mix
        mean_prompt = (mix.max_prompt_tokens +
                       (mix.prompt_len[0] if mix.shared_fraction <= 0
                        and mix.motif_len <= 0
                        else mix.max_prompt_tokens)) / 2
        mean_resident = mean_prompt + (mix.decode_len[0] +
                                       mix.decode_len[1]) / 4
        pages_per_req = _pages_for_tokens(mean_resident, k["page_size"])
        demand = k["num_slots"] * pages_per_req
        if k["prefix_cache"] and mix.shared_fraction > 0:
            cap = k["prefix_cache_pages"]
            retain = mix.shared_prefix_len // k["page_size"]
            demand += retain if cap is None else min(retain, cap)
        return demand, pages_per_req

    def predict(self, knobs):
        """Predict the mix's serving scorecard under ``knobs``: returns
        ``{"fits", "reason", "tokens_per_sec", "ttft_ms",
        "page_seconds_per_request", "terms"}``.  Infeasible configs
        predict nothing (``fits=False`` + the exact reason)."""
        k = self.complete(knobs)
        reason = self.infeasible_reason(k)
        if reason is not None:
            return {"fits": False, "reason": reason,
                    "tokens_per_sec": 0.0, "ttft_ms": None,
                    "page_seconds_per_request": None, "terms": {}}
        base = self._horizon_tokens_per_s(k["decode_horizon_steps"])
        prefix = self._prefix_factor(k)
        spec = self._spec_factor(k)
        # overlap keeps one horizon in flight; its win is small on the
        # committed CPU rig and unfitted — a mild documented prior, the
        # same for every candidate pair that differs only here
        overlap = 1.0 if k["overlap"] else 0.95
        # quantized KV at equal slots: the committed same-slots A/B
        # anchors the factor (1.0 with no committed section — on the
        # CPU rig quantization is a CAPACITY lever, priced through the
        # pressure term below, not a speed claim)
        kvq = self._kv_quant_speed_ref \
            if k["kv_dtype"] in ("int8", "fp8") else 1.0
        lora = self._lora_factor(k)
        demand, pages_per_req = self._page_demand(k)
        pressure = min(1.0, k["num_pages"] / demand) if demand else 1.0
        # under demand > capacity the scheduler shrinks horizons and
        # evicts: discount toward the measured H=1 regime floor
        pressure = max(pressure, 0.25)
        # a page quota caps the effective pool one tenant's traffic can
        # occupy; with the tuner's single-tenant measurement mix the
        # quota binds exactly like a smaller pool would
        if k["tenant_page_quota"] is not None and demand:
            pressure = max(min(pressure, int(k["tenant_page_quota"])
                               / demand), 0.25)
        rate = base * prefix * spec * overlap * pressure * kvq * lora
        comm = 1.0
        if self._comm_bytes_per_token > 0:
            comm = 1.0 / (1.0 + self._comm_bytes_per_token * rate
                          / _NOMINAL_ICI_BYTES_PER_S)
            rate *= comm
        # TTFT: prefill work on UNIQUE tokens (the cache skips shared
        # ones), scaled from the committed reference; queueing rides the
        # throughput ratio
        unique = self.mix.max_prompt_tokens
        if prefix > 1.0:
            unique = max(1.0, unique - self.mix.shared_fraction *
                         self.mix.shared_prefix_len)
        # prefill decomposition: dispatch overhead x chunk count plus
        # per-device compute, against the same decomposition of the
        # committed reference mix (mean prompt 13.5 = one chunk = one
        # dispatch)
        disp, compute, routed = self._prefill_work(k, unique)
        ref = _DISPATCH_TOKEN_EQUIV * 1.0 + self._prompt_ref
        prefill_scale = (_DISPATCH_TOKEN_EQUIV * disp + compute) / ref
        ttft = self._ttft_ref_ms * prefill_scale * \
            (self._horizon_tokens_per_s(8) / max(rate, 1e-9)) ** 0.5
        # page-seconds per request: resident pages x predicted service
        # time (decode budget / per-slot token rate) — the PR-11
        # billing unit; a live telemetry figure overrides the estimate
        service_s = ((self.mix.decode_len[0] + self.mix.decode_len[1])
                     / 2) * self.mix.requests / max(rate, 1e-9) \
            / max(1, self.mix.requests / k["num_slots"])
        psec = self.live.get("page_seconds_per_request",
                             pages_per_req * service_s)
        return {
            "fits": True, "reason": None,
            "tokens_per_sec": round(rate, 2),
            "ttft_ms": round(ttft, 2),
            "page_seconds_per_request": round(float(psec), 4),
            "terms": {"horizon_base": round(base, 2),
                      "prefix_factor": round(prefix, 3),
                      "spec_factor": round(spec, 3),
                      "overlap_factor": overlap,
                      "pressure_factor": round(pressure, 3),
                      "comm_factor": round(comm, 4),
                      "kv_quant_factor": round(kvq, 3),
                      "lora_factor": round(lora, 3),
                      "page_bytes": self.page_bytes(k),
                      "page_demand": demand,
                      "prefill_dispatches": disp,
                      "seq_parallel_routed": routed},
        }

    # ----------------------------------------------- seed-tuner contract
    def prune(self, candidates, top_k=None):
        """The seed ``Autotuner`` cost-model contract
        (``FirstOrderCostModel.prune``): ``[(overrides, cfg), ...] ->
        (kept, dropped)`` with ``kept`` ranked best-predicted-first and
        infeasible candidates dropped with their exact reason —
        analytically, never measured."""
        scored, dropped = [], []
        for ov, cfg in candidates:
            est = self.predict(cfg)
            if not est["fits"]:
                dropped.append({"overrides": ov, "pruned": "infeasible",
                                "estimate": est})
                continue
            scored.append((est["tokens_per_sec"], ov, cfg, est))
        # deterministic ranking: ties break on the override repr so the
        # same mix + space always measures in the same order
        scored.sort(key=lambda t: (-t[0], repr(sorted(t[1].items()))))
        if top_k is not None and len(scored) > top_k:
            for s in scored[top_k:]:
                dropped.append({"overrides": s[1], "pruned": "ranked_out",
                                "estimate": s[3]})
            scored = scored[:top_k]
        logger.info(f"serving cost model: measuring {len(scored)} of "
                    f"{len(scored) + len(dropped)} candidates")
        return [(ov, cfg, est) for _, ov, cfg, est in scored], dropped
