"""Serving autotuner: profile-guided search + online control over the
serving knob space (ROADMAP item 3 — the decision layer the PR-8..12
observability stack feeds).

* :class:`TrafficMix` — the declared workload (traffic.py)
* :class:`ServingCostModel` — analytic pruning + ranking fit to the
  committed bench JSON and live telemetry (cost_model.py)
* :class:`ServingAutotuner` — the measured search (search.py)
* :class:`OnlineTuner` — bounded live nudges (online.py)
"""

from deepspeed_tpu.autotuning.serving.traffic import (  # noqa: F401
    MIX_PRESETS, TrafficMix, load_mix)
from deepspeed_tpu.autotuning.serving.cost_model import (  # noqa: F401
    DEFAULT_KNOBS, ServingCostModel)
from deepspeed_tpu.autotuning.serving.search import (  # noqa: F401
    DEFAULT_SERVING_SPACE, ServingAutotuner, ds_serve_args,
    rank_correlation)
from deepspeed_tpu.autotuning.serving.online import OnlineTuner  # noqa: F401
