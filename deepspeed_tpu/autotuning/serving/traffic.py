"""Traffic-mix specification for the serving autotuner.

A :class:`TrafficMix` is the DECLARED workload a tuned config is tuned
*for*: request rate, prompt/decode length distributions, the
shared-prefix structure (what the radix cache can reuse), the
repetition structure (what the n-gram drafter can exploit), and the
temperature mix (sampled traffic disables speculation).  It is pure
data — serializable to JSON, hashable into a search provenance line —
and it DERIVES a deterministic load (prompts, decode budgets, Poisson
arrival offsets) from its seed, so two searches over the same mix
measure candidate configs against byte-identical request streams.

The presets mirror the committed bench workloads in
``benchmarks/serving_results_cpu.json`` exactly (same generator shapes
and seeds as ``benchmarks/serving_bench.py``): a cost model fit to the
committed sections is only honest about a mix the sections actually
measured, so the presets are the calibration anchors and custom mixes
interpolate from there.
"""

import json

import numpy as np

__all__ = ["TrafficMix", "MIX_PRESETS", "load_mix"]


class TrafficMix:
    """Declarative serving workload: what the tuner optimizes FOR.

    Parameters mirror the bench generators:

    * ``request_rate`` — Poisson arrival rate (req/s); the committed
      bench sections use 1000 (server-bound: arrivals never starve the
      batch, so tokens/s measures the serving loop, not the client).
    * ``prompt_len`` / ``decode_len`` — inclusive ``(lo, hi)`` bounds;
      per-request lengths draw uniformly (the bench convention).
    * ``shared_prefix_len`` / ``tail_len`` — when ``shared_prefix_len >
      0``, ``shared_fraction`` of the requests spell one common system
      prompt plus a distinct tail (the radix cache's target traffic)
      and ``prompt_len`` is ignored for those requests.
    * ``motif_len`` / ``motif_repeats`` — when ``motif_len > 0``,
      prompts are a repeated per-request motif (the n-gram drafter's
      target traffic; composes with neither sharing nor plain prompts —
      one structure per mix, like the bench workloads).
    * ``greedy_fraction`` — fraction of traffic decoded greedily
      (temperature 0).  Speculation only pays off on the greedy share;
      the stock mixes are fully greedy like the committed benches.
    """

    _FIELDS = ("name", "requests", "request_rate", "prompt_len",
               "decode_len", "shared_prefix_len", "tail_len",
               "shared_fraction", "motif_len", "motif_repeats",
               "greedy_fraction", "seed")

    def __init__(self, name="custom", requests=64, request_rate=1000.0,
                 prompt_len=(4, 24), decode_len=(4, 16),
                 shared_prefix_len=0, tail_len=8, shared_fraction=0.0,
                 motif_len=0, motif_repeats=3, greedy_fraction=1.0,
                 seed=0):
        self.name = str(name)
        self.requests = int(requests)
        self.request_rate = float(request_rate)
        self.prompt_len = (int(prompt_len[0]), int(prompt_len[1]))
        self.decode_len = (int(decode_len[0]), int(decode_len[1]))
        self.shared_prefix_len = int(shared_prefix_len)
        self.tail_len = int(tail_len)
        self.shared_fraction = float(shared_fraction)
        self.motif_len = int(motif_len)
        self.motif_repeats = int(motif_repeats)
        self.greedy_fraction = float(greedy_fraction)
        self.seed = int(seed)
        if self.requests <= 0 or self.request_rate <= 0:
            raise ValueError("requests and request_rate must be positive")
        if not 0.0 <= self.shared_fraction <= 1.0:
            raise ValueError("shared_fraction must be in [0, 1]")
        if not 0.0 <= self.greedy_fraction <= 1.0:
            raise ValueError("greedy_fraction must be in [0, 1]")
        if self.shared_fraction > 0 and self.shared_prefix_len <= 0:
            raise ValueError("shared_fraction > 0 needs "
                             "shared_prefix_len > 0")
        if self.motif_len > 0 and self.shared_fraction > 0:
            raise ValueError("a mix is shared-prefix OR motif traffic, "
                             "not both (one structure per mix, like the "
                             "committed bench workloads)")

    # ------------------------------------------------------ serialization
    def to_dict(self):
        return {k: getattr(self, k) for k in self._FIELDS}

    @classmethod
    def from_dict(cls, d):
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(f"unknown TrafficMix fields: {sorted(unknown)}")
        return cls(**d)

    def save(self, path):
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2)
            f.write("\n")

    @classmethod
    def load(cls, path):
        with open(path) as f:
            d = json.load(f)
        # tuple fields round-trip through JSON as lists
        for k in ("prompt_len", "decode_len"):
            if k in d:
                d[k] = tuple(d[k])
        return cls.from_dict(d)

    def __repr__(self):
        return (f"TrafficMix({self.name!r}, requests={self.requests}, "
                f"rate={self.request_rate}, shared={self.shared_fraction}"
                f"@{self.shared_prefix_len}, motif={self.motif_len}x"
                f"{self.motif_repeats}, greedy={self.greedy_fraction}, "
                f"seed={self.seed})")

    # -------------------------------------------------- derived bounds
    @property
    def max_prompt_tokens(self):
        if self.motif_len > 0:
            return self.motif_len * self.motif_repeats + self.tail_len
        plain = self.prompt_len[1]
        if self.shared_fraction > 0:
            shared = self.shared_prefix_len + self.tail_len
            return shared if self.shared_fraction >= 1.0 \
                else max(plain, shared)
        return plain

    @property
    def max_request_tokens(self):
        """Worst-case tokens one request needs resident (prompt + every
        decoded token) — the figure the cost model's analytic
        feasibility check prices against the page arithmetic."""
        return self.max_prompt_tokens + self.decode_len[1]

    # --------------------------------------------------- load generation
    def generate(self, vocab):
        """Derive the deterministic load: ``(prompts, max_new, arrivals,
        sampled)`` — int32 prompt arrays, per-request decode budgets,
        cumulative Poisson arrival offsets (seconds), and a per-request
        bool marking the sampled (non-greedy) share.  Same mix + same
        seed => byte-identical stream; the generator shapes match the
        bench workload builders so the presets reproduce the committed
        workloads exactly."""
        rng = np.random.default_rng(self.seed)
        prompts, max_new = [], []
        if self.motif_len > 0:
            # serving_bench.make_spec_workload shape
            for _ in range(self.requests):
                motif = rng.integers(0, vocab, self.motif_len).astype("i4")
                tail = rng.integers(0, vocab, self.tail_len).astype("i4")
                prompts.append(np.concatenate(
                    [np.tile(motif, self.motif_repeats), tail]))
                max_new.append(int(rng.integers(self.decode_len[0],
                                                self.decode_len[1] + 1)))
        elif self.shared_fraction > 0:
            # serving_bench.make_prefix_workload shape (share=True when
            # every request shares; a partial fraction mixes in plain
            # prompts of the same total length — the control shape)
            sys_prompt = rng.integers(0, vocab,
                                      self.shared_prefix_len).astype("i4")
            total = self.shared_prefix_len + self.tail_len
            for i in range(self.requests):
                if i < round(self.shared_fraction * self.requests):
                    tail = rng.integers(0, vocab, self.tail_len)
                    prompts.append(np.concatenate(
                        [sys_prompt, tail.astype("i4")]))
                else:
                    prompts.append(rng.integers(0, vocab,
                                                total).astype("i4"))
            # budgets draw AFTER all prompts — the bench generator's
            # stream order, kept so the preset replays it exactly
            max_new = [int(rng.integers(self.decode_len[0],
                                        self.decode_len[1] + 1))
                       for _ in range(self.requests)]
        else:
            # serving_bench.make_workload shape (mixed lengths).  NOTE:
            # the bench draws length and budget from the same stream in
            # this order — kept identical so preset "mixed" replays the
            # committed workload byte-for-byte.
            prompts = [rng.integers(
                0, vocab,
                int(rng.integers(self.prompt_len[0],
                                 self.prompt_len[1] + 1))).astype("i4")
                for _ in range(self.requests)]
            max_new = [int(rng.integers(self.decode_len[0],
                                        self.decode_len[1] + 1))
                       for _ in range(self.requests)]
        arrivals = np.cumsum(rng.exponential(1.0 / self.request_rate,
                                             self.requests))
        n_sampled = round((1.0 - self.greedy_fraction) * self.requests)
        sampled = np.zeros(self.requests, bool)
        if n_sampled:
            sampled[rng.choice(self.requests, n_sampled,
                               replace=False)] = True
        return prompts, max_new, arrivals, sampled


# The calibration anchors: each preset reproduces one committed bench
# workload (generator shape, lengths, rate, seed) so the cost model's
# fitted terms and the search's measured trials share a domain.
MIX_PRESETS = {
    # serving_results_cpu.json horizon_sweep/continuous workload
    "mixed": dict(name="mixed", requests=64, request_rate=1000.0,
                  prompt_len=(4, 23), decode_len=(4, 15), seed=0),
    # serving_results_cpu.json prefix_share.shared workload (92% shared
    # fraction by tokens; every request shares the 96-token system
    # prompt)
    "prefix_share": dict(name="prefix_share", requests=64,
                         request_rate=1000.0, decode_len=(4, 15),
                         shared_prefix_len=96, tail_len=8,
                         shared_fraction=1.0, seed=0),
    # serving_results_cpu.json spec_decode workload (repetition-friendly
    # motifs, long decode budgets)
    "spec": dict(name="spec", requests=64, request_rate=1000.0,
                 decode_len=(72, 96), motif_len=8, motif_repeats=3,
                 tail_len=4, seed=0),
}


def load_mix(spec):
    """Resolve a mix argument: a preset name, a JSON file path, or an
    already-built :class:`TrafficMix` (pass-through)."""
    if isinstance(spec, TrafficMix):
        return spec
    if spec in MIX_PRESETS:
        return TrafficMix(**MIX_PRESETS[spec])
    return TrafficMix.load(spec)
