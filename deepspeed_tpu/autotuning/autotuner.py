"""Autotuner: measured search over config candidates.

Reference: ``deepspeed/autotuning/autotuner.py:42`` — generates
experiment configs over (zero stage, micro-batch size, other knobs),
schedules them as subprocess runs across hosts (``scheduler.py:33``),
and picks the fastest by measured throughput.

TPU redesign: trials run IN-PROCESS. Building a fresh engine per
candidate is cheap (jit compile seconds, no process launch, no GPU
re-init), so the tuner is a simple measured grid/greedy search:
for each candidate config it builds an engine via the caller-supplied
factory, runs warmup + measured steps, records samples/sec, and returns
the best config (optionally constrained by a memory estimate from the
engine's cost analysis)."""

import copy
import itertools
import time

from deepspeed_tpu.utils.logging import logger


DEFAULT_TUNING_SPACE = {
    "zero_optimization.stage": [0, 1, 2, 3],
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
}


def _set_path(cfg, dotted, value):
    node = cfg
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


class Autotuner:
    """run_fn(config) -> samples_per_sec drives the measurement; the
    default run_fn builds an engine from (model, loss_fn, batch_fn)."""

    def __init__(self, base_config, tuning_space=None, metric="throughput",
                 warmup_steps=2, measure_steps=5, max_trials=32,
                 cost_model=None, prune_top_k=None, results_path=None):
        self.base_config = dict(base_config)
        self.space = dict(tuning_space or DEFAULT_TUNING_SPACE)
        self.metric = metric
        self.warmup_steps = warmup_steps
        self.measure_steps = measure_steps
        self.max_trials = max_trials
        # cost_model (autotuning/cost_model.py FirstOrderCostModel):
        # drops predicted-OOM candidates and, with prune_top_k, measures
        # only the predicted-top configs — the reference
        # model_based_tuner.py:58 flow with an analytic estimator
        self.cost_model = cost_model
        self.prune_top_k = prune_top_k
        # per-trial records persist like the reference's experiment logs
        # (autotuning/scheduler.py writes exp_<n>.json); one json file
        # with every measured/failed/pruned trial
        self.results_path = results_path
        self.results = []

    def candidates(self):
        keys = list(self.space)
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = copy.deepcopy(self.base_config)
            for k, v in zip(keys, combo):
                _set_path(cfg, k, v)
            yield dict(zip(keys, combo)), cfg

    def default_run_fn(self, model, loss_fn, batch_fn):
        """Build-engine-and-measure trial runner."""
        import jax
        import deepspeed_tpu

        def run(cfg):
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, config=cfg, loss_fn=loss_fn)
            batch = batch_fn(cfg)
            for _ in range(self.warmup_steps):
                loss = engine.forward(batch)
                engine.backward(loss)
                engine.step()
            # fence: warmup dispatches are async; they must drain before
            # the measured window opens.  monotonic(): a wall-clock step
            # (NTP) mid-trial must never corrupt a throughput sample
            float(jax.device_get(loss))
            t0 = time.monotonic()
            for _ in range(self.measure_steps):
                loss = engine.forward(batch)
                engine.backward(loss)
                engine.step()
            float(jax.device_get(loss))
            dt = time.monotonic() - t0
            samples = engine.train_batch_size() * self.measure_steps
            return samples / dt

        return run

    def tune(self, run_fn):
        """Measure the candidates (cost-model-pruned when configured,
        bounded by max_trials); returns
        (best_overrides, best_config, best_metric)."""
        if self.cost_model is not None:
            kept, dropped = self.cost_model.prune(
                list(self.candidates()), top_k=self.prune_top_k)
            self.results.extend(dropped)
            trials = [(ov, cfg) for ov, cfg, est in kept]
        else:
            trials = list(self.candidates())
        best = (None, None, -1.0)
        for i, (overrides, cfg) in enumerate(trials):
            if i >= self.max_trials:
                logger.warning(f"autotuner: stopping at max_trials="
                               f"{self.max_trials}")
                break
            try:
                # monotonic(): trial durations must survive an NTP
                # clock step (time.time() jumps; a negative or wild
                # trial_seconds poisons the persisted record)
                t0 = time.monotonic()
                value = run_fn(cfg)
            except Exception as e:  # OOM / invalid combo: record and skip
                logger.warning(f"autotuner: candidate {overrides} failed: "
                               f"{type(e).__name__}: {e}")
                self.results.append({"overrides": overrides, "error": str(e)})
                continue
            self.results.append({"overrides": overrides, "metric": value,
                                 "trial_seconds":
                                 round(time.monotonic() - t0, 3)})
            logger.info(f"autotuner: {overrides} -> {value:.1f}")
            if value > best[2]:
                best = (overrides, cfg, value)
        self._persist()
        if best[0] is None:
            raise RuntimeError("autotuner: every candidate failed")
        return best

    def _persist(self):
        if not self.results_path:
            return
        import json
        import os
        os.makedirs(os.path.dirname(self.results_path) or ".",
                    exist_ok=True)
        # MERGE into an existing results file instead of clobbering it
        # (the serving-bench --json-out pattern): this tuner's sections
        # replace their own keys, every foreign key another run wrote —
        # other tuners' trials, bench sections, notes — survives.  An
        # unreadable/partial file falls back to a fresh write.
        out = {}
        if os.path.exists(self.results_path):
            try:
                with open(self.results_path) as f:
                    prev = json.load(f)
                if isinstance(prev, dict):
                    out = prev
            except (OSError, ValueError):
                out = {}
        out["space"] = {k: list(v) for k, v in self.space.items()}
        out["trials"] = self.results
        with open(self.results_path, "w") as f:
            json.dump(out, f, indent=2, default=str)
        logger.info(f"autotuner: wrote {len(self.results)} trial records "
                    f"to {self.results_path}")
