"""MoE layer (reference: ``deepspeed/moe/layer.py:16`` ``MoE``).

TPU-native: experts are **stacked** weight tensors with a leading `expert`
axis carrying the logical name "expert", so expert parallelism is a sharding
rule (parallel/sharding.py routes "expert" -> the `expert` mesh axis) and
the dispatch/return all-to-alls are inserted by XLA at the
``with_sharding_constraint`` boundaries — no explicit process groups
(reference builds them in utils/groups.py:108,202).

Residual MoE (``use_residual=True``) reproduces PR-MoE (reference
layer.py:16 use_residual + docs): output = moe_out * sigmoid-weighted mix
with a dense MLP branch.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.moe import sharded_moe


def _maybe_constrain(x, *spec):
    """Sharding constraint if a mesh is active; no-op otherwise."""
    from deepspeed_tpu import comm as dist
    mesh = dist.get_mesh()
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec as P
    # drop axes the mesh doesn't have or that don't divide
    axes = []
    for ax, dim in zip(spec, x.shape):
        ok = ax is not None and ax in mesh.shape and \
            mesh.shape[ax] > 1 and dim % mesh.shape[ax] == 0
        axes.append(ax if ok else None)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*axes)))


class ExpertsMLP(nn.Module):
    """Stacked expert FFNs: params [e, ...] with logical axis "expert"."""
    num_experts: int
    hidden_size: int
    ffn_hidden_size: int
    activation: Callable = nn.gelu
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [e, c, m]
        e, m, f = self.num_experts, self.hidden_size, self.ffn_hidden_size
        wi = self.param("wi", nn.with_partitioning(
            nn.initializers.normal(0.02), ("expert", "embed", "mlp")),
            (e, m, f), self.param_dtype)
        bi = self.param("bi", nn.with_partitioning(
            nn.initializers.zeros_init(), ("expert", "mlp")),
            (e, f), self.param_dtype)
        wo = self.param("wo", nn.with_partitioning(
            nn.initializers.normal(0.02), ("expert", "mlp", "embed")),
            (e, f, m), self.param_dtype)
        bo = self.param("bo", nn.with_partitioning(
            nn.initializers.zeros_init(), ("expert", "embed")),
            (e, m), self.param_dtype)
        wi_v = wi.value if hasattr(wi, "value") else wi
        bi_v = bi.value if hasattr(bi, "value") else bi
        wo_v = wo.value if hasattr(wo, "value") else wo
        bo_v = bo.value if hasattr(bo, "value") else bo
        h = jnp.einsum("ecm,emf->ecf", x, wi_v.astype(self.dtype)) + \
            bi_v.astype(self.dtype)[:, None]
        h = self.activation(h)
        out = jnp.einsum("ecf,efm->ecm", h, wo_v.astype(self.dtype)) + \
            bo_v.astype(self.dtype)[:, None]
        return out


class MoE(nn.Module):
    """Sharded MoE layer. __call__ x: [batch, seq, hidden] ->
    (out [batch, seq, hidden], l_aux scalar, exp_counts [e]).

    Mirrors reference ``MoE.__init__`` arguments (moe/layer.py:16); `expert`
    module injection is replaced by the stacked ``ExpertsMLP`` contract (or
    a custom ``experts_cls``).
    """
    hidden_size: int
    num_experts: int = 1
    ffn_hidden_size: Optional[int] = None
    k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    drop_tokens: bool = True
    use_residual: bool = False           # PR-MoE
    use_rts: bool = False                # Random Token Selection (top-1)
    noisy_gate_policy: Optional[str] = None
    activation: Callable = nn.gelu
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, deterministic=True):
        b, s, m = x.shape
        ffn = self.ffn_hidden_size or 4 * self.hidden_size

        # gate in fp32 (reference TopKGate casts to float, sharded_moe.py:425)
        gate_w = self.param("gate", nn.with_partitioning(
            nn.initializers.normal(0.02), ("embed", None)),
            (m, self.num_experts), jnp.float32)
        gate_w = gate_w.value if hasattr(gate_w, "value") else gate_w

        tokens = x.reshape(b * s, m)
        logits = tokens.astype(jnp.float32) @ gate_w

        rng = None
        # RTS keys off rng AVAILABILITY, not `deterministic`: the engine's
        # default loss applies modules with flax's deterministic default
        # but threads a "gating" rng, and RTS must work there (a
        # deterministic-only gate would make the config flag a silent
        # no-op through deepspeed_tpu.initialize). k=2 never uses it.
        use_rts = self.use_rts and self.k == 1 and self.has_rng("gating")
        if use_rts or (self.noisy_gate_policy == "RSample" and
                       not deterministic):
            rng = self.make_rng("gating")
        cf = self.capacity_factor if not deterministic \
            else self.eval_capacity_factor
        l_aux, combine, dispatch, exp_counts = sharded_moe.gate(
            logits, k=self.k, capacity_factor=cf,
            min_capacity=self.min_capacity, drop_tokens=self.drop_tokens,
            **({"noisy_gate_policy": self.noisy_gate_policy, "rng": rng,
                "use_rts": use_rts}
               if self.k == 1 else {}))

        dispatched = sharded_moe.dispatch_tokens(dispatch, tokens)  # [e,c,m]
        dispatched = _maybe_constrain(dispatched, "expert", "data", None)
        expert_out = ExpertsMLP(self.num_experts, m, ffn, self.activation,
                                self.dtype, self.param_dtype,
                                name="experts")(dispatched)
        expert_out = _maybe_constrain(expert_out, "expert", "data", None)
        out = sharded_moe.combine_tokens(combine, expert_out)       # [s,m]
        out = out.reshape(b, s, m).astype(x.dtype)

        if self.use_residual:
            # PR-MoE: dense MLP branch mixed by a learned 2-way coefficient
            # (reference layer.py forward, use_residual branch). QDense so
            # int8 serving can quantize these kernels like every other
            # Dense in the models (qtensor_params contract).
            from deepspeed_tpu.ops.quant.qdense import QDense
            dense = QDense(ffn, dtype=self.dtype,
                           param_dtype=self.param_dtype, name="res_fc_in")(x)
            dense = self.activation(dense)
            dense = QDense(m, dtype=self.dtype,
                           param_dtype=self.param_dtype,
                           name="res_fc_out")(dense)
            coef = QDense(2, dtype=jnp.float32, param_dtype=jnp.float32,
                          name="coefficient")(x.astype(jnp.float32))
            coef = jax.nn.softmax(coef, axis=-1)
            out = (out * coef[..., 0:1] + dense * coef[..., 1:2]).astype(x.dtype)

        self.sow("intermediates", "moe_aux_loss", l_aux)
        self.sow("intermediates", "exp_counts", exp_counts)
        return out, l_aux, exp_counts
