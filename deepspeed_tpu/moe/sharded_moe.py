"""Top-k gating + dispatch math for Mixture-of-Experts.

Reference: ``deepspeed/moe/sharded_moe.py`` — ``_capacity`` :157,
``top1gating`` :179, ``top2gating`` :277, ``TopKGate`` :343, dispatch via
einsum + ``_AllToAll`` :90. Here gating is pure jax (fp32 throughout) and
the EP all-to-all is *not* an explicit op: the dispatched tensor carries an
``expert``-axis sharding constraint and XLA inserts the collective
(SURVEY.md §2.2 EP row: "lax.all_to_all over an expert mesh axis; gating in
pure jax; capacity/dropping identical").

Shapes follow the reference's einsum notation:
  s = tokens, e = experts, c = capacity, m = model dim.
"""

import math

import jax
import jax.numpy as jnp


def capacity(num_tokens, num_experts, capacity_factor, min_capacity=4):
    """Per-expert token slots: ceil(tokens/experts * factor), floored at
    min_capacity (reference ``_capacity``, sharded_moe.py:157)."""
    cap = math.ceil(num_tokens / num_experts * capacity_factor)
    return max(cap, int(min_capacity))


def _one_hot(idx, n):
    return jax.nn.one_hot(idx, n, dtype=jnp.float32)


def top1_gating(logits, capacity_factor=1.0, min_capacity=4, drop_tokens=True,
                noisy_gate_policy=None, rng=None, used_token_mask=None,
                use_rts=False):
    """Top-1 gating (reference top1gating, sharded_moe.py:179).

    logits: [s, e] raw gate scores (fp32 recommended).
    Returns (l_aux, combine_weights [s,e,c], dispatch_mask [s,e,c] bool,
    exp_counts [e]).

    ``use_rts`` (Random Token Selection, reference sharded_moe.py
    ``use_rts``): when an expert is over capacity, the kept subset is
    chosen by random priority instead of strictly by queue position —
    without it, tokens late in the sequence are ALWAYS the ones dropped,
    a systematic bias RTS removes. Needs ``rng``; queue positions of the
    surviving tokens are re-compacted so capacity slots stay dense.
    """
    s, e = logits.shape
    cap = capacity(s, e, capacity_factor, min_capacity) if drop_tokens else s

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    select_logits = logits
    if noisy_gate_policy == "RSample":
        assert rng is not None, "RSample needs an rng"
        select_logits = logits + jax.random.gumbel(rng, logits.shape,
                                                   jnp.float32)
    indices1 = jnp.argmax(select_logits, axis=-1)            # [s]
    mask1 = _one_hot(indices1, e)                            # [s, e]
    if used_token_mask is not None:                          # padding tokens
        mask1 = mask1 * used_token_mask[:, None]

    exp_counts = jnp.sum(mask1, axis=0).astype(jnp.int32)    # [e]

    # load-balancing loss (reference :232): mean gate mass x mean routed
    # fraction per expert, scaled by e
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    if drop_tokens and use_rts:
        assert rng is not None, "use_rts needs an rng"
        # random priority per (token, expert); unrouted rows rank last.
        # rank-within-expert via double argsort (the reference's
        # _top_idx scatter expressed densely), then keep rank < cap and
        # re-compact queue positions over the survivors.
        prio = jnp.where(mask1 > 0,
                         jax.random.uniform(rng, mask1.shape, jnp.float32),
                         -1.0)
        order = jnp.argsort(-prio, axis=0)
        ranks = jnp.argsort(order, axis=0)
        mask1 = mask1 * (ranks < cap)
    locations1 = jnp.cumsum(mask1, axis=0) - mask1           # [s, e]
    if drop_tokens and not use_rts:
        mask1 = mask1 * (locations1 < cap)
    locations1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)  # [s]

    gates1_s = jnp.sum(gates * mask1, axis=1)                # [s]
    combine = (gates1_s[:, None, None] * mask1[:, :, None] *
               _one_hot(locations1_s, cap)[:, None, :])      # [s, e, c]
    dispatch = combine > 0
    return l_aux, combine, dispatch, exp_counts


def top2_gating(logits, capacity_factor=1.0, min_capacity=4, drop_tokens=True,
                rng=None, second_policy_jitter=True, used_token_mask=None):
    """Top-2 gating (reference top2gating, sharded_moe.py:277).

    Capacity doubles (k=2). Combine weights are the two gate values
    renormalized to sum to 1 per token. The second expert is chosen from
    gumbel-perturbed logits when ``second_policy_jitter`` (the reference's
    noisy second-expert selection); padding tokens flagged off in
    ``used_token_mask`` are neither routed nor counted.
    """
    s, e = logits.shape
    cap = capacity(s, e, 2 * capacity_factor, min_capacity) if drop_tokens \
        else s

    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    indices1 = jnp.argmax(gates, axis=-1)
    mask1 = _one_hot(indices1, e)
    # second expert: argmax with the first masked out, optionally over
    # gumbel-noised logits (reference's noisy second-expert policy)
    select2 = logits.astype(jnp.float32)
    if second_policy_jitter and rng is not None:
        select2 = select2 + jax.random.gumbel(rng, logits.shape, jnp.float32)
    logits_no1 = jnp.where(mask1 > 0, -jnp.inf, select2)
    indices2 = jnp.argmax(logits_no1, axis=-1)
    mask2 = _one_hot(indices2, e)
    if used_token_mask is not None:
        mask1 = mask1 * used_token_mask[:, None]
        mask2 = mask2 * used_token_mask[:, None]

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # expert-2 tokens queue after all expert-1 tokens (reference :300)
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + \
        jnp.sum(mask1, axis=0, keepdims=True)

    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * e

    if drop_tokens:
        mask1 = mask1 * (locations1 < cap)
        mask2 = mask2 * (locations2 < cap)
    loc1_s = jnp.sum(locations1 * mask1, axis=1).astype(jnp.int32)
    loc2_s = jnp.sum(locations2 * mask2, axis=1).astype(jnp.int32)

    gates1_s = jnp.sum(gates * mask1, axis=1)
    gates2_s = jnp.sum(gates * mask2, axis=1)
    denom = gates1_s + gates2_s
    denom = jnp.where(denom < jnp.finfo(jnp.float32).eps, 1.0, denom)
    gates1_s = gates1_s / denom
    gates2_s = gates2_s / denom

    combine1 = (gates1_s[:, None, None] * mask1[:, :, None] *
                _one_hot(loc1_s, cap)[:, None, :])
    combine2 = (gates2_s[:, None, None] * mask2[:, :, None] *
                _one_hot(loc2_s, cap)[:, None, :])
    combine = combine1 + combine2
    dispatch = combine > 0
    exp_counts = jnp.sum(mask1 + mask2, axis=0).astype(jnp.int32)
    return l_aux, combine, dispatch, exp_counts


def gate(logits, k=1, **kw):
    """TopKGate dispatcher (reference TopKGate.forward, sharded_moe.py:409)."""
    if k == 1:
        return top1_gating(logits, **kw)
    if k == 2:
        kw.pop("noisy_gate_policy", None)
        kw.pop("use_rts", None)       # RTS is a top-1 drop policy
        return top2_gating(logits, **kw)
    raise ValueError(f"k={k} not supported (reference supports 1 and 2)")


def dispatch_tokens(dispatch_mask, x):
    """[s,e,c] x [s,m] -> [e,c,m] (reference einsum "sec,sm->ecm", :509)."""
    return jnp.einsum("sec,sm->ecm", dispatch_mask.astype(x.dtype), x)


def combine_tokens(combine_weights, expert_out):
    """[s,e,c] x [e,c,m] -> [s,m] (reference einsum "sec,ecm->sm", :524)."""
    return jnp.einsum("sec,ecm->sm",
                      combine_weights.astype(expert_out.dtype), expert_out)
