"""Mixture-of-Experts / expert parallelism (reference: deepspeed/moe/)."""

from deepspeed_tpu.moe.layer import MoE, ExpertsMLP  # noqa: F401
from deepspeed_tpu.moe.sharded_moe import (capacity, combine_tokens,  # noqa: F401
                                           dispatch_tokens, gate,
                                           top1_gating, top2_gating)
