"""Inference config (reference: ``deepspeed/inference/config.py:128``
``DeepSpeedInferenceConfig`` + ``DeepSpeedTPConfig`` :49, ``DeepSpeedMoEConfig``
:67, quant config :114).

Same JSON/kwargs surface; TPU semantics: `tensor_parallel.tp_size` becomes
the `model` mesh axis size, dtype becomes the compute dtype, and
`replace_with_kernel_inject` selects the Pallas attention path (on TPU the
"kernel injection" decision is just an attention-impl flag — the model is
already native).
"""

from typing import Any, Dict, Optional

from pydantic import BaseModel, ConfigDict, Field


class DeepSpeedTPConfig(BaseModel):
    model_config = ConfigDict(extra="allow", populate_by_name=True)
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(BaseModel):
    model_config = ConfigDict(extra="allow")
    enabled: bool = True
    ep_size: int = 1
    moe_experts: Any = 1
    type: str = "standard"


class QuantizationConfig(BaseModel):
    model_config = ConfigDict(extra="allow")
    enabled: bool = False
    num_bits: int = 8
    group_size: int = 64


class InferenceCheckpointConfig(BaseModel):
    model_config = ConfigDict(extra="allow")
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class DeepSpeedInferenceConfig(BaseModel):
    """Mirrors the reference's field surface (inference/config.py:128)."""
    model_config = ConfigDict(extra="allow", populate_by_name=True)

    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: str = "bfloat16"            # torch.* names accepted via validator
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    moe: DeepSpeedMoEConfig = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[Any] = None
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    max_batch_size: int = 1
    replace_method: str = "auto"
    enable_cuda_graph: bool = False    # accepted, no-op (XLA always compiles)
    zero: Dict[str, Any] = Field(default_factory=dict)
    triangular_masking: bool = True
    return_tuple: bool = True
    # TPU additions
    mesh: Optional[Dict[str, int]] = None
    # multi-slice topologies: ICI (within-slice) sizes ride `mesh`,
    # the across-slice DCN factors ride this — per-axis mesh size is
    # their product (parallel/topology.make_hybrid_mesh; pure config,
    # the serving axis rules are untouched)
    mesh_dcn: Optional[Dict[str, int]] = None
    kv_cache_dtype: str = "bfloat16"
    # paged-attention kernel dispatch policy (ops/attention/decode.py
    # paged_kernel_decision): "auto" picks the Pallas kernel on TPU
    # with 128-aligned pages (shard_mapped per-shard on a multi-device
    # mesh) and the jnp gather reference otherwise; "force" pins the
    # kernel (interpret mode off-TPU — the CI parity oracle);
    # "reference" pins the gather fallback.  Trace-time static: set it
    # before the first serving dispatch, not mid-flight.
    paged_kernel: str = "auto"
    # pluggable checkpoint backend (checkpoint/backend.py) — must match
    # the backend the training engine saved with
    checkpoint_engine: Dict[str, Any] = Field(default_factory=dict)

    def model_post_init(self, _ctx):
        # normalize torch-style dtype strings ("torch.float16", "fp16", "half")
        name = str(self.dtype).lower().replace("torch.", "")
        aliases = {"half": "float16", "fp16": "float16", "bf16": "bfloat16",
                   "float": "float32", "fp32": "float32", "int8": "int8"}
        name = aliases.get(name, name)
        if name == "int8":
            # reference semantics (inference/config.py): dtype=torch.int8
            # means int8 weight quantization with half-precision compute
            self.quant.enabled = True
            name = "bfloat16"
        object.__setattr__(self, "dtype", name)
        # kv_cache_dtype takes the same float aliases PLUS the quantized
        # paged-pool dtypes: "int8" / "fp8" (e4m3) store int8/fp8 KV
        # pages with parallel per-row f32 scale pools (ops/quant/kv.py);
        # unlike dtype, kv "int8" is NOT weight quantization — the two
        # knobs are independent
        kv = str(self.kv_cache_dtype).lower().replace("torch.", "")
        kv_aliases = dict(aliases, fp8="fp8", float8="fp8",
                          float8_e4m3fn="fp8")
        object.__setattr__(self, "kv_cache_dtype",
                           kv_aliases.get(kv, kv))
