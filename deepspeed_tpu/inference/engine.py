"""InferenceEngine: TPU-native serving wrapper.

Reference: ``deepspeed/inference/engine.py:37`` — dtype conversion :422, TP
group creation :198, kernel injection :321, CUDA-graph capture :437,
``forward`` :497, generate wrapper :525 with token-latency hooks :162-196.

TPU redesign:
  * "kernel injection" (`replace_transformer_layer`) becomes a no-op
    decision: models are already native flax; `replace_with_kernel_inject`
    toggles the Pallas flash path via the model's `attn_impl`.
  * auto-TP (`module_inject/auto_tp.py`) becomes sharding: the same logical
    axis rules shard qkv/mlp weights over the `model` mesh axis; the
    row-parallel all-reduce the reference inserts as ``LinearAllreduce``
    (module_inject/layers.py:15) is emitted by XLA at the matmul.
  * CUDA-graph capture/replay is XLA compilation — always on.
  * generation = jitted prefill (batch seq -> logits+cache) + jitted
    single-token decode step, KV cache as a device-resident pytree.
"""

import os
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.parallel import sharding as shd
from deepspeed_tpu.parallel.topology import make_mesh
from deepspeed_tpu.serving.sampling import pipeline as policy_pipeline
from deepspeed_tpu.serving.sharding import (ServingShardingConfig,
                                            config_scope,
                                            pool_bytes_per_device,
                                            resolve_sequence_plan)
from deepspeed_tpu.tracing import jit_cache_size
from deepspeed_tpu.utils.logging import log_dist

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
          "float16": jnp.float16}


def _sampling_label(do_sample, temperature, top_k, top_p):
    """Comm-ledger signature suffix for the sampling statics: greedy
    is the bare label, a sampled combo is its OWN compiled executable
    (the statics are jit static args) and must ledger separately."""
    if not do_sample or not temperature:
        return ""
    return f"[sampled T={temperature:g},k={int(top_k)},p={top_p:g}]"


def _sample_tokens(logits, rng, do_sample, temperature, top_k, top_p):
    """Next-token selection on [batch, vocab] logits, fully traced.

    THE greedy contract (speculative-decode verification depends on it):
    ``do_sample=False`` OR ``temperature == 0`` is a deterministic
    argmax over the fp32 logits — no rng is consumed — and ties break
    to the LOWEST token id (``jnp.argmax`` returns the first maximal
    index).  Verification compares drafted tokens against exactly this
    argmax, so any change here silently breaks token-exactness between
    spec-decode serving and ``generate()``.
    """
    logits = logits.astype(jnp.float32)
    if not do_sample or not temperature:
        return jnp.argmax(logits, axis=-1)
    if temperature and temperature != 1.0:
        logits = logits / temperature
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


class InferenceEngine:
    """Wraps a flax module (+ params) for generation/serving."""

    def __init__(self, model, config, params=None, mesh=None, seed=0):
        self._config = config
        self.module = model
        self.mp_world_size = config.tensor_parallel.tp_size

        # multi-slice ICI x DCN topologies are pure config: `mesh`
        # carries the within-slice (ICI) sizes, `mesh_dcn` the
        # across-slice factors; the serving axis rules are untouched
        # (model stays ICI-innermost, slots span DCN over data)
        self.mesh_dcn = {k: int(v) for k, v in (config.mesh_dcn or {})
                         .items() if int(v) > 1} or None
        if mesh is None:
            from deepspeed_tpu.parallel.topology import make_hybrid_mesh
            from deepspeed_tpu.runtime.config import MeshConfig
            mcfg = config.mesh or {"data": -1,
                                   "model": config.tensor_parallel.tp_size}
            if self.mesh_dcn:
                mesh = make_hybrid_mesh(MeshConfig(**mcfg), self.mesh_dcn,
                                        allow_subset=True)
            else:
                mesh = make_mesh(MeshConfig(**mcfg), allow_subset=True)
        self.mesh = mesh
        # paged-attention dispatch policy ("auto"|"force"|"reference");
        # trace-time static — see DeepSpeedInferenceConfig.paged_kernel
        from deepspeed_tpu.ops.attention import decode as _decode_ops
        mode = {"off": "reference"}.get(config.paged_kernel,
                                        config.paged_kernel)
        if mode not in _decode_ops.PAGED_KERNEL_MODES:
            raise ValueError(
                f"unsupported paged_kernel {config.paged_kernel!r}; "
                f"pick one of {_decode_ops.PAGED_KERNEL_MODES}")
        self.paged_kernel_mode = mode
        # don't clobber a live training engine's global mesh; module
        # internals see self.mesh via dist.mesh_scope around every trace
        if dist.get_mesh() is None:
            dist.set_mesh(mesh)
        # logical serving axes -> mesh axes (kv_heads/slots/pages/vocab;
        # serving/sharding.py); resolved lazily at first paged-serving
        # use so forward/generate-only engines never pay or constrain it
        self.serving_sharding = ServingShardingConfig()
        self._serving_shd = None
        self._validate_mesh_for_model()

        from deepspeed_tpu.ops.quant.kv import (KV_QUANT_DTYPES,
                                                kv_storage_dtype)
        if config.dtype not in DTYPES:
            raise ValueError(
                f"unsupported inference dtype {config.dtype!r}; pick one "
                f"of {sorted(DTYPES)}; dtype='int8' (weight-only "
                "quantization) is accepted via init_inference/"
                "DeepSpeedInferenceConfig")
        if config.kv_cache_dtype not in DTYPES and \
                config.kv_cache_dtype not in KV_QUANT_DTYPES:
            raise ValueError(
                f"unsupported inference kv_cache_dtype "
                f"{config.kv_cache_dtype!r}; pick one of "
                f"{sorted(DTYPES) + sorted(KV_QUANT_DTYPES)}")
        self.dtype = DTYPES[config.dtype]
        # kv_dtype is either a jnp dtype (float pools) or the quantized
        # kv-dtype NAME ("int8"/"fp8" — the paged pools then carry
        # int8/fp8 payload + parallel f32 scale pools, ops/quant/kv.py);
        # fp8 runtime support is validated HERE, at construction, not on
        # the first serving dispatch
        if config.kv_cache_dtype in KV_QUANT_DTYPES:
            kv_storage_dtype(config.kv_cache_dtype)   # runtime gate
            self.kv_dtype = config.kv_cache_dtype
        else:
            self.kv_dtype = DTYPES[config.kv_cache_dtype]
        self.kv_dtype_name = config.kv_cache_dtype
        self._rng = jax.random.PRNGKey(seed)
        self._model_times = []
        self.params = None
        self._decode_fn = None
        self._prefill_fn = None
        self._fwd = None
        # comm/compile observability (PR 12): both default OFF — the
        # zero-cost path is one attribute load + a None check per
        # dispatch, and neither can ever change tokens or compile
        # counts (pinned by tests/unit/test_comm_telemetry.py)
        self._compile_watchdog = None     # tracing.CompileWatchdog
        self._comm_capture = None         # (name,label) -> arg specs
        self._comm_ledger_cache = {}

        # "kernel injection": route attention to the Pallas path via a fresh
        # config (never mutate the caller's model — it may be live in a
        # training engine). "auto" keeps the block-alignment guard.
        cfg = getattr(model, "cfg", None)
        if config.replace_with_kernel_inject and cfg is not None and \
                getattr(cfg, "attn_impl", None) not in (None, "auto"):
            import dataclasses
            self.module = type(model)(dataclasses.replace(cfg,
                                                          attn_impl="auto"))

        ckpt = config.checkpoint
        if isinstance(ckpt, dict):
            ckpt = ckpt.get("checkpoint_dir") or ckpt.get("base_dir")
        elif hasattr(ckpt, "checkpoint_dir"):
            ckpt = ckpt.checkpoint_dir or getattr(ckpt, "base_dir", None)
        if ckpt is not None and not isinstance(ckpt, (str, os.PathLike)):
            raise ValueError(
                f"unusable checkpoint config: {config.checkpoint!r} "
                "(expected a path or {'checkpoint_dir': path})")
        if config.checkpoint is not None and ckpt is None:
            raise ValueError(
                f"unusable checkpoint config: {config.checkpoint!r} "
                "(expected a path or {'checkpoint_dir': path})")

        # a pending checkpoint load replaces provided params — skip the
        # full cast/quantize/offload of a tree about to be thrown away
        if params is not None and ckpt is None:
            self.set_params(params)
        if ckpt is not None:
            self.load_checkpoint(str(ckpt))

    # ------------------------------------------------------------------- mesh
    def _model_head_counts(self):
        """(num_heads, num_kv_heads) from the module config, or (None,
        None) when the module has no head-count contract (generic flax
        modules still forward/generate; only validation and KV-pool
        sharding need the counts)."""
        cfg = getattr(self.module, "cfg", None)
        heads = getattr(cfg, "num_heads", None)
        kv = getattr(cfg, "num_kv_heads", heads)
        return heads, kv

    def _validate_mesh_for_model(self):
        """Construction-time mesh-shape validation: a ``model``-axis
        size that does not divide ``num_heads`` would shard attention
        mid-head — the exact configuration the legacy (jax<0.5) SPMD
        partitioner silently miscompiles into ~1e-2 output drift (the
        seed-era tp=8-over-4-heads failure).  Fail loudly at
        construction instead (the check lives in
        ``ServingShardingConfig.validate_heads`` so a custom rule table
        validates its own configured axis); the serving path
        additionally validates ``num_kv_heads`` when the paged KV pools
        are built (GQA pools shard their kv-head dim over ``model`` —
        kv divisibility is deliberately NOT a construction error:
        generate()-only GQA engines with tp > num_kv_heads are legal
        and tested)."""
        heads, _ = self._model_head_counts()
        if heads:
            self.serving_sharding.validate_heads(self.mesh, heads)

    def _serving_shardings(self, num_slots=None):
        """Resolved serving shardings (serving/sharding.py) for this
        mesh + model: KV pools shard kv_heads over ``model``, per-slot
        carries / token blocks / the page table shard slots over
        ``data``, page ids stay global (replicated page dim).  Raises a
        clear ValueError when ``model`` does not divide num_kv_heads.
        Resolved at first paged-serving use; the serving wrappers pass
        the live ``num_slots`` so a slot count the data axis cannot
        divide evenly degrades that one family to replicated (jax
        requires dim % shards == 0) instead of crashing — when that
        decision flips vs the cached resolution, the jitted serving
        fns are rebuilt (their pinned out_shardings carry it)."""
        def _resolve(n):
            _, kv_heads = self._model_head_counts()
            cfg = getattr(self.module, "cfg", None)
            return self.serving_sharding.resolve(
                self.mesh, num_kv_heads=kv_heads or 1,
                vocab_size=getattr(cfg, "vocab_size", None), num_slots=n)
        if self._serving_shd is None:
            self._serving_shd = _resolve(num_slots)
            self._serving_shd_slots = num_slots
        elif num_slots is not None and \
                num_slots != getattr(self, "_serving_shd_slots", None):
            fresh = _resolve(num_slots)
            if fresh.slot_axis != self._serving_shd.slot_axis:
                log_dist(
                    f"serving slot sharding -> {fresh.slot_axis or 'replicated'}"
                    f" for num_slots={num_slots}; rebuilding serving fns")
                self._paged_prefill_fn = None
                self._paged_prefill_sp_fn = None
                self._paged_decode_fn = None
                self._paged_decode_multi_fn = None
                self._paged_verify_fn = None
                self._paged_decode_policy_fn = None
                self._paged_verify_policy_fn = None
            self._serving_shd = fresh
            self._serving_shd_slots = num_slots
        return self._serving_shd

    def _serving_scope(self):
        """Trace scope for the model-tracing serving primitives: the
        mesh via ``dist.mesh_scope`` (module internals), the engine's
        serving rule table via ``sharding.config_scope`` (the in-graph
        KV-pool constraint must agree with the pinned out_shardings
        even under a custom table), and the paged-kernel dispatch mode
        via ``decode.kernel_mode_scope`` (so
        ``paged_decode_attention`` resolves kernel-vs-reference with
        the engine's configured policy)."""
        import contextlib
        from deepspeed_tpu.ops.attention.decode import kernel_mode_scope
        stack = contextlib.ExitStack()
        stack.enter_context(dist.mesh_scope(self.mesh))
        stack.enter_context(config_scope(self.serving_sharding))
        stack.enter_context(kernel_mode_scope(self.paged_kernel_mode))
        return stack

    def paged_kernel_decision(self, pools=None, page_size=None):
        """The paged-attention kernel-eligibility decision
        (``ops/attention/decode.paged_kernel_decision``) for THIS
        engine's model + mesh + configured mode: ``{"path", "dispatch",
        "reason"}``.  ``page_size`` comes from the live pools when
        given (the leaves' page dim), else from the argument; the
        serving dispatch makes the IDENTICAL decision at trace time, so
        what health() reports is what runs."""
        from deepspeed_tpu.ops.attention import decode as _decode_ops
        heads, kv_heads = self._model_head_counts()
        if page_size is None and pools is not None:
            layers = pools.get("layers") if isinstance(pools, dict) \
                else None
            if layers:
                page_size = int(layers[0]["k_pages"].shape[1])
        cfg = getattr(self.module, "cfg", None)
        return _decode_ops.paged_kernel_decision(
            num_heads=heads or 1, num_kv_heads=kv_heads or heads or 1,
            page_size=page_size, mesh=self.mesh,
            mode=self.paged_kernel_mode,
            has_bias=bool(getattr(cfg, "use_alibi", False)))

    def serving_mesh_info(self, pools=None, num_slots=None):
        """Mesh topology + serving-sharding snapshot for operators
        (``bin/ds_serve`` startup log and ``health()``): per-axis mesh
        sizes, the resolved logical->mesh axis map, and — given the live
        pools — per-device KV-pool bytes (each device holds its kv-head
        shard of every page).  Pass the scheduler's ``num_slots`` so the
        snapshot reflects the slot-family resolution serving will
        actually use (an uneven slot count degrades to replicated — the
        report must say so, not echo the rule table)."""
        info = {
            "mesh_shape": {a: int(s) for a, s in self.mesh.shape.items()
                           if int(s) > 1} or {"data": 1},
            "mesh_devices": int(np.prod(list(self.mesh.shape.values()))),
            "serving_axes":
                self._serving_shardings(num_slots=num_slots).describe(),
            # the kernel-vs-reference dispatch decision, as data — an
            # accidental reference-path fallback must be visible to
            # operators, never silent (health() snapshots this)
            "paged_attention": self.paged_kernel_decision(pools=pools),
        }
        if self.mesh_dcn:
            info["mesh_hybrid"] = {
                "ici": {a: int(s) // self.mesh_dcn.get(a, 1)
                        for a, s in self.mesh.shape.items()
                        if int(s) // self.mesh_dcn.get(a, 1) > 1} or
                       {"data": 1},
                "dcn": dict(self.mesh_dcn),
            }
        if pools is not None:
            info["kv_pool_bytes_per_device"] = pool_bytes_per_device(pools)
            info["kv_pool_bytes_total"] = sum(
                int(leaf.nbytes) for leaf in jax.tree.leaves(pools))
        return info

    # ------------------------------------------------------------------ params
    def _param_shardings(self, params):
        logical = shd.get_logical_specs(params)   # from Partitioned metadata
        unboxed = shd.unbox(params)
        shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), self.dtype), unboxed)
        pspecs = shd.tree_pspecs(self.mesh, shapes, logical, zero_stage=0,
                                 kind="param")
        return shd.tree_shardings(self.mesh, pspecs)

    def set_params(self, params, quantize=None, offload=None):
        """Cast to inference dtype and shard over the mesh (the reference's
        _convert_to_dtype + ReplaceWithTensorSlicing combined); with
        quant.enabled, Dense kernels then quantize to int8 groups
        (reference GroupQuantizer sweep, replace_module.py:138).
        `quantize=False` keeps floats (checkpoint-restore target trees)."""
        offload = (self._config.zero or {}).get("stage") == 3 \
            if offload is None else offload
        sh = self._param_shardings(params)     # needs Partitioned metadata
        params = shd.unbox(params)
        if offload:
            # larger-than-HBM loading: cast/quantize/offload LEAF BY LEAF
            # so peak device memory is one leaf, never the whole model
            return self._set_params_offloaded(params, sh, quantize)
        cast = jax.jit(
            lambda p: jax.tree.map(
                lambda x: x.astype(self.dtype)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
                p),
            out_shardings=sh)
        self.params = cast(params)
        return self._postprocess_params(quantize=quantize, offload=False)

    def _set_params_offloaded(self, params, sh_tree, quantize):
        from deepspeed_tpu.ops.quant import QTensor
        from deepspeed_tpu.ops.quant.quantizer import _eligible, quantize as q
        quantize = self._config.quant.enabled if quantize is None else quantize
        qcfg = self._config.quant

        def host(x):
            return jax.device_put(
                x, x.sharding.with_memory_kind("pinned_host"))

        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        sh_flat = jax.tree.leaves(sh_tree)
        out = []
        for (path, leaf), sh in zip(flat, sh_flat):
            dev = jax.device_put(leaf, sh)
            if jnp.issubdtype(dev.dtype, jnp.floating):
                dev = dev.astype(self.dtype)
            key = jax.tree_util.keystr(path)
            if quantize and self._quant_leaf_predicate(key) and \
                    _eligible(dev):
                qv, scale = q(dev, bits=qcfg.num_bits,
                              group_size=qcfg.group_size)
                out.append(QTensor(host(qv), host(scale), dev.dtype,
                                   qcfg.num_bits, qcfg.group_size))
            else:
                out.append(host(dev))
            del dev
        self.params = jax.tree_util.tree_unflatten(treedef, out)
        self._offload_params = True
        self._params_postprocessed = True
        self._mat_sh = jax.tree.map(
            lambda l: l.sharding.with_memory_kind("device"), self.params)
        n = sum(int(np.prod(np.shape(l)))
                for l in jax.tree.leaves(self.params))
        log_dist(f"inference params ready: {n/1e6:.1f}M, "
                 f"dtype={self._config.dtype}"
                 f"{' +int8' if quantize else ''} +host-offload "
                 f"(leaf-streamed), tp={self.mp_world_size}", ranks=[0])
        return self

    def _postprocess_params(self, quantize=None, offload=None):
        """Quantize then host-offload self.params per config (split out so
        checkpoint restore can load raw floats first)."""
        quantize = self._config.quant.enabled if quantize is None else quantize
        if quantize:
            self.params = self._quantize(self.params)
        if offload is None:
            offload = (self._config.zero or {}).get("stage") == 3
        self._offload_params = bool(offload)
        self._params_postprocessed = bool(quantize or offload)
        if offload:
            # ZeRO-Inference (reference zero.stage=3 + init_inference,
            # docs/2022-09-10-zero-inference.md): weights live in PINNED
            # HOST memory and stream to HBM per use inside the jitted
            # forward — models larger than HBM serve from host RAM, and
            # with int8 the PCIe/DMA stream is the quantized bytes.
            self._mat_sh = jax.tree.map(
                lambda l: l.sharding.with_memory_kind("device"), self.params)
            self.params = jax.tree.map(
                lambda l: jax.device_put(
                    l, l.sharding.with_memory_kind("pinned_host")),
                self.params)
        n = sum(int(np.prod(np.shape(l))) for l in jax.tree.leaves(self.params))
        log_dist(f"inference params ready: {n/1e6:.1f}M, dtype={self._config.dtype}"
                 f"{' +int8' if quantize else ''}"
                 f"{' +host-offload' if offload else ''}, "
                 f"tp={self.mp_world_size}", ranks=[0])
        return self

    @property
    def weight_dtype_name(self):
        """Canonical weight-storage dtype for operator surfaces
        (health(), ds_serve startup log): "int8" under weight-only
        quantization, else the compute dtype name."""
        return "int8" if self._config.quant.enabled else self._config.dtype

    @staticmethod
    def _quant_leaf_predicate(path):
        """THE quant leaf predicate — shared by the on-device tree sweep
        and the leaf-streamed offload path."""
        return "kernel" in path

    def _quantize(self, params):
        from deepspeed_tpu.ops.quant import quantize_tree
        qcfg = self._config.quant
        return quantize_tree(
            params, bits=qcfg.num_bits, group_size=qcfg.group_size,
            predicate=lambda path, leaf: self._quant_leaf_predicate(path))

    def _materialize(self, params):
        """Inside a jitted computation: stream host-offloaded leaves to
        device memory (XLA schedules each transfer next to its consumer).
        QTensor leaves pass through untouched when the module is
        quant-aware (our models' QDense consumes them directly — on a
        single TPU chip via the Pallas dequant-matmul, so the weight
        never materializes in bf16); only legacy float-kernel modules get
        the whole-tree dequantize. Offloaded int8 weights cross the
        host-device link quantized either way."""
        if getattr(self, "_offload_params", False):
            params = jax.tree.map(jax.device_put, params, self._mat_sh)
        if not self._config.quant.enabled or \
                getattr(self.module, "qtensor_params", False):
            return params
        from deepspeed_tpu.ops.quant import dequantize_tree
        return dequantize_tree(params)

    def init_params(self, example_ids=None, seed=0, quantize=None,
                    offload=None):
        """Random init (benchmarks / smoke tests)."""
        ids = example_ids if example_ids is not None \
            else jnp.zeros((1, 8), jnp.int32)
        variables = self.module.init(jax.random.PRNGKey(seed),
                                     jnp.asarray(ids))
        return self.set_params(variables.get("params", variables),
                               quantize=quantize, offload=offload)

    def _host_float_template(self):
        """A zero-valued float param tree already placed in PINNED HOST
        memory, built leaf-by-leaf from eval_shape — nothing ever
        materializes on device (the restore target for larger-than-HBM
        ZeRO-Inference loads)."""
        ids = jnp.zeros((1, 8), jnp.int32)
        boxed = jax.eval_shape(
            lambda: self.module.init(jax.random.PRNGKey(0), ids))["params"]
        sh_tree = self._param_shardings(boxed)
        shapes = shd.unbox(boxed)
        flat, treedef = jax.tree_util.tree_flatten(shapes)
        sh_flat = jax.tree.leaves(sh_tree)
        out = []
        for leaf, sh in zip(flat, sh_flat):
            dtype = self.dtype if jnp.issubdtype(leaf.dtype, jnp.floating) \
                else leaf.dtype
            out.append(jax.device_put(
                np.zeros(leaf.shape, dtype),
                sh.with_memory_kind("pinned_host")))
        return jax.tree_util.tree_unflatten(treedef, out)

    def load_checkpoint(self, path, tag=None):
        """Load params saved by the training engine's save_checkpoint.
        For ZeRO-Inference engines the restore streams straight into host
        memory (and quantizes leaf-by-leaf) — peak device memory during
        the load is at most one parameter. Reads go through the
        pluggable checkpoint backend (checkpoint/backend.py) so custom
        training-side engines serve too."""
        from deepspeed_tpu.checkpoint.backend import get_checkpoint_engine
        backend = get_checkpoint_engine(self._config.checkpoint_engine)

        def load_subtree(path, target, prefix):
            return backend.load_subtree(path, target, prefix=prefix)
        if tag is None:
            latest = os.path.join(path, "latest")
            if os.path.exists(latest):
                with open(latest) as f:
                    tag = f.read().strip()
        full = os.path.join(path, tag) if tag else path
        quant = self._config.quant.enabled
        offload = (self._config.zero or {}).get("stage") == 3

        if offload:
            target = self._host_float_template()
            loaded = load_subtree(full, target, prefix=".params")
            # leaf-streamed postprocess: host float -> (device) quantize
            # -> host, one leaf at a time
            from deepspeed_tpu.ops.quant import QTensor
            from deepspeed_tpu.ops.quant.quantizer import (_eligible,
                                                           quantize as q)
            qcfg = self._config.quant
            flat, treedef = jax.tree_util.tree_flatten_with_path(loaded)
            out = []
            for pth, leaf in flat:
                key = jax.tree_util.keystr(pth)
                if quant and self._quant_leaf_predicate(key) and \
                        _eligible(leaf):
                    dev = jax.device_put(
                        leaf, leaf.sharding.with_memory_kind("device"))
                    qv, scale = q(dev, bits=qcfg.num_bits,
                                  group_size=qcfg.group_size)
                    host = lambda x: jax.device_put(
                        x, x.sharding.with_memory_kind("pinned_host"))
                    out.append(QTensor(host(qv), host(scale), dev.dtype,
                                       qcfg.num_bits, qcfg.group_size))
                    del dev
                else:
                    out.append(leaf)
            self.params = jax.tree_util.tree_unflatten(treedef, out)
            self._offload_params = True
            self._params_postprocessed = True
            self._mat_sh = jax.tree.map(
                lambda l: l.sharding.with_memory_kind("device"), self.params)
            log_dist(f"inference checkpoint loaded from {full} "
                     "(host-offloaded, leaf-streamed)", ranks=[0])
            return self

        if self.params is None or quant or \
                getattr(self, "_params_postprocessed", False):
            # restore needs a float on-DEVICE target tree (shapes +
            # shardings); quantization re-applies after the load. Also
            # rebuilds when the LIVE params were postprocessed (e.g. an
            # explicit set_params override) so the restore target is
            # never a quantized/host tree
            self.init_params(quantize=False, offload=False)
        # restore only the params subtree of the saved TrainState
        self.params = load_subtree(full, self.params, prefix=".params")
        self._postprocess_params(quantize=quant, offload=False)
        log_dist(f"inference checkpoint loaded from {full}", ranks=[0])
        return self

    # ----------------------------------------------------------------- forward
    def forward(self, input_ids, **kwargs):
        """Full forward -> logits (reference engine.forward :497). Extra
        kwargs reach the module: arrays are traced (attention_mask,
        token_type_ids), python scalars/bools are static (deterministic)."""
        assert self.params is not None, "set_params/init_params first"
        static = {k: v for k, v in kwargs.items()
                  if isinstance(v, (bool, str)) or v is None}
        arrays = {k: jnp.asarray(v) for k, v in kwargs.items()
                  if k not in static}
        key = tuple(sorted(static.items()))
        if not hasattr(self, "_fwd_cache"):
            self._fwd_cache = {}
        if key not in self._fwd_cache:
            module = self.module
            materialize = self._materialize

            def fwd(params, ids, **kw):
                return module.apply({"params": materialize(params)}, ids,
                                    **static, **kw)

            self._fwd_cache[key] = jax.jit(fwd)
        t0 = time.time()
        with dist.mesh_scope(self.mesh):
            out = self._fwd_cache[key](self.params, jnp.asarray(input_ids),
                                       **arrays)
        out.block_until_ready()
        self._model_times.append(time.time() - t0)
        return out

    __call__ = forward

    def model_times(self):
        """Per-call latencies (reference token-latency hooks :162-196)."""
        t, self._model_times = self._model_times, []
        return t

    # ---------------------------------------------------------------- generate
    def _supports_cache(self):
        from deepspeed_tpu.models.gpt2 import GPT2
        from deepspeed_tpu.models.llama import Llama
        return isinstance(self.module, (Llama, GPT2))

    def _init_cache(self, batch_size, max_len):
        from deepspeed_tpu.models import gpt2, llama
        from deepspeed_tpu.ops.quant.kv import is_quantized_kv
        mod = llama if isinstance(self.module, llama.Llama) else gpt2
        # quantized kv_dtype applies to the PAGED serving pools only;
        # generate()'s dense cache stays fp32 — generate() is the
        # divergence oracle the quantized serving path is measured
        # against, so it must not quantize out from under that contract
        dt = jnp.float32 if is_quantized_kv(self.kv_dtype) \
            else self.kv_dtype
        return mod.init_kv_cache(self.module.cfg, batch_size,
                                 max_len=max_len, dtype=dt)

    def _build_gen_fns(self):
        module = self.module
        materialize = self._materialize

        def prefill(params, ids, cache):
            logits, cache = module.apply({"params": materialize(params)},
                                         ids, cache=cache)
            return logits[:, -1], cache

        def decode(params, tok, cache, rng, do_sample, temperature, top_k,
                   top_p):
            logits, cache = module.apply({"params": materialize(params)},
                                         tok[:, None], cache=cache)
            nxt = _sample_tokens(logits[:, 0], rng, do_sample, temperature,
                                 top_k, top_p)
            return nxt, cache

        def decode_loop(params, tok, cache, finished, rng, n_steps,
                        do_sample, temperature, top_k, top_p, eos, fill):
            """The whole decode loop as ONE dispatch (lax.scan over steps).
            The per-token Python loop pays a host round-trip per token —
            ruinous over the TPU relay; this is the CUDA-graph-replay
            equivalent of the reference (inference/engine.py:437-456),
            expressed as a traced loop."""
            def body(carry, i):
                tok, cache, finished = carry
                logits, cache = module.apply(
                    {"params": materialize(params)}, tok[:, None],
                    cache=cache)
                nxt = _sample_tokens(logits[:, 0], jax.random.fold_in(rng, i),
                                     do_sample, temperature, top_k, top_p)
                if eos is not None:
                    nxt = jnp.where(finished, fill, nxt.astype(jnp.int32))
                    finished = finished | (nxt == eos)
                return (nxt.astype(tok.dtype), cache, finished), nxt
            (tok, cache, finished), toks = jax.lax.scan(
                body, (tok, cache, finished), jnp.arange(n_steps))
            return toks.T, cache, finished  # [b, n_steps]

        self._prefill_fn = jax.jit(prefill, donate_argnums=(2,))
        # sampling params static: new compile per (do_sample, temp, k, p) combo
        self._decode_fn = jax.jit(decode, donate_argnums=(2,),
                                  static_argnums=(4, 5, 6, 7))
        self._decode_loop_fn = jax.jit(decode_loop, donate_argnums=(2,),
                                       static_argnums=(5, 6, 7, 8, 9, 10, 11))

    # ------------------------------------------------------- paged serving
    # Slot-level primitives for the continuous-batching serving layer
    # (deepspeed_tpu/serving/): a fixed pool of KV pages shared by all
    # live sequences through a page table. Both primitives have a SINGLE
    # jit signature — shapes are fixed by (num_slots, chunk, num_pages,
    # page_size, max_pages) config constants, never by request churn —
    # so the serving loop never recompiles.

    def _paged_module(self):
        from deepspeed_tpu.models import gpt2, llama
        if isinstance(self.module, llama.Llama):
            return llama
        if isinstance(self.module, gpt2.GPT2):
            return gpt2
        raise ValueError(
            "paged serving needs a KV-cache model contract (GPT2/Llama); "
            f"got {type(self.module).__name__}")

    def init_paged_cache(self, num_pages, page_size, kv_dtype=None):
        """Device-resident per-layer K/V page pools, committed to the
        serving pool sharding (kv_heads over ``model``, page ids
        global). The page table, lengths and active mask are host-owned
        (the scheduler passes them per call as small traced inputs).
        Built INSIDE a jit so the pools carry the same committed
        sharding as the pools the serving primitives return — otherwise
        the first prefill/decode call compiles a second signature just
        for the uncommitted zeros.

        ``kv_dtype`` overrides the engine's configured kv_cache_dtype
        for THIS pool (the serving autotuner varies the knob per trial
        scheduler without rebuilding engines): a float name from
        ``DTYPES`` or a quantized name ("int8"/"fp8") — quantized pools
        add parallel f32 scale leaves, all four under the one pool-axis
        sharding (the scale leaf keeps rank 4, trailing dim 1, exactly
        so the single NamedSharding broadcasts)."""
        from deepspeed_tpu.ops.quant.kv import (KV_QUANT_DTYPES,
                                                kv_storage_dtype)
        mod = self._paged_module()
        cfg = self.module.cfg
        dt = self.kv_dtype if kv_dtype is None else kv_dtype
        if isinstance(dt, str):
            if dt in DTYPES:
                dt = DTYPES[dt]
            elif dt in KV_QUANT_DTYPES:
                kv_storage_dtype(dt)   # fp8 runtime gate
            else:
                # a raw CLI path (worker --kv-dtype) can reach here
                # without the config-level alias normalization: fail
                # with the crisp message, not a jnp.zeros TypeError
                # from inside the pool-init jit
                raise ValueError(
                    f"unsupported kv_dtype {dt!r}; pick one of "
                    f"{sorted(DTYPES) + sorted(KV_QUANT_DTYPES)}")
        # one-shot kernel-eligibility report at pool construction (the
        # serving "constructor" moment): which paged-attention path
        # will run, how it dispatches, and why — an accidental
        # reference fallback is a logged fact plus a health() field,
        # never a silent slowdown.  A page size that is the ONLY
        # blocker warns loudly by name (the old silent `page_size %
        # 128` gate).
        dec = self.paged_kernel_decision(page_size=page_size)
        if not getattr(self, "_paged_kernel_logged", False):
            self._paged_kernel_logged = True
            via = f" via {dec['dispatch']}" if dec.get("dispatch") else ""
            log_dist(f"paged attention path: {dec['path']}{via} — "
                     f"{dec['reason']}", ranks=[0])
        if dec.get("blocker") == "page_size":
            import warnings
            warnings.warn(
                f"page_size={page_size} keeps the paged Pallas kernel "
                "OFF (pages must tile the 128-lane TPU layout): decode "
                "runs the gather reference path — use page_size 128 or "
                "256 for kernel-speed paged attention", stacklevel=2)
        pool_sh = self._serving_shardings().pool
        with dist.mesh_scope(self.mesh):
            return jax.jit(lambda: mod.init_paged_kv_cache(
                cfg, num_pages, page_size, dtype=dt),
                out_shardings=pool_sh)()

    def kv_page_bytes(self, page_size, kv_dtype=None):
        """Exact bytes ONE paged-KV page costs across all layers (K+V
        payload + the f32 scale rows of a quantized pool) — the unit
        the capacity ledgers and the autotuner's feasibility arithmetic
        bill in.  Agrees with the allocated leaves' nbytes to the byte
        (pinned by tests/unit/test_kv_quant.py)."""
        from deepspeed_tpu.ops.quant import kv as kvq
        cfg = self.module.cfg
        heads, kv_heads = self._model_head_counts()
        dt = self.kv_dtype if kv_dtype is None else kv_dtype
        if isinstance(dt, str) and dt in DTYPES:
            dt = DTYPES[dt]
        return kvq.kv_page_bytes(cfg.num_layers, kv_heads or heads,
                                 cfg.head_dim, page_size, dt)

    def _build_serving_fns(self):
        module = self.module
        materialize = self._materialize

        def prefill(params, ids, slot, n_valid, page_table, lengths, pools,
                    adapters):
            cache = dict(pools, page_table=page_table, lengths=lengths,
                         slot=slot, n_valid=n_valid)
            # multi-tenant LoRA side input: None is a LEAFLESS pytree, so
            # base-only traffic keeps the exact pre-tenancy signature and
            # trace; a stacked adapter pack adds one signature per rank
            # bucket (shapes), never per adapter (ids/weights are traced)
            if adapters is not None:
                cache["adapters"] = adapters
            logits, cache = module.apply({"params": materialize(params)},
                                         ids, cache=cache)
            # the model already reduced to the chunk's boundary row (the
            # only position a scheduler ever samples from)
            return logits[0, 0], {"layers": cache["layers"]}

        seq_plan = self.seq_parallel_plan()

        def prefill_sp(params, ids, slot, n_valid, page_table, lengths,
                       pools):
            # sequence-parallel twin of prefill: identical signature and
            # paged landing, but the cache carries the static
            # seq_axis/seq_impl markers (plain Python strings at trace
            # time — the dict is built INSIDE the traced closure, same
            # mechanism as the "slot" marker), so the model runs the
            # chunk's attention distributed over the sequence axis.
            # ids arrive sequence-sharded on dim 1 (the staging in
            # prefill_sequence_parallel), which is what makes GSPMD
            # shard the whole per-token pipeline and gather the KV
            # scatter over the axis
            cache = dict(pools, page_table=page_table, lengths=lengths,
                         slot=slot, n_valid=n_valid,
                         seq_axis=seq_plan.axis, seq_impl=seq_plan.impl)
            logits, cache = module.apply({"params": materialize(params)},
                                         ids, cache=cache)
            return logits[0, 0], {"layers": cache["layers"]}

        def decode(params, toks, active, page_table, lengths, pools, rng,
                   do_sample, temperature, top_k, top_p):
            cache = dict(pools, page_table=page_table, lengths=lengths,
                         active=active)
            logits, cache = module.apply({"params": materialize(params)},
                                         toks[:, None], cache=cache)
            nxt = _sample_tokens(logits[:, 0], rng, do_sample, temperature,
                                 top_k, top_p)
            return nxt.astype(jnp.int32), {"layers": cache["layers"]}

        def decode_multi(params, tok, active, page_table, lengths, pools,
                         emitted, budgets, eos_ids, rng, adapters, horizon,
                         do_sample, temperature, top_k, top_p):
            """``horizon`` fused decode steps as ONE dispatch (lax.scan):
            token feedback, the active mask, per-slot lengths and EOS /
            budget freezing all stay on device — the host sees one token
            block per horizon instead of one round-trip per token (the
            continuous-batching counterpart of generate()'s
            _decode_loop_fn).

            Per-slot freeze rules, matching the scheduler's host logic
            exactly so fused output is token-identical to the single-step
            path: a slot freezes after sampling ``eos_ids[slot]`` (-1 =
            no eos) or once its cumulative ``emitted`` count reaches
            ``budgets[slot]`` (= remaining_new at the chain's start;
            ``emitted`` is a carry so chained dispatches continue the
            count). Frozen slots write no K/V, advance no length, and
            emit ``valid=False`` rows."""
            def body(carry, i):
                tok, active, lengths, emitted, layers = carry
                cache = {"layers": layers, "page_table": page_table,
                         "lengths": lengths, "active": active}
                # adapter factors are scan CONSTANTS (closure capture of
                # the traced outer arg), never carries — each step
                # re-gathers by the same per-slot ids
                if adapters is not None:
                    cache["adapters"] = adapters
                logits, cache = module.apply(
                    {"params": materialize(params)}, tok[:, None],
                    cache=cache)
                nxt = _sample_tokens(logits[:, 0],
                                     jax.random.fold_in(rng, i), do_sample,
                                     temperature, top_k, top_p)
                nxt = jnp.where(active, nxt.astype(jnp.int32), tok)
                emitted = emitted + active.astype(jnp.int32)
                new_active = active & (nxt != eos_ids) & (emitted < budgets)
                return (nxt, new_active, cache["lengths"], emitted,
                        cache["layers"]), (nxt, active)
            (tok, active, lengths, emitted, layers), (toks, valid) = \
                jax.lax.scan(body,
                             (tok, active, lengths, emitted,
                              pools["layers"]),
                             jnp.arange(horizon))
            return (toks.T, valid.T, tok, active, lengths, emitted,
                    {"layers": layers})

        def verify_multi(params, tok, drafts, widths, active, page_table,
                         lengths, pools, emitted, budgets, eos_ids,
                         adapters):
            """Teacher-forced speculative verification: score K drafted
            tokens per slot in ONE forward over the paged cache (the
            draft/verify counterpart of ``decode_multi``'s scan).

            The input row is ``[tok, d_1 .. d_K]`` (K+1 columns): column
            j's logits are the target model's prediction for the
            (j+1)-th new token, so the longest prefix of drafts matching
            the greedy argmax is accepted and the first non-matching
            argmax is emitted as the bonus/correction token — by
            construction exactly the token sequential greedy decode
            would have produced, so acceptance only changes SPEED, never
            output.  K/V is written for all ``widths[s]+1`` columns;
            ``lengths_end`` rewinds to count only emitted tokens (the
            host mirrors with ``PagedKVManager.truncate_slot``) and the
            stale tail is overwritten before any later gather can read
            it.  EOS / budget freezing replays ``decode_multi``'s rules
            over the emitted stream so the carries stay
            loop-compatible."""
            slots, K = drafts.shape
            x = jnp.concatenate([tok[:, None], drafts], axis=1)
            cols = jnp.where(active, widths + 1, 0)
            cache = dict(pools, page_table=page_table, lengths=lengths,
                         active=active, widths=cols)
            if adapters is not None:
                cache["adapters"] = adapters
            logits, cache = module.apply({"params": materialize(params)},
                                         x, cache=cache)
            # the greedy contract: fp32 argmax, ties to the lowest id
            g = jnp.argmax(logits.astype(jnp.float32),
                           axis=-1).astype(jnp.int32)       # [slots, K+1]
            jK = jnp.arange(K)
            ok = (drafts == g[:, :K]) & (jK[None, :] < widths[:, None])
            a = jnp.cumprod(ok.astype(jnp.int32), axis=1).sum(axis=1)
            bonus = jnp.take_along_axis(g, a[:, None], axis=1)
            jW = jnp.arange(K + 1)
            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros((slots, 1), jnp.int32)], axis=1)
            # emitted stream: accepted drafts then the bonus token
            # (positions past it are frozen padding, masked by `valid`)
            out_toks = jnp.where(jW[None, :] < a[:, None], drafts_pad,
                                 bonus)
            nominal = a + 1
            is_eos = (out_toks == eos_ids[:, None]) & \
                (eos_ids[:, None] >= 0)
            has_eos = jnp.any(is_eos, axis=1)
            n_eos = jnp.where(has_eos, jnp.argmax(is_eos, axis=1) + 1,
                              K + 2)
            n = jnp.minimum(jnp.minimum(nominal, n_eos),
                            jnp.maximum(budgets - emitted, 0))
            n = jnp.where(active, n, 0)
            valid = jW[None, :] < n[:, None]
            emitted_end = emitted + n
            last = jnp.take_along_axis(
                out_toks, jnp.maximum(n - 1, 0)[:, None], axis=1)[:, 0]
            tok_end = jnp.where(n > 0, last, tok)
            emitted_eos = has_eos & (n_eos <= n)
            active_end = active & ~emitted_eos & (emitted_end < budgets)
            lengths_end = lengths + n
            accepted = jnp.minimum(a, n)
            return (out_toks, valid, tok_end, active_end, lengths_end,
                    emitted_end, accepted, {"layers": cache["layers"]})

        def decode_multi_policy(params, tok, active, page_table, lengths,
                                pools, emitted, budgets, eos_ids, keys,
                                tok_base, temps, top_ks, top_ps, rep_pens,
                                pres_pens, freq_pens, counts, mask,
                                horizon):
            """``decode_multi`` with the per-slot decoding-policy
            pipeline (serving/sampling/pipeline.py) in place of the
            static-args sampler.  EVERY policy knob is a traced
            per-slot array — temperature, top-k/p, the three history
            penalties over the ``counts`` token table, the grammar
            ``mask``, and a per-request PRNG key + absolute token base
            — so a mixed greedy/sampled/penalized/constrained batch is
            ONE compiled signature per horizon bucket and param churn
            never recompiles.  Token ``tok_base[s] + emitted[s]`` keys
            the slot's fold_in stream: batching-independent and
            replayable across preemption/failover.  Freeze rules are
            decode_multi's exactly; ``counts`` rides the carry so
            penalties see tokens sampled earlier in the same chain."""
            slots = tok.shape[0]

            def body(carry, i):
                tok, active, lengths, emitted, counts, layers = carry
                cache = {"layers": layers, "page_table": page_table,
                         "lengths": lengths, "active": active}
                logits, cache = module.apply(
                    {"params": materialize(params)}, tok[:, None],
                    cache=cache)
                x = policy_pipeline.process_logits(
                    logits[:, 0], counts, mask, temps, top_ks, top_ps,
                    rep_pens, pres_pens, freq_pens)
                nxt = policy_pipeline.sample_processed(
                    x, keys, tok_base + emitted, temps).astype(jnp.int32)
                nxt = jnp.where(active, nxt, tok)
                counts = counts.at[jnp.arange(slots), nxt].add(
                    active.astype(jnp.int32))
                emitted = emitted + active.astype(jnp.int32)
                new_active = active & (nxt != eos_ids) & (emitted < budgets)
                return (nxt, new_active, cache["lengths"], emitted,
                        counts, cache["layers"]), (nxt, active)
            (tok, active, lengths, emitted, counts, layers), \
                (toks, valid) = jax.lax.scan(
                    body, (tok, active, lengths, emitted, counts,
                           pools["layers"]), jnp.arange(horizon))
            return (toks.T, valid.T, tok, active, lengths, emitted,
                    counts, {"layers": layers})

        def verify_multi_policy(params, tok, drafts, widths, active,
                                page_table, lengths, pools, emitted,
                                budgets, eos_ids, keys, tok_base, temps,
                                top_ks, top_ps, rep_pens, pres_pens,
                                freq_pens, counts, mask):
            """Lossless speculative verification under the decoding
            policy: one teacher-forced forward (identical to
            ``verify_multi``), then a scan over the K+1 logit columns
            applying leftover-probability rejection sampling per slot.
            Our drafters propose point-mass tokens (no draft probs), so
            the accept rule collapses to ``u < p_target(draft)`` and a
            rejection resamples the residual (p_target with the draft
            zeroed, renormalized) — by construction the emitted stream
            is distributed EXACTLY as sequential ``decode_multi_policy``
            (frequency oracle pins this).  Greedy rows (temp == 0) keep
            the legacy token-exact rule: accept iff fp32 argmax ==
            draft, the correction token IS the argmax.  Column ``j``
            draws from ``fold_in(key, tok_base + j)`` sub-streams;
            counts carry accepted drafts so penalties stay causal
            within the round.  Assembly (eos/budget clamping, rewound
            lengths, carries) matches ``verify_multi`` line for line."""
            slots, K = drafts.shape
            x_in = jnp.concatenate([tok[:, None], drafts], axis=1)
            cols = jnp.where(active, widths + 1, 0)
            cache = dict(pools, page_table=page_table, lengths=lengths,
                         active=active, widths=cols)
            logits, cache = module.apply({"params": materialize(params)},
                                         x_in, cache=cache)
            drafts_pad = jnp.concatenate(
                [drafts, jnp.zeros((slots, 1), jnp.int32)], axis=1)

            def col(carry, j):
                counts_c, accepting, acc, bonus = carry
                lg = policy_pipeline.process_logits(
                    logits[:, j], counts_c, mask, temps, top_ks, top_ps,
                    rep_pens, pres_pens, freq_pens)
                d = drafts_pad[:, j]
                is_draft = (j < widths) & accepting
                is_bonus = (j == widths) & accepting
                accept_col, fallback = policy_pipeline.accept_or_resample(
                    lg, d, keys, tok_base + j, temps)
                bonus_col = policy_pipeline.bonus_sample(
                    lg, keys, tok_base + j, temps)
                draft_accept = is_draft & accept_col
                reject_now = is_draft & ~accept_col
                bonus = jnp.where(reject_now, fallback,
                                  jnp.where(is_bonus, bonus_col, bonus))
                counts_c = counts_c.at[jnp.arange(slots), d].add(
                    draft_accept.astype(jnp.int32))
                acc = acc + draft_accept.astype(jnp.int32)
                return (counts_c, draft_accept, acc, bonus), None
            (counts, _, a, bonus), _ = jax.lax.scan(
                col, (counts, active, jnp.zeros(slots, jnp.int32),
                      jnp.zeros(slots, jnp.int32)), jnp.arange(K + 1))
            jW = jnp.arange(K + 1)
            out_toks = jnp.where(jW[None, :] < a[:, None], drafts_pad,
                                 bonus[:, None])
            nominal = a + 1
            is_eos = (out_toks == eos_ids[:, None]) & \
                (eos_ids[:, None] >= 0)
            has_eos = jnp.any(is_eos, axis=1)
            n_eos = jnp.where(has_eos, jnp.argmax(is_eos, axis=1) + 1,
                              K + 2)
            n = jnp.minimum(jnp.minimum(nominal, n_eos),
                            jnp.maximum(budgets - emitted, 0))
            n = jnp.where(active, n, 0)
            valid = jW[None, :] < n[:, None]
            emitted_end = emitted + n
            last = jnp.take_along_axis(
                out_toks, jnp.maximum(n - 1, 0)[:, None], axis=1)[:, 0]
            tok_end = jnp.where(n > 0, last, tok)
            emitted_eos = has_eos & (n_eos <= n)
            active_end = active & ~emitted_eos & (emitted_end < budgets)
            lengths_end = lengths + n
            accepted = jnp.minimum(a, n)
            return (out_toks, valid, tok_end, active_end, lengths_end,
                    emitted_end, accepted, counts,
                    {"layers": cache["layers"]})

        # every in/out array family gets its serving sharding
        # (serving/sharding.py): pools shard kv_heads over `model`,
        # slot carries / token blocks / the page table shard slots over
        # `data`. out_shardings stay PINNED so the donated round-trip
        # keeps ONE jit signature per bucket: an inferred sharding that
        # differed from init_paged_cache's (or from the staged host
        # inputs') would compile a second copy on the first feedback
        # call — same invariant as the replicated PR-1 design, now per
        # axis family
        shd = self._serving_shardings()
        slot, block, pool = shd.slot, shd.block, shd.pool
        self._paged_prefill_fn = jax.jit(prefill, donate_argnums=(6,),
                                         out_shardings=(shd.logits, pool))
        # the sequence-parallel twin only exists when the mesh has a
        # usable sequence axis (resolve_sequence_plan); its pools /
        # logits round-trip is pinned identically, so landed pages and
        # boundary logits are drop-in for everything downstream
        self._paged_prefill_sp_fn = jax.jit(
            prefill_sp, donate_argnums=(6,),
            out_shardings=(shd.logits, pool)) if seq_plan.usable else None
        self._paged_decode_fn = jax.jit(decode, donate_argnums=(5,),
                                        static_argnums=(7, 8, 9, 10),
                                        out_shardings=(slot, pool))
        # one compiled signature per (horizon, sampling) combo — the
        # scheduler quantizes horizons to a small bucket set so the
        # compile count stays bounded across slot churn
        self._paged_decode_multi_fn = jax.jit(
            decode_multi, donate_argnums=(5,),
            static_argnums=(11, 12, 13, 14, 15),
            out_shardings=(block, block, slot, slot, slot, slot, pool))
        # K is baked into the drafts shape, so the compile count is
        # bounded by the scheduler's spec-K bucket set (greedy-only: no
        # sampling statics)
        self._paged_verify_fn = jax.jit(
            verify_multi, donate_argnums=(7,),
            out_shardings=(block, block, slot, slot, slot, slot, slot,
                           pool))
        # policy twins: horizon is the ONLY static — every sampling /
        # penalty / grammar knob is a traced per-slot array, so the
        # compile count stays bounded by the horizon/K bucket sets
        # across arbitrary per-request param churn.  counts donates and
        # round-trips (the in-chain penalty carry); mask is read-only.
        self._paged_decode_policy_fn = jax.jit(
            decode_multi_policy, donate_argnums=(5, 17),
            static_argnums=(19,),
            out_shardings=(block, block, slot, slot, slot, slot, block,
                           pool))
        self._paged_verify_policy_fn = jax.jit(
            verify_multi_policy, donate_argnums=(7, 19),
            out_shardings=(block, block, slot, slot, slot, slot, slot,
                           block, pool))

    def copy_page(self, pools, src_page, dst_page):
        """Copy ONE KV page across every layer's pool (the prefix
        cache's copy-on-write primitive: a partially matched cached page
        is duplicated into a fresh private page before the owning slot
        may append to it).  Page ids are traced scalars, so churn in
        which pages get copied never adds a jit signature — ONE compile
        per serving config, like the other paged primitives."""
        if getattr(self, "_copy_page_fn", None) is None:
            # a page copy moves one index of the GLOBAL page dim; the
            # kv-head shards copy in place on their own devices (no
            # cross-device traffic), so the pool sharding is pinned
            # through like every other primitive.  Copying EVERY leaf of
            # the layer dict (not just k/v payload) is what keeps a
            # quantized pool's per-row scales welded to their page: a
            # COW copy that moved payload without scales would dequantize
            # the private copy with the ORIGINAL page's scales forever
            def copy(pools, src, dst):
                return {"layers": [
                    {name: arr.at[dst].set(arr[src])
                     for name, arr in L.items()}
                    for L in pools["layers"]]}
            pool_sh = self._serving_shardings().pool

            self._copy_page_fn = jax.jit(copy, donate_argnums=(0,),
                                         out_shardings=pool_sh)
        args = (pools, jnp.int32(src_page), jnp.int32(dst_page))
        if self._comm_capture is not None:
            self._capture_comm_sig("copy_page", "copy_page",
                                   "_copy_page_fn", args)
        with dist.mesh_scope(self.mesh):
            return self._dispatch("copy_page", self._copy_page_fn, *args)

    def serving_page_copy_compile_count(self):
        """Compiled signatures behind copy_page (stays <= 1 per serving
        config: cache hits/misses must never grow the compile set).
        Reads ``tracing.jit_cache_size`` — the ONE compile-count
        definition shared with the train engine, the goodput ledger and
        the recompile watchdog."""
        return jit_cache_size(getattr(self, "_copy_page_fn", None))

    def export_page_chain(self, pools, page_ids):
        """Gather a page chain out of the paged pool as a transferable
        payload: one ``[n, page_size, kv_heads, d]`` leaf per pool leaf
        per layer, where ``n == len(page_ids)``.  The disaggregated
        handoff transport's READ half — the payload either rides
        ``jax.device_put`` to a sibling pool in-process or gets staged
        to host and framed onto a cross-process KV sidecar fd.

        Gathering EVERY leaf of each layer dict (not just k/v payload)
        is what keeps a quantized pool's per-row scales welded to their
        page across a transfer: a chain that moved int8/fp8 payload
        without its scale rows would dequantize on the destination with
        whatever stale scales its fresh pages held.  Same rule as
        ``copy_page``, for the same reason.

        ``page_ids`` must be padded to a power-of-two chunk bucket
        (``transport.chunk_bucket``) — pad with any in-range id (0 is
        conventional; the extra gathered page is trimmed on host).  Ids
        are a traced operand, so churn in WHICH pages transfer never
        adds a signature: exactly one compile per bucket length."""
        if getattr(self, "_chain_export_fn", None) is None:
            def export(pools, ids):
                return [{name: arr[ids] for name, arr in L.items()}
                        for L in pools["layers"]]
            pool_sh = self._serving_shardings().pool
            # payload leaves keep the pool's layout ([page-dim, ps,
            # kvh, d] with kv-heads model-sharded), so the pool
            # sharding pins through — device_put to the destination's
            # identical NamedSharding is then resharding-free
            self._chain_export_fn = jax.jit(export, out_shardings=pool_sh)
        args = (pools, jnp.asarray(page_ids, jnp.int32))
        with dist.mesh_scope(self.mesh):
            return self._dispatch("chain_export", self._chain_export_fn,
                                  *args)

    def import_page_chain(self, pools, payload, page_ids):
        """Scatter an exported chain payload into this pool at
        ``page_ids`` (the destination's freshly allocated pages) and
        return the updated pools — the transport's WRITE half, the
        functional-update twin of ``export_page_chain``.

        ``page_ids`` must be padded to the payload's chunk bucket with
        ``num_pages`` (one past the last page): ``mode="drop"`` masks
        the padded writes, the same out-of-range discipline every paged
        write primitive rides.  Donates the pools like every other
        pool-mutating primitive; one compile per bucket length."""
        if getattr(self, "_chain_import_fn", None) is None:
            def imp(pools, payload, ids):
                return {"layers": [
                    {name: arr.at[ids].set(pl[name], mode="drop")
                     for name, arr in L.items()}
                    for L, pl in zip(pools["layers"], payload)]}
            pool_sh = self._serving_shardings().pool
            self._chain_import_fn = jax.jit(imp, donate_argnums=(0,),
                                            out_shardings=pool_sh)
        args = (pools, payload, jnp.asarray(page_ids, jnp.int32))
        with dist.mesh_scope(self.mesh):
            return self._dispatch("chain_import", self._chain_import_fn,
                                  *args)

    def serving_chain_export_compile_count(self):
        """Compiled signatures behind export_page_chain — one per
        power-of-two chunk bucket a transfer ever used, NOT per chain
        length (the bucket pins assert this stays flat across handoff
        churn)."""
        return jit_cache_size(getattr(self, "_chain_export_fn", None))

    def serving_chain_import_compile_count(self):
        """Compiled signatures behind import_page_chain — one per
        chunk bucket, the mirror of the export pin."""
        return jit_cache_size(getattr(self, "_chain_import_fn", None))

    # -------------------------------------- comm/compile observability
    def set_compile_watchdog(self, watchdog):
        """Install a :class:`tracing.CompileWatchdog` (None removes
        it): every serving dispatch whose jit signature cache grows
        records a ``compile`` span, and steady-state growth fires the
        watchdog's recompile detection.  Pure host bookkeeping around
        the dispatch — it never changes what compiles."""
        self._compile_watchdog = watchdog

    def _dispatch(self, name, fn, *args, detail=None):
        """Run one serving-primitive dispatch, feeding the compile
        watchdog when the callable's signature cache grew across the
        call (jit compiles synchronously at dispatch, so this call's
        wall time IS compile + dispatch)."""
        wd = self._compile_watchdog
        if wd is None:
            return fn(*args)
        n0 = jit_cache_size(fn)
        t0 = time.monotonic()
        out = fn(*args)
        n1 = jit_cache_size(fn)
        if n1 > n0:
            wd.on_compile(name, n1 - n0, t0, time.monotonic(),
                          detail=detail)
        return out

    def enable_comm_telemetry(self, enabled=True):
        """Arm (or disarm) HLO comm-ledger capture: each serving
        primitive records the arg specs (shapes/dtypes/shardings +
        statics) of every distinct signature it dispatches, so
        :meth:`comm_ledger` can later re-lower and statically count the
        collective bytes of exactly the executables serving runs.  The
        capture itself is a dict lookup per dispatch; the analysis
        compile happens only inside :meth:`comm_ledger`."""
        if enabled:
            # re-arming keeps both the capture and the analyzed-ledger
            # cache: signatures are (name, label)-keyed and stable, so
            # a fleet of schedulers sharing one engine (each __init__
            # re-arms) must not force a re-compile sweep per replica
            if self._comm_capture is None:
                self._comm_capture = {}
        else:
            self._comm_capture = None
            self._comm_ledger_cache = {}

    def _capture_comm_sig(self, name, label, fn_attr, args, statics=()):
        cap = self._comm_capture
        if cap is None:
            return
        # geometry rides the ARRAY arg shapes (slots/pages/chunk): two
        # schedulers sharing one engine with different geometry are
        # distinct executables and must ledger separately even under
        # the same display label
        geom = tuple(np.shape(a) for a in args
                     if isinstance(a, (np.ndarray, jax.Array)))
        if (name, label, geom) in cap:
            return
        # ShapeDtypeStructs with committed shardings: enough for
        # .lower() to reproduce the exact partitioned executable
        # without holding (donated!) buffers alive.  An UNCOMMITTED
        # single-device array (the rng key from jax.random.split) is
        # normalized to replicated-on-mesh — that is what jit does
        # with it at real dispatch, and a literal single-device spec
        # would make the analysis lowering reject the mesh-sharded
        # co-arguments
        mesh_devs = frozenset(
            d.id for d in np.asarray(self.mesh.devices).flat)

        def spec(x):
            sh = getattr(x, "sharding", None)
            if sh is not None:
                try:
                    if frozenset(d.id for d in sh.device_set) != \
                            mesh_devs:
                        sh = NamedSharding(self.mesh, P())
                except Exception:
                    sh = None
            return jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                        sharding=sh)

        cap[(name, label, geom)] = (fn_attr, jax.tree.map(spec, args),
                                    statics)

    def comm_ledger(self, refresh=False):
        """Static HLO comm ledger per captured serving signature
        (``profiling/comm_ledger.py``): ``{label: ledger}`` where the
        label carries the primitive and its statics (e.g.
        ``decode_multi[h=8]``).  First call per signature pays one
        analysis re-compile (lower -> compile -> parse); results are
        cached until ``refresh=True`` or :meth:`enable_comm_telemetry`
        is toggled.  Empty dict when capture is off or nothing
        dispatched yet."""
        if self._comm_capture is None:
            return {}
        from deepspeed_tpu.profiling import comm_ledger as _cl
        out = {}
        for key, (fn_attr, specs, statics) in \
                list(self._comm_capture.items()):
            name, label = key[0], key[1]
            # two geometries under one display label (engine shared by
            # differently-sized schedulers) stay distinct entries
            disp = label
            n = 2
            while disp in out:
                disp = f"{label}@{n}"
                n += 1
            cached = self._comm_ledger_cache.get(key)
            if cached is not None and not refresh:
                out[disp] = cached
                continue
            fn = getattr(self, fn_attr, None)
            if fn is None:
                # the serving fns were rebuilt (slot-family resharding)
                self._build_serving_fns()
                fn = getattr(self, fn_attr, None)
                if fn is None:
                    continue
            with self._serving_scope():
                led = _cl.ledger_for(fn, *specs, *statics,
                                     mesh=self.mesh)
            self._comm_ledger_cache[key] = led
            out[disp] = led
        return out

    def prefill_into_slots(self, ids_chunk, slot, n_valid, page_table,
                           lengths, pools, adapter_ids=None, adapters=None):
        """One prefill chunk of one slot: write the chunk's K/V through
        the page table and return (boundary logits [vocab], new pools).
        ``ids_chunk`` is [1, chunk] (padded past ``n_valid``); the pages
        covering positions lengths[slot] .. +n_valid must be allocated.

        The chunk's positions (and rotary offsets) start at
        ``lengths[slot]``, which need not be 0 OR page-aligned: a
        prefix-cache hit seeds ``lengths[slot]`` to the cached boundary
        and prefill resumes there with this same single jit signature —
        per-row start offsets are data (the lengths array), never
        shape."""
        assert self.params is not None, "set_params/init_params first"
        shd = self._serving_shardings(num_slots=int(np.shape(lengths)[0]))
        if getattr(self, "_paged_prefill_fn", None) is None:
            self._build_serving_fns()
        rep, slot_sh, blk = shd.replicated, shd.slot, shd.block
        ids_chunk, slot, n_valid, page_table, lengths = \
            self._stage_host_inputs([
                (ids_chunk, np.int32, rep), (slot, np.int32, rep),
                (n_valid, np.int32, rep), (page_table, np.int32, blk),
                (lengths, np.int32, slot_sh)])
        # multi-tenant LoRA: the stacked factor pack is already device-
        # committed (AdapterStore caches it); only the per-slot ids are
        # per-dispatch host state. None = leafless side input, so base-
        # only traffic keeps the exact pre-tenancy signature.
        ad = None
        if adapters is not None:
            (ids_arr,) = self._stage_host_inputs(
                [(adapter_ids, np.int32, slot_sh)])
            ad = dict(adapters, ids=ids_arr)
        args = (self.params, ids_chunk, slot, n_valid, page_table,
                lengths, pools, ad)
        if self._comm_capture is not None:   # label cost only when armed
            self._capture_comm_sig(
                "prefill", f"prefill[chunk={np.shape(ids_chunk)[1]}]",
                "_paged_prefill_fn", args)
        with self._serving_scope():
            return self._dispatch("prefill", self._paged_prefill_fn,
                                  *args)

    def seq_parallel_plan(self):
        """The resolved sequence-parallel prefill plan for this engine's
        mesh + model (``serving.sharding.resolve_sequence_plan``),
        cached — the scheduler reads it once at construction to decide
        whether a ``seq_parallel_threshold`` can route anywhere, and
        health() surfaces it."""
        if getattr(self, "_seq_plan", None) is None:
            heads, kv_heads = self._model_head_counts()
            self._seq_plan = resolve_sequence_plan(
                self.mesh, self.serving_sharding,
                num_heads=heads or 1, num_kv_heads=kv_heads or 1)
        return self._seq_plan

    def prefill_sequence_parallel(self, ids_chunk, slot, n_valid,
                                  page_table, lengths, pools):
        """Sequence-parallel twin of :meth:`prefill_into_slots`: same
        arguments, same ``(boundary logits [vocab], new pools)`` return,
        same paged landing — but ``ids_chunk`` stages SHARDED over the
        sequence mesh axis, the per-token pipeline (embedding, rotary,
        MLP) runs 1/P-sized per device under GSPMD, and the chunk's
        attention runs through the Ulysses all-to-all (or ring
        ppermute) transport per the resolved plan.  The chunk length
        must be a multiple of the axis size (the scheduler's power-of-
        two chunk buckets >= the axis size guarantee it).  Pages land
        in the standard pool, so decode / prefix-cache donation / COW /
        spec verify / handoff downstream never notice which path
        prefilled them."""
        assert self.params is not None, "set_params/init_params first"
        plan = self.seq_parallel_plan()
        assert plan.usable, \
            f"no usable sequence axis on this mesh: {plan.reason}"
        chunk = int(np.shape(ids_chunk)[1])
        assert chunk % plan.size == 0, \
            (f"chunk length {chunk} must be a multiple of the "
             f"'{plan.axis}' axis size {plan.size}")
        shd = self._serving_shardings(num_slots=int(np.shape(lengths)[0]))
        if getattr(self, "_paged_prefill_sp_fn", None) is None:
            self._build_serving_fns()
        rep, slot_sh, blk = shd.replicated, shd.slot, shd.block
        seq_sh = NamedSharding(self.mesh, P(None, plan.axis))
        ids_chunk, slot, n_valid, page_table, lengths = \
            self._stage_host_inputs([
                (ids_chunk, np.int32, seq_sh), (slot, np.int32, rep),
                (n_valid, np.int32, rep), (page_table, np.int32, blk),
                (lengths, np.int32, slot_sh)])
        args = (self.params, ids_chunk, slot, n_valid, page_table,
                lengths, pools)
        if self._comm_capture is not None:
            self._capture_comm_sig(
                "seq_prefill", f"seq_prefill[chunk={chunk}]",
                "_paged_prefill_sp_fn", args)
        with self._serving_scope():
            return self._dispatch("seq_prefill",
                                  self._paged_prefill_sp_fn, *args)

    def decode_step(self, toks, active, page_table, lengths, pools,
                    do_sample=False, temperature=1.0, top_k=0, top_p=1.0):
        """One continuous-batching decode step over ALL slots: write each
        active slot's token K/V at position lengths[slot], attend through
        the page table, and return (next tokens [slots] i32, new pools).
        Inactive slots pass through untouched (writes dropped)."""
        assert self.params is not None, "set_params/init_params first"
        shd = self._serving_shardings(num_slots=int(np.shape(lengths)[0]))
        if getattr(self, "_paged_decode_fn", None) is None:
            self._build_serving_fns()
        self._rng, rng = jax.random.split(self._rng)
        toks, active, page_table, lengths = self._stage_host_inputs([
            (toks, np.int32, shd.slot), (active, bool, shd.slot),
            (page_table, np.int32, shd.block),
            (lengths, np.int32, shd.slot)])
        args = (self.params, toks, active, page_table, lengths, pools,
                rng)
        statics = (bool(do_sample), float(temperature), int(top_k),
                   float(top_p))
        if self._comm_capture is not None:
            self._capture_comm_sig(
                "decode", "decode" + _sampling_label(*statics),
                "_paged_decode_fn", args, statics)
        with self._serving_scope():
            return self._dispatch("decode", self._paged_decode_fn,
                                  *args, *statics)

    def _stage_host_inputs(self, triples):
        """Move the per-dispatch host arrays to their committed serving
        shardings in ONE batched ``device_put`` (per-array puts cost
        ~0.2 ms each of pure dispatch machinery on the CPU rig — at 7-9
        small arrays per decode/verify round that overhead was rivaling
        the model compute itself).  Each triple is ``(value, dtype,
        sharding)``; slot-indexed arrays stage to the data-axis
        sharding, the page table to the block sharding, scalars to
        replicated.  Device-resident carries from a previous dispatch
        pass through untouched: they are already committed to their
        exact sharding by ``out_shardings``, so barrier and chained
        dispatches share one compiled signature per bucket."""
        staged = [x if isinstance(x, jax.Array) and x.dtype == dt
                  else np.asarray(x, dt) for x, dt, _ in triples]
        return jax.device_put(tuple(staged),
                              tuple(sh for _, _, sh in triples))

    def decode_multi(self, toks, active, page_table, lengths, pools, *,
                     horizon, budgets, eos_ids, emitted=None,
                     do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                     adapter_ids=None, adapters=None):
        """``horizon`` continuous-batching decode steps as ONE dispatch.

        Returns ``(toks_block [slots, H] i32, valid [slots, H] bool,
        tok_end, active_end, lengths_end, emitted_end, new pools)``.
        ``valid[s, i]`` marks a genuinely sampled token; rows after a
        slot hits its eos id or exhausts ``budgets[slot]`` are frozen
        padding. The ``*_end`` carries are device arrays that can feed
        the next ``decode_multi`` call directly (the overlapped serving
        loop chains horizons without a host round-trip); ``emitted``
        must then be threaded through so budget accounting spans the
        chain. ``toks``/``active``/``lengths`` accept host numpy or the
        previous call's device carries interchangeably."""
        assert self.params is not None, "set_params/init_params first"
        # host inputs get the SAME committed shardings the *_end carries
        # come back with (slot arrays over `data`, table over `data`),
        # so barrier dispatches and chained dispatches share one
        # compiled signature per horizon bucket
        shd = self._serving_shardings(num_slots=int(np.shape(budgets)[0]))
        if getattr(self, "_paged_decode_multi_fn", None) is None:
            self._build_serving_fns()
        self._rng, rng = jax.random.split(self._rng)
        if emitted is None:
            emitted = np.zeros(np.shape(budgets), np.int32)
        slot, blk = shd.slot, shd.block
        toks, active, page_table, lengths, emitted, budgets, eos_ids = \
            self._stage_host_inputs([
                (toks, np.int32, slot), (active, bool, slot),
                (page_table, np.int32, blk), (lengths, np.int32, slot),
                (emitted, np.int32, slot), (budgets, np.int32, slot),
                (eos_ids, np.int32, slot)])
        ad = None
        if adapters is not None:
            (ids_arr,) = self._stage_host_inputs(
                [(adapter_ids, np.int32, slot)])
            ad = dict(adapters, ids=ids_arr)
        args = (self.params, toks, active, page_table, lengths, pools,
                emitted, budgets, eos_ids, rng, ad)
        statics = (int(horizon), bool(do_sample), float(temperature),
                   int(top_k), float(top_p))
        if self._comm_capture is not None:
            self._capture_comm_sig(
                "decode_multi",
                f"decode_multi[h={int(horizon)}]"
                + _sampling_label(*statics[1:]),
                "_paged_decode_multi_fn", args, statics)
        with self._serving_scope():
            return self._dispatch(
                "decode_multi", self._paged_decode_multi_fn,
                *args, *statics,
                detail=None if self._compile_watchdog is None
                else {"horizon": int(horizon)})

    def verify_multi(self, toks, drafts, active, page_table, lengths,
                     pools, *, widths, budgets, eos_ids, emitted=None,
                     adapter_ids=None, adapters=None):
        """Speculative-decode verification: score ``drafts`` [slots, K]
        proposed tokens per slot in ONE teacher-forced dispatch over the
        paged cache, accept the longest greedy-matching prefix plus the
        target model's one bonus/correction token.

        ``widths[s] <= K`` is the real draft count for slot ``s`` (the
        rest of the row is padding); pages covering positions
        ``lengths[s] .. lengths[s] + widths[s]`` must be allocated.
        Greedy-only by design: acceptance compares against the
        ``temperature=0`` argmax contract of ``sample_from_logits``, so
        spec-decode output is token-exact vs ``generate()``.

        Returns ``(toks_block [slots, K+1] i32, valid [slots, K+1]
        bool, tok_end, active_end, lengths_end, emitted_end,
        accepted [slots] i32, new pools)``.  The carries have exactly
        ``decode_multi``'s shapes/meanings — ``lengths_end`` already
        reflects the KV rollback (count of emitted tokens only), so a
        follow-up dispatch can run straight off them; the host mirrors
        the rollback with ``PagedKVManager.truncate_slot``.  One
        compiled signature per K (the scheduler's spec-K bucket set)."""
        assert self.params is not None, "set_params/init_params first"
        shd = self._serving_shardings(num_slots=int(np.shape(budgets)[0]))
        if getattr(self, "_paged_verify_fn", None) is None:
            self._build_serving_fns()
        if emitted is None:
            emitted = np.zeros(np.shape(budgets), np.int32)
        slot, blk = shd.slot, shd.block
        (toks, drafts, widths, active, page_table, lengths, emitted,
         budgets, eos_ids) = self._stage_host_inputs([
             (toks, np.int32, slot), (drafts, np.int32, blk),
             (widths, np.int32, slot), (active, bool, slot),
             (page_table, np.int32, blk), (lengths, np.int32, slot),
             (emitted, np.int32, slot), (budgets, np.int32, slot),
             (eos_ids, np.int32, slot)])
        ad = None
        if adapters is not None:
            (ids_arr,) = self._stage_host_inputs(
                [(adapter_ids, np.int32, slot)])
            ad = dict(adapters, ids=ids_arr)
        args = (self.params, toks, drafts, widths, active, page_table,
                lengths, pools, emitted, budgets, eos_ids, ad)
        k = int(np.shape(drafts)[1])
        if self._comm_capture is not None:
            self._capture_comm_sig("verify", f"verify[k={k}]",
                                   "_paged_verify_fn", args)
        with self._serving_scope():
            return self._dispatch("verify", self._paged_verify_fn,
                                  *args,
                                  detail=None if self._compile_watchdog
                                  is None else {"k": k})

    def _stage_policy_inputs(self, shd, keys, tok_base, temps, top_ks,
                             top_ps, rep_pens, pres_pens, freq_pens,
                             counts, mask):
        """Stage the per-slot decoding-policy arrays (one batched
        device_put, same committed shardings every dispatch): the raw
        uint32 request keys and every pipeline knob as slot lanes, the
        counts/mask tables slot-major like the page table."""
        slot, blk = shd.slot, shd.block
        return self._stage_host_inputs([
            (keys, np.uint32, blk), (tok_base, np.int32, slot),
            (temps, np.float32, slot), (top_ks, np.int32, slot),
            (top_ps, np.float32, slot), (rep_pens, np.float32, slot),
            (pres_pens, np.float32, slot), (freq_pens, np.float32, slot),
            (counts, np.int32, blk), (mask, bool, blk)])

    def decode_multi_policy(self, toks, active, page_table, lengths,
                            pools, *, horizon, budgets, eos_ids, keys,
                            tok_base, temps, top_ks, top_ps, rep_pens,
                            pres_pens, freq_pens, counts, mask,
                            emitted=None):
        """``decode_multi`` under the per-slot decoding policy.  Same
        carries and return shape plus a ``counts`` carry before the
        pools: ``(toks_block, valid, tok_end, active_end, lengths_end,
        emitted_end, counts_end, pools)``.  All policy knobs are traced
        per-slot arrays (see ``_build_serving_fns``) — ONE compiled
        signature per horizon bucket regardless of the request mix, so
        ``serving_decode_multi_compile_count()`` (which sums the legacy
        and policy caches) stays within the bucket set across sampling-
        param churn.  ``counts``/``mask`` accept host numpy at a
        barrier or the previous call's device carry in a chain."""
        assert self.params is not None, "set_params/init_params first"
        shd = self._serving_shardings(num_slots=int(np.shape(budgets)[0]))
        if getattr(self, "_paged_decode_policy_fn", None) is None:
            self._build_serving_fns()
        if emitted is None:
            emitted = np.zeros(np.shape(budgets), np.int32)
        slot, blk = shd.slot, shd.block
        toks, active, page_table, lengths, emitted, budgets, eos_ids = \
            self._stage_host_inputs([
                (toks, np.int32, slot), (active, bool, slot),
                (page_table, np.int32, blk), (lengths, np.int32, slot),
                (emitted, np.int32, slot), (budgets, np.int32, slot),
                (eos_ids, np.int32, slot)])
        (keys, tok_base, temps, top_ks, top_ps, rep_pens, pres_pens,
         freq_pens, counts, mask) = self._stage_policy_inputs(
             shd, keys, tok_base, temps, top_ks, top_ps, rep_pens,
             pres_pens, freq_pens, counts, mask)
        args = (self.params, toks, active, page_table, lengths, pools,
                emitted, budgets, eos_ids, keys, tok_base, temps,
                top_ks, top_ps, rep_pens, pres_pens, freq_pens, counts,
                mask)
        if self._comm_capture is not None:
            self._capture_comm_sig(
                "decode_multi_policy",
                f"decode_multi_policy[h={int(horizon)}]",
                "_paged_decode_policy_fn", args, (int(horizon),))
        with self._serving_scope():
            return self._dispatch(
                "decode_multi_policy", self._paged_decode_policy_fn,
                *args, int(horizon),
                detail=None if self._compile_watchdog is None
                else {"horizon": int(horizon), "policy": True})

    def verify_multi_policy(self, toks, drafts, active, page_table,
                            lengths, pools, *, widths, budgets, eos_ids,
                            keys, tok_base, temps, top_ks, top_ps,
                            rep_pens, pres_pens, freq_pens, counts, mask,
                            emitted=None):
        """Lossless speculative verification under the decoding policy
        (leftover-probability rejection sampling; greedy rows keep the
        token-exact argmax rule).  ``verify_multi``'s contract with a
        ``counts`` carry before the pools: ``(toks_block, valid,
        tok_end, active_end, lengths_end, emitted_end, accepted,
        counts_end, pools)``.  One compiled signature per K bucket —
        sampling params are traced, so sampled+spec composes without
        recompiles (the gate ``ds_serve`` used to force off)."""
        assert self.params is not None, "set_params/init_params first"
        shd = self._serving_shardings(num_slots=int(np.shape(budgets)[0]))
        if getattr(self, "_paged_verify_policy_fn", None) is None:
            self._build_serving_fns()
        if emitted is None:
            emitted = np.zeros(np.shape(budgets), np.int32)
        slot, blk = shd.slot, shd.block
        (toks, drafts, widths, active, page_table, lengths, emitted,
         budgets, eos_ids) = self._stage_host_inputs([
             (toks, np.int32, slot), (drafts, np.int32, blk),
             (widths, np.int32, slot), (active, bool, slot),
             (page_table, np.int32, blk), (lengths, np.int32, slot),
             (emitted, np.int32, slot), (budgets, np.int32, slot),
             (eos_ids, np.int32, slot)])
        (keys, tok_base, temps, top_ks, top_ps, rep_pens, pres_pens,
         freq_pens, counts, mask) = self._stage_policy_inputs(
             shd, keys, tok_base, temps, top_ks, top_ps, rep_pens,
             pres_pens, freq_pens, counts, mask)
        args = (self.params, toks, drafts, widths, active, page_table,
                lengths, pools, emitted, budgets, eos_ids, keys,
                tok_base, temps, top_ks, top_ps, rep_pens, pres_pens,
                freq_pens, counts, mask)
        k = int(np.shape(drafts)[1])
        if self._comm_capture is not None:
            self._capture_comm_sig("verify_policy",
                                   f"verify_policy[k={k}]",
                                   "_paged_verify_policy_fn", args)
        with self._serving_scope():
            return self._dispatch(
                "verify_policy", self._paged_verify_policy_fn, *args,
                detail=None if self._compile_watchdog is None
                else {"k": k, "policy": True})

    def sample_from_logits_policy(self, logits, keys, tok_idx, temps,
                                  top_ks, top_ps, rep_pens, pres_pens,
                                  freq_pens, counts, mask):
        """Boundary sampling under the decoding policy: the prefill-
        finish counterpart of ``sample_from_logits``.  ``logits`` is a
        list of [vocab] rows (or an [n, vocab] batch); every other
        argument is per-row.  Unlike the legacy sampled path (one rng
        split per CALL), each row draws from ``fold_in(keys[r],
        tok_idx[r])`` — the same position-keyed stream the fused decode
        uses, so the boundary token is reproducible across batching,
        preemption-recompute and failover.  One compiled signature per
        row count (bounded by num_slots)."""
        if isinstance(logits, (list, tuple)):
            rows = jnp.stack([jnp.asarray(r) for r in logits])
        else:
            rows = jnp.asarray(logits)
        single = rows.ndim == 1
        if single:
            rows = rows[None]
        if getattr(self, "_policy_rows_fn", None) is None:
            def rows_fn(rows, keys, tok_idx, temps, top_ks, top_ps,
                        rep_pens, pres_pens, freq_pens, counts, mask):
                x = policy_pipeline.process_logits(
                    rows, counts, mask, temps, top_ks, top_ps, rep_pens,
                    pres_pens, freq_pens)
                return policy_pipeline.sample_processed(
                    x, keys, tok_idx, temps).astype(jnp.int32)
            self._policy_rows_fn = jax.jit(rows_fn)
        n = rows.shape[0]
        with dist.mesh_scope(self.mesh):
            toks = self._dispatch(
                "sample_policy", self._policy_rows_fn, rows,
                jnp.asarray(np.asarray(keys, np.uint32).reshape(n, 2)),
                jnp.asarray(np.asarray(tok_idx, np.int32)),
                jnp.asarray(np.asarray(temps, np.float32)),
                jnp.asarray(np.asarray(top_ks, np.int32)),
                jnp.asarray(np.asarray(top_ps, np.float32)),
                jnp.asarray(np.asarray(rep_pens, np.float32)),
                jnp.asarray(np.asarray(pres_pens, np.float32)),
                jnp.asarray(np.asarray(freq_pens, np.float32)),
                jnp.asarray(np.asarray(counts, np.int32)),
                jnp.asarray(np.asarray(mask, bool)))
        out = [int(t) for t in np.asarray(jax.device_get(toks))]
        return out[0] if single else out

    def serving_verify_compile_count(self):
        """Compiled signatures behind verify_multi (legacy greedy +
        policy twin summed) — bounded by the scheduler's spec-K bucket
        set per path, never by request churn, acceptance outcomes or
        sampling-param churn."""
        return (jit_cache_size(getattr(self, "_paged_verify_fn", None)) +
                jit_cache_size(getattr(self, "_paged_verify_policy_fn",
                                       None)))

    def sample_from_logits(self, logits, do_sample=False, temperature=1.0,
                           top_k=0, top_p=1.0):
        """Sample from logits (same `_sample_tokens` math as generate()).
        A single [vocab] row returns an int; a list of rows (or an
        [n, vocab] batch) samples every row in ONE device call and
        returns a list — the serving scheduler batches all slots
        finishing prefill in a step this way instead of paying one tiny
        dispatch per slot. Sampled mode draws one rng split per CALL
        (not per row), so batching changes the stream; greedy decoding
        is unaffected.

        Greedy contract: ``do_sample=False`` OR ``temperature=0`` is a
        deterministic fp32 argmax, ties breaking to the LOWEST token id
        — the exact comparison ``verify_multi`` replays on device, so
        speculative verification stays token-exact vs this function."""
        if isinstance(logits, (list, tuple)):
            rows = jnp.stack([jnp.asarray(r) for r in logits])
        else:
            rows = jnp.asarray(logits)
        single = rows.ndim == 1
        if single:
            rows = rows[None]
        self._rng, rng = jax.random.split(self._rng)
        toks = _sample_tokens(rows, rng, do_sample, temperature, top_k,
                              top_p)
        out = [int(t) for t in np.asarray(jax.device_get(toks))]
        return out[0] if single else out

    def serving_seq_prefill_compile_count(self):
        """Compiled signatures behind prefill_sequence_parallel —
        bounded by the scheduler's chunk bucket set (one per distinct
        chunk length), never by request churn: slot / n_valid /
        positions are traced data, the chunk length is the only shape
        in the signature."""
        return jit_cache_size(getattr(self, "_paged_prefill_sp_fn", None))

    def serving_decode_compile_count(self):
        """Number of compiled signatures behind decode_step (the
        no-per-step-recompilation guarantee: stays 1 across churn)."""
        return jit_cache_size(getattr(self, "_paged_decode_fn", None))

    def serving_decode_multi_compile_count(self):
        """Compiled signatures behind decode_multi (legacy greedy +
        policy twin summed) — bounded by the scheduler's horizon bucket
        set (one per distinct horizon per path), never by request churn
        or per-request sampling-param churn: policy knobs are traced
        arrays, so a greedy/sampled/penalized mix re-uses the bucket's
        one executable."""
        return (jit_cache_size(getattr(self, "_paged_decode_multi_fn",
                                       None)) +
                jit_cache_size(getattr(self, "_paged_decode_policy_fn",
                                       None)))

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 max_length=None, stream=False, **kwargs):
        """Autoregressive generation with device-resident KV cache.

        Default path runs the whole decode loop as a single fused dispatch
        (lax.scan) — the per-token host round-trip of a Python loop
        dominates latency on TPU. ``stream=True`` keeps the token-at-a-time
        loop (early eos exit, per-token latencies in model_times())."""
        assert self.params is not None, "set_params/init_params first"
        if kwargs:
            raise TypeError(
                f"generate() got unsupported arguments {sorted(kwargs)}; "
                "supported: max_new_tokens, do_sample, temperature, top_k, "
                "top_p, eos_token_id, max_length, stream")
        ids = np.asarray(input_ids)
        if ids.ndim == 1:
            ids = ids[None]
        b, prompt_len = ids.shape
        if max_length is not None:
            max_new_tokens = max(int(max_length) - prompt_len, 0)
        if max_new_tokens == 0:
            return ids
        max_len = prompt_len + max_new_tokens
        if max_len > self._config.max_out_tokens:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_out_tokens={self._config.max_out_tokens}; "
                "raise max_out_tokens in the inference config")

        if not self._supports_cache():
            return self._generate_nocache(ids, max_new_tokens, do_sample,
                                          temperature, top_k, top_p,
                                          eos_token_id)

        # bucket the cache length so calls with nearby lengths share one
        # compiled prefill/decode (the reference sizes its workspace to
        # max_out_tokens once, inference_context.h)
        bucket = 128
        cache_len = min(-(-max_len // bucket) * bucket,
                        self._config.max_out_tokens)
        cache_len = max(cache_len, max_len)
        cache = self._init_cache(b, cache_len)
        if self._prefill_fn is None:
            self._build_gen_fns()

        t0 = time.time()
        with dist.mesh_scope(self.mesh):
            logits, cache = self._prefill_fn(self.params, jnp.asarray(ids),
                                             cache)
        self._rng, rng = jax.random.split(self._rng)
        tok = _sample_tokens(logits, rng, do_sample, temperature, top_k, top_p)
        first = np.asarray(jax.device_get(tok))
        self._model_times.append(time.time() - t0)
        n_rest = max_new_tokens - 1

        if not stream and n_rest > 0:
            # bucket the step count too: scan a rounded-up length and slice,
            # so varying max_new_tokens shares one compiled loop (extra
            # steps only write cache slots past the returned tokens)
            n_bucket = min(-(-n_rest // 32) * 32, cache_len - prompt_len - 1)
            n_bucket = max(n_bucket, n_rest)
            t0 = time.time()
            self._rng, rng = jax.random.split(self._rng)
            finished = jnp.asarray(first == eos_token_id) \
                if eos_token_id is not None else jnp.zeros(b, bool)
            with dist.mesh_scope(self.mesh):
                toks, cache, _ = self._decode_loop_fn(
                    self.params, jnp.asarray(first), cache, finished, rng,
                    int(n_bucket), bool(do_sample), float(temperature),
                    int(top_k), float(top_p),
                    None if eos_token_id is None else int(eos_token_id),
                    0 if eos_token_id is None else int(eos_token_id))
            rest = np.asarray(jax.device_get(toks))[:, :n_rest]
            dt = time.time() - t0
            # aggregate dispatch: spread the loop time over the *emitted*
            # tokens so the recorded times sum to the measured wall time
            # even when the scan length was rounded up past n_rest
            self._model_times.extend([dt / n_rest] * n_rest)
            gen = np.concatenate([first[:, None], rest], axis=1)
            return np.concatenate([ids, gen], axis=1)

        out = [first]
        finished = np.zeros(b, bool)
        if eos_token_id is not None:
            finished |= first == eos_token_id
        tok = jnp.asarray(first)
        for _ in range(n_rest):
            if eos_token_id is not None and finished.all():
                break
            t0 = time.time()
            self._rng, rng = jax.random.split(self._rng)
            with dist.mesh_scope(self.mesh):
                tok, cache = self._decode_fn(self.params, tok, cache, rng,
                                             bool(do_sample),
                                             float(temperature),
                                             int(top_k), float(top_p))
            host_tok = np.asarray(jax.device_get(tok))
            self._model_times.append(time.time() - t0)
            if eos_token_id is not None:
                # rows that finished earlier emit eos fill, not garbage
                host_tok = np.where(finished, eos_token_id, host_tok)
                out.append(host_tok)
                finished |= host_tok == eos_token_id
            else:
                out.append(host_tok)
        gen = np.stack(out, axis=1)
        return np.concatenate([ids, gen], axis=1)

    def _generate_nocache(self, ids, max_new_tokens, do_sample, temperature,
                          top_k, top_p, eos_token_id):
        """Fallback for models without a KV-cache contract: full re-forward
        per token (correct, O(n^2); the reference non-injected path).

        The working buffer is padded to the final length once so the jitted
        forward compiles for a single shape instead of once per emitted
        token (causal models ignore positions past the read index)."""
        module = self.module

        if self._fwd is None:
            materialize = self._materialize
            self._fwd = jax.jit(
                lambda params, ids: module.apply(
                    {"params": materialize(params)}, ids))
        ids = np.asarray(ids)
        b, l0 = ids.shape
        total = l0 + max_new_tokens
        buf = np.zeros((b, total), ids.dtype)
        buf[:, :l0] = ids
        finished = np.zeros(b, bool)
        pos = l0
        for _ in range(max_new_tokens):
            with dist.mesh_scope(self.mesh):
                logits = self._fwd(self.params, jnp.asarray(buf))
            self._rng, rng = jax.random.split(self._rng)
            tok = _sample_tokens(logits[:, pos - 1], rng, do_sample,
                                 temperature, top_k, top_p)
            host_tok = np.asarray(jax.device_get(tok))
            if eos_token_id is not None:
                host_tok = np.where(finished, eos_token_id, host_tok)
            buf[:, pos] = host_tok
            pos += 1
            if eos_token_id is not None:
                finished |= host_tok == eos_token_id
                if finished.all():
                    break
        return buf[:, :pos]
