from deepspeed_tpu.accelerator.abstract_accelerator import \
    DeepSpeedAccelerator  # noqa: F401
from deepspeed_tpu.accelerator.real_accelerator import (  # noqa: F401
    get_accelerator, set_accelerator)
from deepspeed_tpu.accelerator.tpu_accelerator import (  # noqa: F401
    CPU_Accelerator, TPU_Accelerator)
