"""TPU (and CPU-fallback) accelerators over jax.

Reference: ``accelerator/cuda_accelerator.py`` implementing
``abstract_accelerator.py:10`` on torch.cuda; here the backing runtime is
jax/XLA. The same class serves the virtual-CPU test platform (the device
list just holds CPU devices), mirroring how the reference's accelerator
abstraction lets one code path span CUDA/CPU.
"""

import jax

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):
    _name = "tpu"
    _communication_backend_name = "xla"

    def __init__(self):
        self._current = 0
        self._seed = 0

    def _devices(self):
        return jax.local_devices()

    def device_count(self):
        return len(self._devices())

    def current_device(self):
        return self._current

    def set_device(self, device_index):
        assert 0 <= device_index < self.device_count()
        self._current = device_index

    def synchronize(self, device_index=None):
        # fence: a tiny transfer that cannot complete before queued work
        (jax.device_put(0, self._devices()[device_index or 0]) + 0
         ).block_until_ready()

    def manual_seed(self, seed):
        self._seed = int(seed)
        return jax.random.PRNGKey(self._seed)

    def memory_allocated(self, device_index=None):
        d = self._devices()[device_index or self._current]
        stats = d.memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    def total_memory(self, device_index=None):
        d = self._devices()[device_index or self._current]
        stats = d.memory_stats() or {}
        return stats.get("bytes_limit", stats.get("bytes_in_use", 0))

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        # fp16 runs on TPU but bf16 is native; both advertised (the fp16
        # loss-scaler path is tested on this platform)
        return True

    def device_kind(self):
        return getattr(self._devices()[0], "device_kind", self._name)


class CPU_Accelerator(TPU_Accelerator):
    """The virtual multi-device CPU platform used by the test mesh."""
    _name = "cpu"
    _communication_backend_name = "xla"
