"""Abstract accelerator contract.

Reference: ``accelerator/abstract_accelerator.py:10`` (DeepSpeedAccelerator)
— the conformance surface every accelerator must provide: naming, device
management, RNG, memory statistics, dtype support, communication backend
name (:177) and op-builder discovery (:225-235). The torch API surface
(streams/events) collapses on TPU: XLA owns scheduling, so stream/event
methods are explicit no-ops that keep client code portable.
"""

import abc


class DeepSpeedAccelerator(abc.ABC):
    _name = None
    _communication_backend_name = None

    # ----------------------------------------------------------- naming
    def device_name(self, device_index=None):
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def communication_backend_name(self):
        return self._communication_backend_name

    # ---------------------------------------------------------- devices
    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    def current_device_name(self):
        return self.device_name(self.current_device())

    @abc.abstractmethod
    def set_device(self, device_index):
        ...

    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    def is_available(self):
        return self.device_count() > 0

    # -------------------------------------------------------------- rng
    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    def initial_seed(self):
        return self._seed

    # ------------------------------------------------------------ memory
    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    def available_memory(self, device_index=None):
        return self.total_memory(device_index) - \
            self.memory_allocated(device_index)

    def memory_stats(self, device_index=None):
        return {"allocated_bytes": self.memory_allocated(device_index),
                "total_bytes": self.total_memory(device_index)}

    def empty_cache(self):
        """XLA manages HBM; nothing to flush."""

    # ------------------------------------------------------------ dtypes
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    def supported_dtypes(self):
        import jax.numpy as jnp
        out = [jnp.float32]
        if self.is_bf16_supported():
            out.append(jnp.bfloat16)
        if self.is_fp16_supported():
            out.append(jnp.float16)
        return out

    # ------------------------------------------- streams/events (no-ops)
    def stream(self, *a, **k):
        """XLA schedules asynchronously; explicit streams don't exist."""
        import contextlib
        return contextlib.nullcontext()

    def default_stream(self):
        return None

    def range_push(self, name):
        """Profiler annotation (reference NVTX range_push)."""
        import jax.profiler
        tc = jax.profiler.TraceAnnotation(name)
        tc.__enter__()
        self._open_ranges = getattr(self, "_open_ranges", [])
        self._open_ranges.append(tc)

    def range_pop(self):
        if getattr(self, "_open_ranges", None):
            self._open_ranges.pop().__exit__(None, None, None)

    # -------------------------------------------------------- op builders
    def op_builder_dir(self):
        return "deepspeed_tpu.ops.op_builder"

    def create_op_builder(self, class_name):
        import importlib
        mod = importlib.import_module(self.op_builder_dir())
        cls = getattr(mod, class_name, None)
        return cls() if cls is not None else None

    def get_op_builder(self, class_name):
        import importlib
        mod = importlib.import_module(self.op_builder_dir())
        return getattr(mod, class_name, None)
